"""C14–C16 — compile-discipline rules on the value-origin dataflow
(EDL105 recompile hazard / EDL106 captured-constant bloat / EDL107
PRNG-key discipline).

These are the STATIC twins of the PR 14 runtime health plane: the
recompile sentry convicts steady-state recompiles after the first
churned executable has already cost a compile; these rules convict the
shapes that produce them at lint time, on the CFG/dataflow engine.

* EDL105 — a call to a jit-wrapped executable (``jax.jit``/``pjit``/
  ``tracked_jit`` and the repo's ``_tjit``/``_pool_tjit`` adapters,
  bound by assignment in the same function or at module scope) passes
  an argument with an UNSTABLE value origin (see value_origin.py):
  loop-counter-derived ints when the call repeats inside that loop,
  ``len()``/``.shape`` of growing containers, wall-clock or env reads.
  Each such call re-keys the compile cache — the steady-state
  recompile loop the sentry counts. The engine/kv_pool bucketing
  idioms (``*_bucket`` helpers, ``-(-p // 64) * 64`` pads, power-of-
  two tiles) are STABILIZERS, not hazards, and a wrapper (re)built in
  the same loop as the call is a deliberate per-shape executable, not
  cache churn.
* EDL106 — a traced function (any jit context, decorator or wrap
  idiom) READS a free variable that the enclosing scope bound to a
  numpy/jnp array constructor (``np.zeros``/``jnp.asarray``/
  ``device_put``/...). The capture is baked into the trace as a
  CONSTANT: every retrace re-hashes and re-embeds the full array
  (slow compiles, bloated executables), and an update to the name is
  silently invisible to the compiled code. Arrays threaded as proper
  arguments are clean — that is the fix.
* EDL107 — PRNG-key discipline, two shapes: (a) one
  ``jax.random.PRNGKey``-tainted name consumed by two or more
  ``jax.random.*`` sampler sinks along one CFG path (loops included:
  a single in-loop sink re-consumes the same key every iteration)
  without an intervening ``split``/``fold_in`` or rebind — identical
  "randomness" at every sink; (b) a closure defined inside a loop
  capturing a key created OUTSIDE the loop — every iteration's
  closure shares one key. The sanctioned idioms (``fold_in(rng,
  position)`` per step, ``split`` then consume each child once) are
  untouched.

All three follow the engine's precision-first contract: attribute
state and cross-function flows contribute nothing without
same-function evidence; unresolvable receivers are silent.
"""

import ast

from elasticdl_tpu.analysis.cfg import walk_shallow
from elasticdl_tpu.analysis.core import Finding, Rule, register
from elasticdl_tpu.analysis.value_origin import (
    ORIGIN_LEN,
    ORIGIN_LEN_LOCAL,
    ORIGIN_LOOP,
    OriginAnalysis,
    call_tail,
    collect_jit_wrappers,
    dotted_text,
    enclosing_loops,
    loop_bodies,
)

#: human-facing names per origin tag for EDL105 messages
_TAG_TEXT = {
    ORIGIN_LOOP: "a Python loop counter",
    ORIGIN_LEN: "len()/.shape of a growing container",
    ORIGIN_LEN_LOCAL: "len()/.shape of a growing container",
    "clock": "a wall-clock read",
    "config": "an environment/config read",
}


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_name(tree, fndef):
    """Class.method for methods, bare name otherwise."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            if fndef in node.body:
                return "%s.%s" % (node.name, fndef.name)
    return fndef.name


# ------------------------------------------------- EDL105 recompile hazard


@register
class RecompileHazardRule(Rule):
    """EDL105 — see module docstring."""

    id = "EDL105"
    name = "recompile-hazard"

    def check_module(self, tree, lines, path):
        findings = []
        module_wrappers = collect_jit_wrappers(tree.body)
        class_wrappers = {}  # id(method fndef) -> {self.X: binding}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [m for m in node.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            shared = {}
            for m in methods:
                for spelling, binding in collect_jit_wrappers(
                    m.body
                ).items():
                    if spelling.startswith("self."):
                        shared[spelling] = binding
            for m in methods:
                class_wrappers[id(m)] = shared
        for fndef in _functions(tree):
            wrappers = dict(module_wrappers)
            wrappers.update(class_wrappers.get(id(fndef), {}))
            wrappers.update(collect_jit_wrappers(fndef.body))
            if not wrappers:
                continue
            findings.extend(
                self._check_function(fndef, wrappers, tree, path)
            )
        return findings

    def _check_function(self, fndef, wrappers, tree, path):
        analysis = OriginAnalysis(fndef)
        scope = _scope_name(tree, fndef)
        for node_call in self._wrapper_calls(analysis.cfg, wrappers):
            node, call, spelling, binding = node_call
            call_loops = enclosing_loops(analysis.loops, call)
            if binding is not None and any(
                id(binding) in inner
                for lp, inner in analysis.loops
                if id(call) in inner
            ):
                # wrapper (re)built in the same loop as the call: a
                # fresh executable per iteration is deliberate
                # per-shape compilation, not cache churn
                continue
            for arg in list(call.args) + [
                kw.value for kw in call.keywords
            ]:
                tags = analysis.origins_at(node, arg)
                tags = self._gate(tags, call_loops)
                for tag in sorted(tags):
                    report = (ORIGIN_LEN if tag == ORIGIN_LEN_LOCAL
                              else tag)
                    yield Finding(
                        "EDL105", path, call.lineno, scope,
                        "%s(%s)" % (spelling, report),
                        "argument to jit-wrapped %r derives from %s — "
                        "its abstract signature varies across "
                        "executions, so this call re-keys the compile "
                        "cache every time (the steady-state recompile "
                        "loop the runtime sentry counts); bucket/pad "
                        "the value or hoist it out of the signature"
                        % (spelling, _TAG_TEXT[tag]),
                    )
                    break  # one finding per argument

    @staticmethod
    def _gate(tags, call_loops):
        """loop / local-len instability only matters when the call
        itself repeats (inside a loop); clock/config/attr-len convict
        anywhere."""
        out = set(tags)
        if not call_loops:
            out.discard(ORIGIN_LOOP)
            out.discard(ORIGIN_LEN_LOCAL)
        return out

    @staticmethod
    def _wrapper_calls(cfg, wrappers):
        """Yield (node, call, spelling, binding stmt) for calls of
        known wrapper spellings, walking the SAME CFG the origin
        states are keyed by."""
        seen = set()
        for node in cfg.nodes:
            for root in node.scan_roots():
                for n in walk_shallow(root):
                    if not isinstance(n, ast.Call):
                        continue
                    spelling = dotted_text(n.func)
                    if spelling not in wrappers:
                        continue
                    key = (id(n), node.idx)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield node, n, spelling, wrappers[spelling]


# --------------------------------------------- EDL106 captured constants

#: array-constructor tails whose results are materialized ndarrays /
#: device buffers when rooted at a numpy/jnp/jax spelling
_ARRAY_CTORS = {
    "zeros", "ones", "full", "empty", "arange", "linspace", "eye",
    "asarray", "array", "rand", "randn", "device_put", "load",
    "loadtxt",
}
_ARRAY_ROOTS = {"np", "numpy", "onp", "jnp", "jax"}


#: shape/dtype methods that preserve array-ness through a chain
#: (``np.arange(n).reshape(a, b)`` is still a materialized ndarray)
_ARRAY_METHODS = {"reshape", "astype", "copy", "transpose", "ravel"}


def _is_array_ctor(value):
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr in _ARRAY_METHODS:
        return _is_array_ctor(fn.value)
    if fn.attr not in _ARRAY_CTORS:
        return False
    root = fn.value
    while isinstance(root, ast.Attribute):
        root = root.value
    return isinstance(root, ast.Name) and root.id in _ARRAY_ROOTS


def _bound_names(fndef):
    """Names bound WITHIN fndef (params, assignments, loop targets,
    withitems, comprehension targets, nested def/class names) — reads
    of anything else are free."""
    bound = set()
    a = fndef.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        bound.add(arg.arg)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for n in ast.walk(fndef):
        if isinstance(n, ast.Name) and isinstance(
            n.ctx, (ast.Store, ast.Del)
        ):
            bound.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            if n is not fndef:
                bound.add(n.name)
        elif isinstance(n, ast.comprehension):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
    return bound


def _free_reads(fndef):
    """{name: first-read line} of free Name loads in fndef's body
    (nested defs included — the whole body is traced together)."""
    bound = _bound_names(fndef)
    reads = {}
    for n in ast.walk(fndef):
        if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                and n.id not in bound and n.id not in reads):
            reads[n.id] = n.lineno
    return reads


@register
class CapturedConstantRule(Rule):
    """EDL106 — see module docstring."""

    id = "EDL106"
    name = "captured-constant-bloat"

    def check_module(self, tree, lines, path):
        from elasticdl_tpu.analysis.jit_rules import (
            _collect_jit_contexts,
        )

        contexts = _collect_jit_contexts(tree)
        if not contexts:
            return []
        findings = []
        self._scan_scope(tree, tree.body, {}, contexts, tree, path,
                         findings)
        return findings

    def _scan_scope(self, owner, body, inherited, contexts, tree,
                    path, findings):
        """One lexical scope: extend the visible array bindings, judge
        jit contexts defined here, recurse."""
        bindings = dict(inherited)
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Assign) and _is_array_ctor(
                node.value
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        bindings[tgt.id] = node.lineno
            stack.extend(ast.iter_child_nodes(node))
        defs = []
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                defs.append(node)
                continue
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        for sub in sorted(defs, key=lambda d: d.lineno):
            if isinstance(sub, ast.ClassDef):
                self._scan_scope(sub, sub.body, bindings, contexts,
                                 tree, path, findings)
                continue
            if sub in contexts:
                self._judge(sub, bindings, tree, path, findings)
            self._scan_scope(sub, sub.body, bindings, contexts, tree,
                             path, findings)

    @staticmethod
    def _judge(fndef, bindings, tree, path, findings):
        for name, line in sorted(_free_reads(fndef).items()):
            bound_line = bindings.get(name)
            if bound_line is None:
                continue
            findings.append(Finding(
                "EDL106", path, line, _scope_name(tree, fndef), name,
                "traced function %r captures %r — an ndarray built at "
                "line %d — by closure: every retrace re-hashes and "
                "re-bakes the full array into the executable, and "
                "rebinding the name never reaches compiled code; "
                "thread it as an argument instead"
                % (fndef.name, name, bound_line),
            ))


# --------------------------------------------- EDL107 PRNG-key discipline

#: jax.random consuming sinks (first positional arg is the key)
_SAMPLERS = {
    "uniform", "normal", "categorical", "bernoulli", "gumbel",
    "choice", "randint", "permutation", "truncated_normal",
    "exponential", "beta", "gamma", "poisson", "dirichlet", "laplace",
    "shuffle", "orthogonal", "bits",
}
_KEY_MAKERS = {"PRNGKey", "key", "fold_in", "split"}
_KEY_SETTLERS = {"fold_in", "split"}


def _random_receiver(fn):
    """True for ``jax.random.X`` / ``random.X`` attribute chains."""
    if not isinstance(fn, ast.Attribute):
        return False
    text = dotted_text(fn.value)
    return text in ("jax.random", "random")


def _sink_key(call, key_names):
    """The consumed key name when `call` is a sampler sink over a
    known key, else None."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _SAMPLERS
            and _random_receiver(fn)):
        return None
    if call.args and isinstance(call.args[0], ast.Name) and \
            call.args[0].id in key_names:
        return call.args[0].id
    return None


def _settles_key(node, name):
    """Does this CFG node rebind `name` or route it through
    split/fold_in (minting fresh keys)?"""
    for root in node.scan_roots():
        for n in walk_shallow(root):
            if isinstance(n, ast.Call):
                tail = call_tail(n.func)
                if tail in _KEY_SETTLERS and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in n.args
                ):
                    return True
            elif isinstance(n, ast.Name) and isinstance(
                n.ctx, ast.Store
            ) and n.id == name:
                return True
    return False


@register
class PrngKeyRule(Rule):
    """EDL107 — see module docstring."""

    id = "EDL107"
    name = "prng-key-discipline"

    def check_module(self, tree, lines, path):
        findings = []
        for fndef in _functions(tree):
            findings.extend(self._check_function(fndef, tree, path))
        return findings

    def _check_function(self, fndef, tree, path):
        from elasticdl_tpu.analysis.cfg import build_cfg

        key_stmts = {}  # name -> creating Assign stmt
        for n in walk_shallow(fndef):
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Call
            ) and call_tail(n.value.func) in _KEY_MAKERS:
                for tgt in n.targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name):
                            key_stmts[t.id] = n
        if not key_stmts:
            return
        key_names = frozenset(key_stmts)
        scope = _scope_name(tree, fndef)
        cfg = build_cfg(fndef)
        loops = loop_bodies(fndef)

        reported = set()
        for node in cfg.nodes:
            sinks = self._node_sinks(node, key_names)
            for name, calls in sinks.items():
                if len(calls) >= 2 and (name, calls[1].lineno) not in \
                        reported:
                    reported.add((name, calls[1].lineno))
                    yield self._reuse_finding(
                        path, calls[1].lineno, scope, name,
                        calls[0].lineno,
                    )
                elif calls:
                    hit = self._reaches_sink_again(
                        cfg, node, name, key_names
                    )
                    if hit is not None and (name, hit) not in reported:
                        reported.add((name, hit))
                        yield self._reuse_finding(
                            path, hit, scope, name, calls[0].lineno,
                        )

        # closures minted per loop iteration over a pre-loop key
        for n in walk_shallow(fndef):
            if not isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            n_loops = enclosing_loops(loops, n)
            if not n_loops:
                continue
            body = n.body if isinstance(n.body, list) else [n.body]
            for stmt in body:
                for r in ast.walk(stmt):
                    if not (isinstance(r, ast.Name)
                            and isinstance(r.ctx, ast.Load)
                            and r.id in key_names):
                        continue
                    made = key_stmts[r.id]
                    if any(id(made) in inner for _lp, inner in loops
                           if id(n) in inner):
                        continue  # key minted inside the same loop
                    fp = ("closure", r.id, n.lineno)
                    if fp in reported:
                        continue
                    reported.add(fp)
                    yield Finding(
                        "EDL107", path, n.lineno, scope, r.id,
                        "closure defined inside a loop captures PRNG "
                        "key %r created before the loop — every "
                        "iteration's closure shares ONE key, so all "
                        "of them sample identical values; fold_in the "
                        "loop counter (or split per iteration) first"
                        % r.id,
                    )

    @staticmethod
    def _reuse_finding(path, line, scope, name, first_line):
        return Finding(
            "EDL107", path, line, scope, name,
            "PRNG key %r is consumed by a second jax.random sink on "
            "the same CFG path (first sink at line %d) with no "
            "split/fold_in in between — both sinks draw IDENTICAL "
            "randomness; split the key or fold_in a counter"
            % (name, first_line),
        )

    @staticmethod
    def _node_sinks(node, key_names):
        out = {}
        for root in node.scan_roots():
            for n in walk_shallow(root):
                name = _sink_key(n, key_names)
                if name is not None:
                    out.setdefault(name, []).append(n)
        return out

    def _reaches_sink_again(self, cfg, start, name, key_names):
        """Line of another sink consuming `name` CFG-reachable from
        `start` (loops included — the start node itself counts when
        re-entered) without a settle in between, else None."""
        seen = set()
        stack = list(start.succ)
        while stack:
            node = stack.pop()
            if node.idx in seen:
                continue
            seen.add(node.idx)
            sinks = self._node_sinks(node, key_names)
            if name in sinks:
                return sinks[name][0].lineno
            if _settles_key(node, name):
                continue
            stack.extend(node.out)
        return None
