"""Declared journal-protocol state machines for WAL-backed controllers.

Every controller that write-aheads events through a `JobStateStore`
declares its protocol as a module-level pure-literal call:

    PROTOCOL = JournalProtocol(
        name="rollout",
        kind_key="ev",            # payload key carrying the event kind
        emit="_journal",          # the writer method (call surface)
        replay="_apply_event",    # the paired replay function
        states=(IDLE, STAGING, ...),
        initial=IDLE,
        events={
            "begin": {"from": TERMINAL, "to": STAGING,
                      "requires": ("target", "old", "plan")},
            "phase": {"from": "*", "to_key": "to"},
            "swap_start": {"from": (CANARY, WAVE, ROLLING_BACK),
                           "informational": True},
            ...
        },
        transitions={STAGING: (CANARY, ABORTED), ...},
        recoverable={STAGING: "re-stage the checkpoint", ...},
    )

The declaration is the SINGLE SOURCE OF TRUTH, consumed three ways:

* at runtime by the controller itself and by the spec-derived
  crash-point replay batteries (`analysis/protocol_testgen.py`);
* at lint time by `journal_rules` (EDL701-EDL704), which re-reads the
  SAME declaration from the module's AST (`machine_from_ast`) so the
  checker needs no imports — it works on fixture files and in the
  minimal CI lint environment alike;
* by reviewers, as the one place a controller's crash contract is
  written down.

Event entry vocabulary (all optional except membership itself):

* ``"from"`` — tuple of states the event may be emitted in, or ``"*"``
  (any state; the default). Idempotent-replay protocols declare
  liberal from-sets on purpose.
* ``"to"`` — the machine state after the event (omit/None = no state
  change).
* ``"to_key"`` — for generic transition events ("phase"): the payload
  key that CARRIES the target state; legality of the hop is judged
  against ``transitions`` (declared adjacency between states).
* ``"requires"`` — payload keys every emit site must write (the
  replay side reads them unconditionally; EDL702's contract).
* ``"optional"`` — payload keys an emit MAY write (the replay side
  must read them tolerantly, via ``.get``).
* ``"informational"`` — forensic-only: no replay branch required and
  no state effect (the router cell's ``lease`` beacon, rollout's
  ``swap_start``). EDL701 exempts these from write/replay closure.
* ``"entity_key"`` — for per-entity lifecycles (a seat, a replica
  address, a task id): the payload key naming the entity this event
  transitions. Events without it act on the GLOBAL machine state.

``recoverable`` maps each state in which a crash may legally strand
the journal to its declared resume action (a one-line description of
how recovery proceeds from there). EDL704 convicts an emit that can
be followed by another emit while the machine sits in a state absent
from this map. ``terminal`` states need no resume action by
construction but may still be listed.

Pure stdlib on purpose: imported by serving/master controllers AND by
the analyzer, in environments without jax.
"""

ANY = "*"


class ProtocolError(ValueError):
    """A malformed declaration or an illegal event sequence."""


class EventSpec(object):
    def __init__(self, kind, frm=ANY, to=None, to_key=None,
                 requires=(), optional=(), informational=False,
                 entity_key=None):
        self.kind = kind
        self.frm = ANY if frm == ANY else tuple(frm)
        self.to = to
        self.to_key = to_key
        self.requires = tuple(requires)
        self.optional = tuple(optional)
        self.informational = bool(informational)
        self.entity_key = entity_key
        if to is not None and to_key is not None:
            raise ProtocolError(
                "event %r declares both 'to' and 'to_key'" % kind
            )
        if informational and (to is not None or to_key is not None):
            raise ProtocolError(
                "informational event %r cannot change state" % kind
            )


_EVENT_FIELDS = frozenset((
    "from", "to", "to_key", "requires", "optional", "informational",
    "entity_key",
))


class JournalProtocol(object):
    """A declared WAL protocol: states, event alphabet, legal
    transitions, recoverable states, and the emit/replay pairing."""

    def __init__(self, name, states, initial, events,
                 recoverable=None, transitions=None, kind_key="ev",
                 emit="_journal", replay="_apply_event", terminal=()):
        self.name = name
        self.states = tuple(states)
        self.initial = initial
        self.kind_key = kind_key
        self.emit = emit
        self.replay = replay
        self.terminal = tuple(terminal)
        self.transitions = {
            s: tuple(ts) for s, ts in (transitions or {}).items()
        }
        self.recoverable = dict(recoverable or {})
        self.events = {}
        for kind, entry in events.items():
            extra = set(entry) - _EVENT_FIELDS
            if extra:
                raise ProtocolError(
                    "event %r has unknown field(s) %s"
                    % (kind, ", ".join(sorted(extra)))
                )
            self.events[kind] = EventSpec(
                kind,
                frm=entry.get("from", ANY),
                to=entry.get("to"),
                to_key=entry.get("to_key"),
                requires=entry.get("requires", ()),
                optional=entry.get("optional", ()),
                informational=entry.get("informational", False),
                entity_key=entry.get("entity_key"),
            )
        self._validate()

    def _validate(self):
        known = set(self.states)
        if self.initial not in known:
            raise ProtocolError(
                "initial state %r not in states" % (self.initial,)
            )
        for s in self.terminal:
            if s not in known:
                raise ProtocolError(
                    "terminal state %r not in states" % (s,)
                )
        for s, targets in self.transitions.items():
            for t in (s,) + tuple(targets):
                if t not in known:
                    raise ProtocolError(
                        "transition state %r not in states" % (t,)
                    )
        for s in self.recoverable:
            if s not in known:
                raise ProtocolError(
                    "recoverable state %r not in states" % (s,)
                )
        for spec in self.events.values():
            if spec.frm != ANY:
                for s in spec.frm:
                    if s not in known:
                        raise ProtocolError(
                            "event %r 'from' state %r not in states"
                            % (spec.kind, s)
                        )
            if spec.to is not None and spec.to not in known:
                raise ProtocolError(
                    "event %r 'to' state %r not in states"
                    % (spec.kind, spec.to)
                )

    # ------------------------------------------------------ machine ops

    @property
    def alphabet(self):
        return frozenset(self.events)

    def replayed_kinds(self):
        """Kinds that MUST have a replay branch (non-informational)."""
        return frozenset(
            k for k, s in self.events.items() if not s.informational
        )

    def legal(self, state, kind, payload=None):
        """May `kind` be emitted while the (global or entity) machine
        sits in `state`? `state` may be None (unknown) — then any
        emit is legal (precision over recall, like every engine
        layer)."""
        spec = self.events.get(kind)
        if spec is None:
            return False
        if state is None:
            return True
        if spec.frm != ANY and state not in spec.frm:
            return False
        if spec.to_key is not None and payload is not None:
            target = payload.get(spec.to_key)
            if target is not None:
                allowed = self.transitions.get(state)
                if allowed is not None and target not in allowed:
                    return False
        return True

    def apply(self, state, kind, payload=None):
        """The machine state after emitting `kind` from `state`.
        Returns None (unknown) when the target cannot be determined
        statically; raises ProtocolError on an undeclared kind."""
        spec = self.events.get(kind)
        if spec is None:
            raise ProtocolError(
                "undeclared event kind %r in protocol %r"
                % (kind, self.name)
            )
        if spec.informational:
            return state
        if spec.to is not None:
            return spec.to
        if spec.to_key is not None:
            target = (payload or {}).get(spec.to_key)
            if target in self.states:
                return target
            return None
        return state

    def simulate(self, events, strict=True):
        """Fold a journal (list of event dicts) through the machine.

        Returns ``(global_state, entity_states)``: the global machine
        state plus a dict entity-id -> state for per-entity events
        (entities start at `initial`... for entity protocols the
        declared `initial` doubles as the per-entity start state).
        With strict=True an illegal emission raises ProtocolError —
        the dynamic twin of EDL703."""
        state = self.initial
        entities = {}
        for i, ev in enumerate(events):
            kind = ev.get(self.kind_key)
            spec = self.events.get(kind)
            if spec is None:
                if strict:
                    raise ProtocolError(
                        "event %d: undeclared kind %r" % (i, kind)
                    )
                continue
            if spec.entity_key is not None:
                eid = ev.get(spec.entity_key)
                cur = entities.get(eid, self.initial)
                if strict and not self.legal(cur, kind, ev):
                    raise ProtocolError(
                        "event %d: %r illegal for entity %r in "
                        "state %r" % (i, kind, eid, cur)
                    )
                nxt = self.apply(cur, kind, ev)
                if not spec.informational:
                    entities[eid] = nxt if nxt is not None else cur
            else:
                if strict and not self.legal(state, kind, ev):
                    raise ProtocolError(
                        "event %d: %r illegal in state %r"
                        % (i, kind, state)
                    )
                nxt = self.apply(state, kind, ev)
                if nxt is not None:
                    state = nxt
        return state, entities

    def assert_recoverable_prefixes(self, events):
        """Every prefix of `events` must leave the machine in a state
        with a declared resume action — the dynamic twin of EDL704.
        Terminal states count as trivially recoverable."""
        state = self.initial
        ok = set(self.recoverable) | set(self.terminal)
        ok.add(self.initial)
        for i, ev in enumerate(events):
            kind = ev.get(self.kind_key)
            spec = self.events.get(kind)
            if spec is None or spec.entity_key is not None:
                continue
            nxt = self.apply(state, kind, ev)
            if nxt is not None:
                state = nxt
            if state not in ok:
                raise ProtocolError(
                    "after event %d (%r) the machine is in %r, which "
                    "declares no resume action" % (i, kind, state)
                )


# ----------------------------------------------- AST-side extraction
#
# journal_rules re-reads the SAME declaration from the module AST so
# the checker needs no imports: fixture files are parsed, never
# imported, and the CI lint job runs without the serving deps. The
# declaration must therefore be a PURE LITERAL call — constants,
# module-level string/tuple constants, tuples/lists/dicts/bools.

import ast  # noqa: E402  (grouped with the extraction half on purpose)


def module_constant_env(tree):
    """Resolve module-level literal assignments (``CANARY = "canary"``,
    ``TERMINAL = (IDLE, COMMITTED)``) into a name -> value map, in
    statement order so later constants may reference earlier ones."""
    env = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            try:
                env[node.targets[0].id] = _literal(node.value, env)
            except ProtocolError:
                pass
    return env


def _literal(node, env):
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise ProtocolError("unresolvable name %r" % node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_literal(e, env) for e in node.elts)
    if isinstance(node, ast.Dict):
        out = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                raise ProtocolError("dict ** expansion not literal")
            out[_literal(k, env)] = _literal(v, env)
        return out
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)):
        left = _literal(node.left, env)
        right = _literal(node.right, env)
        if isinstance(left, tuple) and isinstance(right, tuple):
            return left + right
        raise ProtocolError("non-tuple concatenation")
    raise ProtocolError(
        "non-literal %s in PROTOCOL declaration"
        % type(node).__name__
    )


def find_protocol_decl(tree):
    """The module-level ``PROTOCOL = JournalProtocol(...)`` assignment
    node, or None."""
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "PROTOCOL"
                and isinstance(node.value, ast.Call)):
            func = node.value.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name == "JournalProtocol":
                return node
    return None


def machine_from_ast(call_node, env):
    """Rebuild the JournalProtocol from its declaration Call node.
    Raises ProtocolError when the declaration is not a pure literal
    or fails the machine's own validation."""
    if call_node.args:
        raise ProtocolError(
            "PROTOCOL must use keyword arguments only"
        )
    kwargs = {}
    for kw in call_node.keywords:
        if kw.arg is None:
            raise ProtocolError("** expansion is not literal")
        kwargs[kw.arg] = _literal(kw.value, env)
    try:
        return JournalProtocol(**kwargs)
    except TypeError as e:
        raise ProtocolError(str(e))
