"""C17 — sharding-discipline checker (EDL601), BORN GATED for the
GSPMD serving PR.

The ROADMAP's "multi-chip GSPMD serving and sharded weight updates"
item will multiply the sharding-annotation surface (per-layer
NamedShardings, with_sharding_constraint pins inside the decode step,
donated sharded optimizer state a la ZeRO). Sharding-annotation drift
is the dominant silent-wrongness risk of that work: a constraint
outside jit silently does nothing, a typo'd mesh-axis name silently
replicates (or raises only on hardware the CI doesn't have), and a
donated-but-unsharded output silently materializes a gathered copy of
the state the donation existed to avoid. This family exists BEFORE
that PR lands — the same precedent as PR 7 gating the aggregation
tier — seeded on today's surface (`parallel/mesh.py`,
`parallel/sharding.py`, the MoE a2a machinery, the trainer's
donate+shardings jit calls), so the GSPMD PR is born with its
discipline machine-checked.

Three shapes, all lexical/precision-first:

* **constraint-outside-jit** — ``with_sharding_constraint(x, s)``
  called in a function that is neither a jit context (decorator or
  wrap idiom, per the EDL101 context collection) nor lexically nested
  inside one. Outside a trace the call is a silent no-op (or an
  error, backend-depending): the pin the author wrote does not exist.
* **unknown-mesh-axis** — a string-literal axis name inside a
  ``PartitionSpec``/``P(...)`` (incl. nested tuples) or the axis-name
  argument of ``shard_map``/``all_to_all``-style collectives that is
  not declared by the enclosing mesh: checked against a literal
  ``Mesh(devs, ("a", "b"))`` axis tuple in the same function or
  module when one exists, else against the repo's canonical axis set
  (``common.constants.MeshAxis.ALL`` — imported at rule runtime, the
  single source of truth). A typo'd axis name places NOTHING and
  raises only at mesh-build time on the right topology.
* **donated-sharding-drop** — a ``jax.jit``/``pjit`` call that
  declares ``donate_argnums``/``donate_argnames`` AND
  ``in_shardings`` but NO ``out_shardings``: the donated buffers'
  output placement is left to inference, and a silently replicated
  output un-does the sharded-update memory win (and round-trips the
  full state through every device). Declare the output sharding —
  the trainer's train-step/apply-rows calls are the sanctioned shape.

Non-literal axis expressions (``MeshAxis.EP``, computed tuples)
contribute nothing — the rule never guesses.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, Rule, register
from elasticdl_tpu.analysis.value_origin import call_tail, dotted_text

#: PartitionSpec-ish constructors whose string args are axis names
_PSPEC_TAILS = {"P", "PartitionSpec"}

#: collective call keywords/positions whose string args name axes
_AXIS_KEYWORDS = {"axis_name", "axis_names"}


def canonical_axes():
    """The repo's canonical mesh-axis union (MeshAxis.ALL in
    common/constants.py — stdlib-only import, single source of
    truth)."""
    from elasticdl_tpu.common.constants import MeshAxis

    return frozenset(MeshAxis.ALL)


def _literal_strs(node):
    """Every string constant inside `node` (tuples/lists walked)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append((n.value, n.lineno))
    return out


def _mesh_axes_of_call(call):
    """Literal axis tuple of a ``Mesh(devs, ("dp", ...))`` /
    ``Mesh(devs, axis_names=(...))`` call, else None."""
    if call_tail(call.func) != "Mesh":
        return None
    cand = None
    if len(call.args) >= 2:
        cand = call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_names":
            cand = kw.value
    if cand is None:
        return None
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return frozenset([cand.value])
    if isinstance(cand, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, str)
        for e in cand.elts
    ):
        return frozenset(e.value for e in cand.elts)
    return None  # computed axis names: contribute nothing


def _collect_literal_meshes(body):
    """Union of literal mesh axis declarations in one scope (nested
    function bodies excluded)."""
    axes = set()
    found = False
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            mesh_axes = _mesh_axes_of_call(node)
            if mesh_axes is not None:
                axes.update(mesh_axes)
                found = True
        stack.extend(ast.iter_child_nodes(node))
    return (frozenset(axes) if found else None)


@register
class ShardingDisciplineRule(Rule):
    """EDL601 — see module docstring."""

    id = "EDL601"
    name = "sharding-discipline"

    def check_module(self, tree, lines, path):
        from elasticdl_tpu.analysis.jit_rules import (
            _collect_jit_contexts,
        )

        contexts = _collect_jit_contexts(tree)
        traced = self._traced_functions(tree, contexts)
        module_axes = _collect_literal_meshes(tree.body)
        findings = []
        findings.extend(
            self._check_constraints(tree, traced, path)
        )
        findings.extend(
            self._check_axis_names(tree, module_axes, path)
        )
        findings.extend(self._check_donate_shardings(tree, path))
        return findings

    # ---------------------------------------------------- jit nesting

    @staticmethod
    def _traced_functions(tree, contexts):
        """Jit contexts plus every function lexically nested inside
        one (traced with it)."""
        traced = set(id(f) for f in contexts)
        for ctx in contexts:
            for n in ast.walk(ctx):
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    traced.add(id(n))
        return traced

    def _enclosing_chain(self, tree):
        """{id(fndef): [enclosing fndefs outermost-first]} so a
        constraint inside a helper nested in a jit context resolves."""
        chains = {}

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    chains[id(child)] = list(stack) + [child]
                    walk(child, stack + [child])
                else:
                    walk(child, stack)

        walk(tree, [])
        return chains

    # ------------------------------------------- constraint-outside-jit

    def _check_constraints(self, tree, traced, path):
        # module scope is never traced
        stack = list(tree.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call) and call_tail(
                n.func
            ) == "with_sharding_constraint":
                yield Finding(
                    "EDL601", path, n.lineno, "<module>",
                    "with_sharding_constraint",
                    "with_sharding_constraint outside a jit context "
                    "is a silent no-op — the pin you wrote does not "
                    "exist in any executable; move it inside the "
                    "traced function (or delete it)",
                )
            stack.extend(ast.iter_child_nodes(n))

        chains = self._enclosing_chain(tree)
        for fid, chain in sorted(chains.items(),
                                 key=lambda kv: kv[1][-1].lineno):
            fndef = chain[-1]
            if any(id(f) in traced for f in chain):
                continue
            for n in self._own_nodes(fndef):
                if isinstance(n, ast.Call) and call_tail(
                    n.func
                ) == "with_sharding_constraint":
                    yield Finding(
                        "EDL601", path, n.lineno, fndef.name,
                        "with_sharding_constraint",
                        "with_sharding_constraint outside a jit "
                        "context is a silent no-op — the pin you "
                        "wrote does not exist in any executable; "
                        "move it inside the traced function (or "
                        "delete it)",
                    )

    @staticmethod
    def _own_nodes(fndef):
        """Nodes of fndef excluding nested function bodies (those are
        judged under their own chain)."""
        stack = list(fndef.body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    # ----------------------------------------------- unknown-mesh-axis

    def _check_axis_names(self, tree, module_axes, path):
        canon = canonical_axes()

        def judge(call, allowed, scope, source):
            names = []
            if call_tail(call.func) in _PSPEC_TAILS:
                for arg in call.args:
                    names.extend(_literal_strs(arg))
            for kw in call.keywords:
                if kw.arg in _AXIS_KEYWORDS:
                    names.extend(_literal_strs(kw.value))
            for name, lineno in names:
                if name not in allowed:
                    yield Finding(
                        "EDL601", path, lineno, scope,
                        "axis:%s" % name,
                        "mesh-axis name %r is not declared by %s — a "
                        "typo'd axis places nothing (silent "
                        "replication) and only raises on the real "
                        "topology; declared axes: %s"
                        % (name, source, ", ".join(sorted(allowed))),
                    )

        findings = []

        def visit(node, scope, allowed, source):
            if isinstance(node, ast.ClassDef):
                for c in node.body:
                    visit(c, node.name, allowed, source)
                return
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                fn_axes = _collect_literal_meshes(node.body)
                if fn_axes is not None:
                    allowed = fn_axes
                    source = "the enclosing Mesh declaration"
                inner = (node.name if scope == "<module>"
                         else "%s.%s" % (scope, node.name))
                for c in node.body:
                    visit(c, inner, allowed, source)
                return
            if isinstance(node, ast.Lambda):
                pass  # fall through: lambdas share the scope
            if isinstance(node, ast.Call):
                findings.extend(judge(node, allowed, scope, source))
            for c in ast.iter_child_nodes(node):
                visit(c, scope, allowed, source)

        allowed = module_axes if module_axes is not None else canon
        source = ("the enclosing Mesh declaration"
                  if module_axes is not None
                  else "the canonical MeshAxis.ALL set")
        for node in tree.body:
            visit(node, "<module>", allowed, source)
        return findings

    # ------------------------------------------- donated-sharding-drop

    @staticmethod
    def _check_donate_shardings(tree, path):
        for n in ast.walk(tree):
            if not isinstance(n, ast.Call):
                continue
            if call_tail(n.func) not in ("jit", "pjit"):
                continue
            kws = {kw.arg for kw in n.keywords if kw.arg}
            if not kws & {"donate_argnums", "donate_argnames"}:
                continue
            if "in_shardings" in kws and "out_shardings" not in kws:
                target = dotted_text(n.args[0]) if n.args else "<fn>"
                yield Finding(
                    "EDL601", path, n.lineno, "<module>",
                    "donate:%s" % target,
                    "jit call donates input buffers and declares "
                    "in_shardings but NO out_shardings — the donated "
                    "state's output placement is left to inference, "
                    "and a silently replicated output un-does the "
                    "sharded-update memory win; re-declare the "
                    "sharding on the output",
                )
