"""C2 — jit-hazard checker (EDL101 host sync / EDL102 tracer branch /
EDL103 host side effects).

A function is a JIT CONTEXT when it is decorated with ``@jax.jit`` /
``@jit`` / ``@partial(jit, ...)``, or defined locally and later passed
to ``jit`` / ``pjit`` / ``vmap`` / ``pmap`` / ``shard_map`` in the same
scope (the repo's dominant idiom: ``step_fn = jax.jit(step)``). Nested
``def``s inside a jit context are traced with it and inherit the
context.

Inside a jit context:

* EDL101 — host-sync forcers: ``.item()``, ``.block_until_ready()``,
  ``jax.device_get``, ``np.asarray``/``np.array`` of a traced value,
  and ``float()``/``int()``/``bool()`` applied to a TAINTED expression.
  Each forces the accelerator pipeline to drain mid-trace (or fails
  under tracing); either way the hot loop dies.
* EDL102 — Python ``if``/``while`` on a tainted expression: control
  flow on a tracer raises ConcretizationTypeError at trace time, or —
  worse — silently bakes one branch in when the value is accidentally
  concrete. Use ``lax.cond``/``jnp.where``.
* EDL103 — ``time.*()`` and ``print()``: traced exactly once at
  compile time, so they LIE at runtime (a timestamp becomes a
  constant). Use ``jax.debug.print`` / time outside the jit boundary.

EDL108 extends the same hazard surface to ``pallas_call`` index maps:
a ``BlockSpec(..., lambda i, j, tbl_ref, ...: ...)`` lambda (2nd
positional arg or ``index_map=``) is traced with grid indices and
scalar-prefetch refs as its arguments — ALWAYS tracer inputs, no
taint analysis needed. ``np.asarray``/``np.array``, ``.item()`` and
``int()``/``float()``/``bool()`` casts inside one either raise
TracerArrayConversionError at trace time or, when the table happens
to be concrete (interpret-mode tests), silently BAKE a stale block
table into the compiled kernel — the block-table indirection the
paged decode kernel exists for then reads freed blocks after churn.
Index maps are checked module-wide, not only inside jit contexts: a
``pallas_call`` built in a plain helper is traced all the same.

TAINT is a deliberate approximation of "derived from a traced value":
the jit'd function's parameters seed the set, and single-assignment
propagation (``y = f(x)`` with ``x`` tainted taints ``y``) extends it
in statement order. Closure variables are NOT tainted — static Python
config captured from the enclosing scope (``if self.causal:``) is the
normal, correct idiom. Arguments declared static via
``static_argnums``/``static_argnames`` are untainted when the
declaration is a literal; a computed declaration falls back to
all-params-tainted (pragma the call if that over-approximates).
"""

import ast

from elasticdl_tpu.analysis.core import Finding, Rule, register

_JIT_WRAPPERS = {"jit", "pjit", "vmap", "pmap", "shard_map"}
_NP_NAMES = {"np", "numpy", "onp"}
_CASTS = {"float", "int", "bool"}
_TIME_FUNCS = {
    "time", "monotonic", "perf_counter", "sleep", "process_time",
    "thread_time",
}


def _dotted_tail(fn):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _jit_call_static_names(call, fndef):
    """Parameter names declared static on a jit(...) call/decorator,
    or None when they cannot be decided statically."""
    args = [a.arg for a in fndef.args.args]
    static = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = kw.value
            if isinstance(names, ast.Constant) and isinstance(
                names.value, str
            ):
                static.add(names.value)
            elif isinstance(names, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) for e in names.elts
            ):
                static.update(e.value for e in names.elts)
            else:
                return None
        elif kw.arg == "static_argnums":
            nums = kw.value
            if isinstance(nums, ast.Constant) and isinstance(
                nums.value, int
            ):
                idxs = [nums.value]
            elif isinstance(nums, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) for e in nums.elts
            ):
                idxs = [e.value for e in nums.elts]
            else:
                return None
            for i in idxs:
                if 0 <= i < len(args):
                    static.add(args[i])
    return static


def _index_map_lambdas(tree):
    """Every index-map lambda of a BlockSpec(...) call in the module:
    the 2nd positional argument or the ``index_map=`` keyword (both
    spellings: ``pl.BlockSpec`` and a bare imported ``BlockSpec``)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted_tail(node.func) != "BlockSpec":
            continue
        cands = []
        if len(node.args) >= 2:
            cands.append(node.args[1])
        cands.extend(kw.value for kw in node.keywords
                     if kw.arg == "index_map")
        for cand in cands:
            if isinstance(cand, ast.Lambda):
                yield cand


def _check_index_map(lam, path, findings):
    """EDL108 hits inside one index-map lambda body."""

    def emit(line, detail, what):
        findings.append(Finding(
            "EDL108", path, line, "BlockSpec.index_map", detail,
            "%s inside a pallas_call index map: the lambda is traced "
            "with grid indices and scalar-prefetch refs — host "
            "materialization raises at trace time or bakes a stale "
            "block table into the kernel; index with jnp ops on the "
            "prefetch ref" % what,
        ))

    for sub in ast.walk(lam.body):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if isinstance(fn, ast.Attribute):
            if fn.attr == "item" and not sub.args:
                emit(sub.lineno, ".item()", ".item()")
            elif (fn.attr in ("asarray", "array")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in _NP_NAMES):
                emit(sub.lineno, "np.%s" % fn.attr,
                     "np.%s()" % fn.attr)
        elif isinstance(fn, ast.Name) and fn.id in _CASTS:
            emit(sub.lineno, "%s()" % fn.id, "%s() cast" % fn.id)


def _collect_jit_contexts(tree):
    """(fndef, static_names) for every function that is a jit context."""
    contexts = {}

    def walk_scope_level(body):
        """ast.walk pruned at nested function/class boundaries: a call
        inside a nested def resolves names against THAT def's scope,
        not this one (recursion handles it with its own defs map)."""
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scope: recursion owns its body
            stack.extend(ast.iter_child_nodes(node))

    def scan_scope(body, local_defs):
        """One lexical scope: map name -> FunctionDef for local defs,
        then find jit/vmap wraps referencing them."""
        defs = dict(local_defs)
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        for call in walk_scope_level(body):
            if not isinstance(call, ast.Call):
                continue
            tail = _dotted_tail(call.func)
            if tail not in _JIT_WRAPPERS:
                continue
            for arg in call.args[:1]:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    fndef = defs[arg.id]
                    static = _jit_call_static_names(call, fndef)
                    contexts[fndef] = static
        # recurse into nested scopes
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                scan_scope(node.body, defs)

    scan_scope(tree.body, {})

    # decorator form
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _dotted_tail(dec) in _JIT_WRAPPERS:
                contexts[node] = set()
            elif isinstance(dec, ast.Call):
                tail = _dotted_tail(dec.func)
                if tail in _JIT_WRAPPERS:
                    contexts[node] = _jit_call_static_names(dec, node)
                elif tail == "partial" and dec.args and _dotted_tail(
                    dec.args[0]
                ) in _JIT_WRAPPERS:
                    contexts[node] = _jit_call_static_names(dec, node)
    return contexts


#: attribute reads that yield STATIC metadata even on a tracer — an
#: expression only reaching a tainted name through one of these is not
#: value-dependent (x.shape[0] is concrete at trace time)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}


class _Taint(object):
    """Statement-order single-pass taint over local names."""

    def __init__(self, seeds):
        self.names = set(seeds)

    def mentions_tainted(self, expr):
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Name) and node.id in self.names:
                return True
            if (isinstance(node, ast.Attribute)
                    and node.attr in _STATIC_ATTRS):
                continue  # x.shape / .dtype / .ndim are trace-static
            stack.extend(ast.iter_child_nodes(node))
        return False

    def assign(self, stmt):
        if isinstance(stmt, ast.Assign):
            tainted = self.mentions_tainted(stmt.value)
            for tgt in stmt.targets:
                for node in ast.walk(tgt):
                    if isinstance(node, ast.Name):
                        if tainted:
                            self.names.add(node.id)
                        else:
                            self.names.discard(node.id)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name) and self.mentions_tainted(
                stmt.value
            ):
                self.names.add(stmt.target.id)


class _JitBodyChecker(ast.NodeVisitor):
    def __init__(self, rule_path, scope, taint):
        self.path = rule_path
        self.scope = scope
        self.taint = taint
        self.findings = []

    def _emit(self, rule, line, detail, message):
        self.findings.append(
            Finding(rule, self.path, line, self.scope, detail, message)
        )

    def visit_Assign(self, node):
        self.generic_visit(node)
        self.taint.assign(node)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        self.taint.assign(node)

    def visit_For(self, node):
        # loop targets over tainted iterables are tainted
        if self.taint.mentions_tainted(node.iter):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self.taint.names.add(n.id)
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr == "item" and not node.args:
                self._emit(
                    "EDL101", node.lineno, ".item()",
                    ".item() forces a device->host sync inside a jit "
                    "context (fails on tracers; drains the pipeline "
                    "otherwise)",
                )
            elif fn.attr == "block_until_ready":
                self._emit(
                    "EDL101", node.lineno, ".block_until_ready()",
                    "block_until_ready() inside a jit context drains "
                    "the accelerator pipeline",
                )
            elif (fn.attr in ("asarray", "array")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in _NP_NAMES
                    and node.args
                    and self.taint.mentions_tainted(node.args[0])):
                self._emit(
                    "EDL101", node.lineno,
                    "np.%s" % fn.attr,
                    "numpy materialization of a traced value forces a "
                    "host sync; use jnp inside jit",
                )
            elif fn.attr == "device_get":
                self._emit(
                    "EDL101", node.lineno, "device_get",
                    "jax.device_get inside a jit context forces a host "
                    "sync",
                )
            elif (fn.attr in _TIME_FUNCS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "time"):
                self._emit(
                    "EDL103", node.lineno, "time.%s" % fn.attr,
                    "time.%s() is traced ONCE at compile time — inside "
                    "jit it returns a baked-in constant (time outside "
                    "the jit boundary)" % fn.attr,
                )
        elif isinstance(fn, ast.Name):
            if fn.id in _CASTS and node.args and self.taint.mentions_tainted(
                node.args[0]
            ):
                self._emit(
                    "EDL101", node.lineno, "%s()" % fn.id,
                    "%s() on a traced value forces concretization "
                    "(host sync / ConcretizationTypeError); use jnp "
                    "ops or mark the argument static" % fn.id,
                )
            elif fn.id == "print":
                self._emit(
                    "EDL103", node.lineno, "print",
                    "print() runs at trace time only — use "
                    "jax.debug.print for runtime values",
                )
        self.generic_visit(node)

    def visit_If(self, node):
        if self.taint.mentions_tainted(node.test):
            self._emit(
                "EDL102", node.lineno, "if",
                "Python `if` on a tracer-derived value: raises at "
                "trace time or silently bakes one branch in — use "
                "lax.cond / jnp.where",
            )
        self.generic_visit(node)

    def visit_While(self, node):
        if self.taint.mentions_tainted(node.test):
            self._emit(
                "EDL102", node.lineno, "while",
                "Python `while` on a tracer-derived value cannot be "
                "traced — use lax.while_loop",
            )
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # nested def: traced with the enclosing jit context; its params
        # are tainted too (scan/cond body carries tracers)
        inner = _Taint(self.taint.names)
        inner.names.update(a.arg for a in node.args.args)
        saved, self.taint = self.taint, inner
        for stmt in node.body:
            self.visit(stmt)
        self.taint = saved

    visit_AsyncFunctionDef = visit_FunctionDef


@register
class JitHazardRule(Rule):
    """EDL101/EDL102/EDL103 — see module docstring."""

    id = "EDL101"
    name = "jit-hazard"

    def check_module(self, tree, lines, path):
        findings = []
        for lam in _index_map_lambdas(tree):
            _check_index_map(lam, path, findings)
        for fndef, static in _collect_jit_contexts(tree).items():
            params = {a.arg for a in fndef.args.args}
            params.update(a.arg for a in fndef.args.kwonlyargs)
            if fndef.args.vararg:
                params.add(fndef.args.vararg.arg)
            if static:  # None = undecidable -> keep everything tainted
                params -= static
            # `self`-methods wrapped in jit: self is static in practice
            params.discard("self")
            taint = _Taint(params)
            checker = _JitBodyChecker(
                path, self._scope_name(fndef), taint
            )
            for stmt in fndef.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
        return findings

    @staticmethod
    def _scope_name(fndef):
        return fndef.name
