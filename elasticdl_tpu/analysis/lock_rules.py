"""C1 — lock-discipline race detector (EDL001 write / EDL002 read).

For every class that creates a ``threading.Lock``/``RLock``/
``Condition`` instance attribute, infer the set of ``self._x``
attributes the class considers lock-guarded — the attributes WRITTEN
inside any ``with self._lock:`` block — and then report accesses of
those attributes outside the lock:

* EDL001: a write (assignment, augmented assignment, subscript store,
  or a mutating method call like ``.append``/``.pop``) outside the
  lock — the canonical lost-update race.
* EDL002: a read outside the lock — usually torn/stale state; often
  benign for a monotonic scalar, which is what the pragma and the
  baseline are for.

The inference is methodwise with a LIGHT call-graph fixpoint over
intra-class ``self.method()`` calls, because this codebase's idiom is
"public method takes the lock, private helper assumes it":

* ``__init__`` and other ctor-only helpers are single-threaded by
  construction — exempt;
* a method named ``*_locked`` declares "caller holds the lock" —
  treated as locked (the convention is self-documenting; the checker
  just honors it);
* a method whose every non-ctor intra-class call site sits inside a
  lock region is treated as locked (e.g. telemetry's ``_scalar``);
  one unlocked call site makes it open, and its body is checked.

Deliberately NOT modeled (keep the rule predictable): cross-object
accesses (``other.attr``), class-level locks, lock identity when a
class holds several locks (any held lock counts — flagging
wrong-lock-held would need alias analysis and drown signal in noise).
"""

import ast

from elasticdl_tpu.analysis.core import Finding, Rule, register

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: method calls that mutate their receiver
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "clear", "pop", "popleft", "popitem",
    "update", "setdefault", "sort", "reverse",
}

# method contexts, ordered as a lattice: EXEMPT < LOCKED < OPEN
_EXEMPT, _LOCKED, _OPEN = 0, 1, 2


def _self_attr(node):
    """'x' for an ast.Attribute spelling ``self.x``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_ctor(value):
    """True for ``threading.Lock()`` / ``Lock()`` / ``RLock()`` /
    ``Condition(...)`` call expressions."""
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_CTORS
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_CTORS
    return False


class _Access(object):
    __slots__ = ("attr", "line", "is_write", "locked")

    def __init__(self, attr, line, is_write, locked):
        self.attr = attr
        self.line = line
        self.is_write = is_write
        self.locked = locked


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body: records every self.<attr> access
    with its locked-ness, every lock-attr assignment, and every
    intra-class ``self.m()`` call site."""

    def __init__(self, lock_attrs):
        self.lock_attrs = lock_attrs
        self.depth = 0  # with-lock nesting
        self.accesses = []
        self.lock_defs = set()
        self.call_sites = []  # (callee_name, locked)

    # -- lock regions

    def visit_With(self, node):
        holds = 0
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is None and isinstance(item.context_expr, ast.Call):
                # with self._lock: vs with self._cv: — Conditions are
                # entered directly; .acquire()-style calls are not
                # with-items in this codebase, but cover self._x()
                attr = _self_attr(item.context_expr.func)
            if attr in self.lock_attrs:
                holds += 1
        self.depth += holds
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.depth -= holds

    # -- accesses

    def _record(self, attr, line, is_write):
        if attr in self.lock_attrs:
            return
        self.accesses.append(
            _Access(attr, line, is_write, self.depth > 0)
        )

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._visit_store_target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        attr = _self_attr(node.target)
        if attr is not None:
            self._record(attr, node.lineno, True)
        else:
            self._visit_store_target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.target is not None:
            self._visit_store_target(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_Delete(self, node):
        for tgt in node.targets:
            self._visit_store_target(tgt)

    def _visit_store_target(self, tgt):
        attr = _self_attr(tgt)
        if attr is not None:
            self._record(attr, tgt.lineno, True)
            return
        if isinstance(tgt, ast.Subscript):
            # self.x[k] = v mutates x
            attr = _self_attr(tgt.value)
            if attr is not None:
                self._record(attr, tgt.lineno, True)
            else:
                self.visit(tgt.value)
            self.visit(tgt.slice)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._visit_store_target(elt)
            return
        self.visit(tgt)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv_attr = _self_attr(fn.value)
            if recv_attr is None and isinstance(fn.value, ast.Subscript):
                # self.x[k].append(...) mutates the structure x guards
                recv_attr = _self_attr(fn.value.value)
            if recv_attr is not None and fn.attr in _MUTATORS:
                # self.x.append(...) — a write to x
                self._record(recv_attr, node.lineno, True)
                for arg in node.args:
                    self.visit(arg)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
            callee = _self_attr(fn)
            if callee is not None:
                # self.m(...) — intra-class call site
                self.call_sites.append((callee, self.depth > 0))
        self.generic_visit(node)

    def visit_Attribute(self, node):
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._record(attr, node.lineno, False)
        self.generic_visit(node)

    # nested defs execute later but still touch shared state from this
    # class's threads — scan them in place (their own with-locks count)
    def visit_FunctionDef(self, node):
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.visit(node.body)


def _find_lock_attrs(classdef):
    locks = set()
    for node in ast.walk(classdef):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    locks.add(attr)
    return locks


@register
class LockDisciplineRule(Rule):
    """EDL001/EDL002 — see module docstring. One registered Rule emits
    both ids so the lock inference runs once per class."""

    id = "EDL001"
    name = "lock-discipline"

    def check_module(self, tree, lines, path):
        findings = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, classdef, path):
        lock_attrs = _find_lock_attrs(classdef)
        if not lock_attrs:
            return
        methods = {
            n.name: n for n in classdef.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        scans = {}
        for name, fn in methods.items():
            scan = _MethodScan(lock_attrs)
            for stmt in fn.body:
                scan.visit(stmt)
            scans[name] = scan

        # guarded set: attributes written under any held lock
        guarded = set()
        for scan in scans.values():
            for acc in scan.accesses:
                if acc.is_write and acc.locked:
                    guarded.add(acc.attr)
        if not guarded:
            return

        ctx = self._method_contexts(methods, scans)

        for name, scan in scans.items():
            if ctx[name] != _OPEN:
                continue
            scope = "%s.%s" % (classdef.name, name)
            for acc in scan.accesses:
                if acc.locked or acc.attr not in guarded:
                    continue
                if acc.is_write:
                    yield Finding(
                        "EDL001", path, acc.line, scope, acc.attr,
                        "write of lock-guarded attribute %r outside "
                        "the lock (guarded by with-blocks on %s)"
                        % (acc.attr, "/".join(sorted(lock_attrs))),
                    )
                else:
                    yield Finding(
                        "EDL002", path, acc.line, scope, acc.attr,
                        "read of lock-guarded attribute %r outside "
                        "the lock; may observe torn/stale state"
                        % (acc.attr,),
                    )

    @staticmethod
    def _method_contexts(methods, scans):
        """Fixpoint over the lattice EXEMPT < LOCKED < OPEN. A method
        starts at bottom; ``__init__`` and ``*_locked`` are pinned;
        a method with no intra-class callers is OPEN (public API);
        otherwise it joins its call sites' contexts, where a site in a
        lock region contributes LOCKED and any other site contributes
        the CALLER's context."""
        pinned = {}
        for name in methods:
            if name == "__init__":
                pinned[name] = _EXEMPT
            elif name.endswith("_locked"):
                pinned[name] = _LOCKED
        callers = {name: [] for name in methods}
        for caller, scan in scans.items():
            for callee, locked in scan.call_sites:
                if callee in callers:
                    callers[callee].append((caller, locked))
            # a bare `self.m` READ is a reference that will be invoked
            # later (deferred-callback idiom); the reference's context
            # is the best available approximation of the call's
            for acc in scan.accesses:
                if not acc.is_write and acc.attr in callers:
                    callers[acc.attr].append((caller, acc.locked))
        ctx = {
            name: pinned.get(
                name, _EXEMPT if callers[name] else _OPEN
            )
            for name in methods
        }
        changed = True
        while changed:
            changed = False
            for name in methods:
                if name in pinned or not callers[name]:
                    continue
                joined = _EXEMPT
                for caller, locked in callers[name]:
                    site = _LOCKED if locked else ctx[caller]
                    joined = max(joined, site)
                if joined > ctx[name]:
                    ctx[name] = joined
                    changed = True
        return ctx
