"""k8s TensorBoard exposure: create a LoadBalancer service in front of
the master's TensorBoard and wait for its external URL.

Parity with the reference's
elasticdl/python/common/k8s_tensorboard_client.py:22-66
(`TensorBoardClient`): `start_tensorboard_service` creates the service
via the shared k8s client and polls the service's load-balancer ingress
until an external IP appears or the timeout lapses. The subprocess that
actually runs TensorBoard is master/tensorboard_service.py; this module
is only the cluster-networking half.
"""

import time

from elasticdl_tpu.common.k8s_client import Client
from elasticdl_tpu.common.log_utils import default_logger as logger


class TensorBoardClient(object):
    def __init__(self, client=None, **kwargs):
        """`client`: an existing k8s_client.Client (tests pass one with
        a fake core_api); otherwise one is built from **kwargs exactly
        like the reference constructor."""
        self._k8s_client = client if client is not None else Client(
            **kwargs
        )

    def start_tensorboard_service(self, check_interval=5,
                                  wait_timeout=120):
        try:
            self._k8s_client.create_tensorboard_service()
        except Exception as e:  # noqa: BLE001
            # Tolerate an already-existing service (409 on master
            # restart/resubmission under the same job name) — the poll
            # below answers whether a usable service is there either way.
            logger.warning(
                "create_tensorboard_service failed (%s); polling the "
                "existing service", e,
            )
        logger.info("Waiting for the URL for TensorBoard service...")
        tb_url = self._get_tensorboard_url(
            check_interval=check_interval, wait_timeout=wait_timeout
        )
        if tb_url:
            logger.info(
                "TensorBoard service is available at: %s", tb_url
            )
        else:
            logger.warning(
                "Unable to get the URL for TensorBoard service"
            )
        return tb_url

    def _get_tensorboard_service(self):
        return self._k8s_client.read_service(
            self._k8s_client.get_tensorboard_service_name()
        )

    def _get_tensorboard_url(self, check_interval=5, wait_timeout=120):
        """Poll until the LoadBalancer reports an ingress IP (reference
        k8s_tensorboard_client.py:53-66)."""
        start_time = time.time()
        while True:
            service = self._get_tensorboard_service()
            # the k8s client's to_dict() emits unset fields as explicit
            # None values, so chained .get(..., {}) defaults don't help
            status = (service or {}).get("status") or {}
            lb = status.get("load_balancer") or {}
            ingress = lb.get("ingress")
            if ingress:
                return ingress[0].get("ip") or ingress[0].get(
                    "hostname"
                )
            if time.time() - start_time > wait_timeout:
                return None
            time.sleep(check_interval)
