"""Kubernetes client: pod/service lifecycle for the elastic master.

Parity with the reference's master-side client (common/k8s_client.py:
29-329): worker pod CRUD with owner references to the master pod, a
watch-stream thread feeding pod events to a callback, per-replica
services, master-pod labels as job status. TPU-native deltas: replicas
are TPU-VM worker pods (resource key `google.com/tpu`), and there are no
PS pods or FTLib gossip services to manage.

The `kubernetes` package import is gated: construction takes an optional
`core_api` (anything with the CoreV1Api surface), which is how unit
tests drive the client without a cluster — the reference mocks the same
boundary (k8s_client_test.py).

Pod manifests are plain dicts (the k8s API accepts them verbatim), so
nothing here needs the kubernetes model classes.
"""

import threading
import traceback

from elasticdl_tpu.common.log_utils import default_logger as logger

ELASTICDL_APP_NAME = "elasticdl"
ELASTICDL_JOB_KEY = "elasticdl-job-name"
ELASTICDL_REPLICA_TYPE_KEY = "elasticdl-replica-type"
ELASTICDL_REPLICA_INDEX_KEY = "elasticdl-replica-index"

_SERVICE_PORT = {"worker": 3333, "master": 50001}


def get_master_pod_name(job_name):
    return "elasticdl-%s-master" % job_name


class Client(object):
    def __init__(
        self,
        *,
        image_name,
        namespace,
        job_name,
        event_callback=None,
        cluster_spec="",
        core_api=None,
    ):
        self.image_name = image_name
        self.namespace = namespace
        self.job_name = job_name
        self._event_cb = event_callback
        self._cluster_spec = cluster_spec
        self._watch_thread = None
        self._stopped = threading.Event()
        if core_api is not None:
            self.client = core_api
        else:
            self.client = self._load_core_api()
        if self._event_cb:
            self._watch_thread = threading.Thread(
                target=self._watch, name="event_watcher", daemon=True
            )
            self._watch_thread.start()

    @staticmethod
    def _load_core_api():
        try:
            from kubernetes import client as k8s_client
            from kubernetes import config
        except ImportError as e:
            raise RuntimeError(
                "The kubernetes package is not installed; pass core_api= "
                "or use the local instance manager"
            ) from e
        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        return k8s_client.CoreV1Api()

    # ------------------------------------------------------------- watch

    def _watch(self):
        """Stream pod events for this job to the callback (reference
        Client._watch, k8s_client.py:82-96)."""
        from kubernetes import watch

        label_selector = "%s=%s" % (ELASTICDL_JOB_KEY, self.job_name)
        while not self._stopped.is_set():
            try:
                stream = watch.Watch().stream(
                    self.client.list_namespaced_pod,
                    self.namespace,
                    label_selector=label_selector,
                )
                for event in stream:
                    if self._stopped.is_set():
                        break
                    self._event_cb(event)
            except Exception:
                if not self._stopped.is_set():
                    traceback.print_exc()
                    # don't busy-spin when the API server is unreachable
                    self._stopped.wait(3.0)

    def stop(self):
        self._stopped.set()

    # -------------------------------------------------------------- names

    def get_master_pod_name(self):
        return get_master_pod_name(self.job_name)

    def get_worker_pod_name(self, worker_id):
        return "elasticdl-%s-worker-%s" % (self.job_name, worker_id)

    def get_worker_service_name(self, worker_id):
        return self.get_worker_pod_name(worker_id)

    # ------------------------------------------------------------ get/del

    def get_master_pod(self):
        return self.get_pod(self.get_master_pod_name())

    def get_pod(self, pod_name):
        try:
            return self.client.read_namespaced_pod(
                namespace=self.namespace, name=pod_name
            )
        except Exception as e:
            logger.warning("Exception in read_namespaced_pod: %s", e)
            return None

    def delete_pod(self, pod_name):
        self.client.delete_namespaced_pod(
            pod_name,
            self.namespace,
            body={"propagationPolicy": "Foreground"},
        )

    def delete_worker(self, worker_id):
        self.delete_pod(self.get_worker_pod_name(worker_id))

    # ------------------------------------------------------------- create

    def _owner_reference(self):
        """Owner ref to the master pod so worker pods are GC'd with it
        (reference create_owner_reference, k8s_client.py)."""
        master = self.get_master_pod()
        if master is None:
            return []
        meta = (
            master["metadata"]
            if isinstance(master, dict)
            else master.metadata
        )
        name = meta["name"] if isinstance(meta, dict) else meta.name
        uid = meta["uid"] if isinstance(meta, dict) else meta.uid
        return [
            {
                "apiVersion": "v1",
                "blockOwnerDeletion": True,
                "kind": "Pod",
                "name": name,
                "uid": uid,
            }
        ]

    def _pod_manifest(
        self,
        *,
        pod_name,
        replica_type,
        replica_index,
        command,
        args,
        resource_requests,
        resource_limits,
        priority_class=None,
        restart_policy="Never",
        image_pull_policy="Always",
        envs=None,
        volume=None,
        image_name=None,
    ):
        container = {
            "name": pod_name,
            "image": image_name or self.image_name,
            "command": list(command or []),
            "args": list(args or []),
            "imagePullPolicy": image_pull_policy,
            "resources": {
                "requests": dict(resource_requests or {}),
                "limits": dict(
                    resource_limits or resource_requests or {}
                ),
            },
            "env": [
                {"name": k, "value": str(v)}
                for k, v in (envs or {}).items()
            ],
        }
        spec = {
            "containers": [container],
            "restartPolicy": restart_policy,
        }
        if priority_class:
            spec["priorityClassName"] = priority_class
        if volume:
            spec["volumes"] = [
                {
                    "name": "elasticdl-volume",
                    "hostPath": {"path": volume["host_path"]},
                }
            ]
            container["volumeMounts"] = [
                {
                    "name": "elasticdl-volume",
                    "mountPath": volume["mount_path"],
                }
            ]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "labels": {
                    "app": ELASTICDL_APP_NAME,
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: replica_type,
                    ELASTICDL_REPLICA_INDEX_KEY: str(replica_index),
                },
                "ownerReferences": self._owner_reference(),
            },
            "spec": spec,
        }

    def create_worker_pod(self, worker_id, **kwargs):
        manifest = self._pod_manifest(
            pod_name=self.get_worker_pod_name(worker_id),
            replica_type="worker",
            replica_index=worker_id,
            **kwargs,
        )
        if self._cluster_spec:
            manifest = self._apply_cluster_spec(manifest)
        return self.client.create_namespaced_pod(self.namespace, manifest)

    def _apply_cluster_spec(self, manifest):
        """Load the user cluster-spec module and let it patch the pod
        manifest (reference cluster spec hook)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "cluster_spec", self._cluster_spec
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        if hasattr(module, "with_pod"):
            return module.with_pod(manifest)
        return manifest

    def create_worker_service(self, worker_id):
        """Per-replica service so a relaunched worker keeps its address
        (reference create_service, k8s_client.py; ports at :29-31)."""
        name = self.get_worker_service_name(worker_id)
        manifest = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name,
                "labels": {
                    "app": ELASTICDL_APP_NAME,
                    ELASTICDL_JOB_KEY: self.job_name,
                },
                "ownerReferences": self._owner_reference(),
            },
            "spec": {
                "selector": {
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: "worker",
                    ELASTICDL_REPLICA_INDEX_KEY: str(worker_id),
                },
                "ports": [
                    {"port": _SERVICE_PORT["worker"], "protocol": "TCP"}
                ],
                "clusterIP": "None",
            },
        }
        return self.client.create_namespaced_service(
            self.namespace, manifest
        )

    def get_tensorboard_service_name(self):
        """Reference k8s_client.py:219-220."""
        return self.job_name + "-tensorboard"

    def create_tensorboard_service(self, port=80, target_port=6006,
                                   service_type="LoadBalancer"):
        """Expose the master pod's TensorBoard through a LoadBalancer
        service (reference k8s_client.py:222-237
        create_tensorboard_service: port 80 -> master's 6006)."""
        manifest = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": self.get_tensorboard_service_name(),
                "labels": {
                    "app": ELASTICDL_APP_NAME,
                    ELASTICDL_JOB_KEY: self.job_name,
                },
                "ownerReferences": self._owner_reference(),
            },
            "spec": {
                "selector": {
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: "master",
                    ELASTICDL_REPLICA_INDEX_KEY: "0",
                },
                "ports": [
                    {
                        "port": int(port),
                        "targetPort": int(target_port),
                        "protocol": "TCP",
                    }
                ],
                "type": service_type,
            },
        }
        return self.client.create_namespaced_service(
            self.namespace, manifest
        )

    def read_service(self, name):
        """Read a namespaced service; None when unreadable (mirrors the
        reference TB client's tolerant read,
        k8s_tensorboard_client.py:41-51)."""
        try:
            svc = self.client.read_namespaced_service(
                name=name, namespace=self.namespace
            )
            return svc.to_dict() if hasattr(svc, "to_dict") else svc
        except Exception as e:  # noqa: BLE001 - absent/denied -> None
            logger.warning(
                "Exception when reading service %s: %s", name, e
            )
            return None

    def create_master_pod(self, *, command, args, resource_requests,
                          resource_limits=None, priority_class=None,
                          restart_policy="Never",
                          image_pull_policy="Always", envs=None,
                          volume=None):
        """Create the job-root master pod (reference client-side
        create_master, elasticdl_client/common/k8s_client.py). The
        master owns the job: no owner reference."""
        manifest = self._pod_manifest(
            pod_name=self.get_master_pod_name(),
            replica_type="master",
            replica_index=0,
            command=command,
            args=args,
            resource_requests=resource_requests,
            resource_limits=resource_limits,
            priority_class=priority_class,
            restart_policy=restart_policy,
            image_pull_policy=image_pull_policy,
            envs=envs,
            volume=volume,
        )
        manifest["metadata"]["ownerReferences"] = []
        return self.client.create_namespaced_pod(self.namespace, manifest)

    # ------------------------------------------------------------- status

    def update_master_label(self, status):
        """Reflect job status as a master-pod label (reference: master
        pod labels carry status for the CLI job monitor)."""
        body = {"metadata": {"labels": {"status": status}}}
        self.client.patch_namespaced_pod(
            self.get_master_pod_name(), self.namespace, body
        )
