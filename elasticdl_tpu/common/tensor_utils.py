"""ndarray / pytree (de)serialization for the control plane and checkpoints.

Replaces the reference's TF `TensorProto`-based codec
(elasticdl/python/common/tensor_utils.py:29-114) with a self-contained binary
layout (no TF dependency):

    Tensor   := name | wire_dtype | ndim | dims[] | raw bytes (C-order)
    IndexedSlices := ids tensor + values tensor

Also provides `deduplicate_indexed_slices` / `merge_indexed_slices`, which the
reference uses to combine sparse embedding gradients before the PS scatter
(tensor_utils.py:84-114); here they feed the sharded-HBM embedding update.
"""

import struct

import numpy as np

from elasticdl_tpu.common.dtypes import (
    BYTES_WIRE_ID,
    dtype_to_wire,
    wire_to_dtype,
)

_HEADER = struct.Struct("<HBB")  # name_len, wire_dtype, ndim
_DIM = struct.Struct("<q")


def serialize_ndarray(array, name=""):
    """Serialize one ndarray (with optional name) to bytes."""
    array = np.asarray(array)
    shape = array.shape  # before ascontiguousarray, which promotes 0-d to 1-d
    array = np.ascontiguousarray(array)
    name_b = name.encode("utf-8")
    if len(name_b) > 0xFFFF:
        raise ValueError("tensor name too long")
    if array.dtype.kind == "U":  # unicode str arrays ride as utf-8 bytes
        array = np.char.encode(array, "utf-8")
    wire = dtype_to_wire(array.dtype)
    dims = list(shape)
    if wire == BYTES_WIRE_ID:
        if array.dtype.itemsize == 0:  # all-empty strings -> 1-byte slots
            array = array.astype("S1")
        dims.append(array.dtype.itemsize)  # trailing pseudo-dim: byte width
    parts = [_HEADER.pack(len(name_b), wire, len(dims))]
    parts.append(name_b)
    for d in dims:
        parts.append(_DIM.pack(d))
    parts.append(array.tobytes())
    return b"".join(parts)


def deserialize_ndarray(buf, offset=0):
    """Inverse of serialize_ndarray. Returns (name, array, next_offset)."""
    name_len, wire, ndim = _HEADER.unpack_from(buf, offset)
    offset += _HEADER.size
    name = bytes(buf[offset : offset + name_len]).decode("utf-8")
    offset += name_len
    shape = []
    for _ in range(ndim):
        (d,) = _DIM.unpack_from(buf, offset)
        shape.append(d)
        offset += _DIM.size
    if wire == BYTES_WIRE_ID:
        itemsize = max(1, shape.pop())  # trailing pseudo-dim: byte width
        dtype = np.dtype("S%d" % itemsize)
    else:
        dtype = wire_to_dtype(wire)
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    array = np.frombuffer(buf, dtype=dtype, count=count, offset=offset).reshape(
        tuple(shape)
    )
    offset += nbytes
    return name, array, offset


def serialize_ndarray_dict(d):
    """Serialize {name: ndarray} to bytes (order-stable by name)."""
    parts = [struct.pack("<I", len(d))]
    for name in sorted(d):
        parts.append(serialize_ndarray(np.asarray(d[name]), name))
    return b"".join(parts)


def deserialize_ndarray_dict(buf):
    (n,) = struct.unpack_from("<I", buf, 0)
    offset = 4
    out = {}
    for _ in range(n):
        name, arr, offset = deserialize_ndarray(buf, offset)
        out[name] = arr
    return out


def deduplicate_indexed_slices(values, indices):
    """Sum-combine rows with duplicate indices.

    Reference: common/tensor_utils.py `deduplicate_indexed_slices` (via
    tf.math.segment_sum). Pure numpy: returns (sum_combined_values,
    unique_indices) where sum_combined_values[i] is the sum of all rows of
    `values` whose index == unique_indices[i].
    """
    values = np.asarray(values)
    indices = np.asarray(indices)
    unique_ids, inverse = np.unique(indices, return_inverse=True)
    summed = np.zeros((unique_ids.shape[0],) + values.shape[1:], values.dtype)
    np.add.at(summed, inverse, values)
    return summed, unique_ids


def merge_indexed_slices(*slices_list):
    """Concatenate (values, ids) pairs (reference tensor_utils.py
    `merge_indexed_slices`); combine with deduplicate_indexed_slices."""
    values = np.concatenate([np.asarray(v) for v, _ in slices_list], axis=0)
    ids = np.concatenate([np.asarray(i) for _, i in slices_list], axis=0)
    return values, ids
