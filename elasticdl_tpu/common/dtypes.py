"""Dtype registry: numpy <-> wire ids <-> JAX dtypes.

Replaces the reference's TF-centric dtype maps
(elasticdl/python/common/dtypes.py). Wire ids are stable small ints used by
the tensor serialization (`tensor_utils.py`) and the native record codec.
"""

import numpy as np

# Stable wire ids. Never renumber — checkpoints and the control-plane protocol
# depend on them.
_WIRE = [
    (1, np.dtype(np.float16)),
    (2, np.dtype(np.float32)),
    (3, np.dtype(np.float64)),
    (4, np.dtype(np.int8)),
    (5, np.dtype(np.int16)),
    (6, np.dtype(np.int32)),
    (7, np.dtype(np.int64)),
    (8, np.dtype(np.uint8)),
    (9, np.dtype(np.uint16)),
    (10, np.dtype(np.uint32)),
    (11, np.dtype(np.uint64)),
    (12, np.dtype(np.bool_)),
    # bfloat16 — the TPU-native default compute dtype. numpy has no builtin
    # bfloat16; ml_dtypes (a JAX dependency) provides it.
    (13, None),  # placeholder, filled below
    # wire id 14 is BYTES_WIRE_ID: fixed-length bytes ('S<n>'); object
    # arrays are rejected (np.frombuffer cannot reconstruct them)
]

try:  # ml_dtypes ships with jax
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = None

NP_DTYPE_TO_WIRE = {}
WIRE_TO_NP_DTYPE = {}
for wire_id, dt in _WIRE:
    if wire_id == 13:
        dt = _BFLOAT16
    if dt is None:
        continue
    NP_DTYPE_TO_WIRE[dt] = wire_id
    WIRE_TO_NP_DTYPE[wire_id] = dt


# wire id 14: fixed-length bytes (numpy 'S<n>'); the itemsize rides in the
# serialized shape (tensor_utils appends it as a trailing pseudo-dim).
BYTES_WIRE_ID = 14


def dtype_to_wire(dtype):
    dtype = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if dtype.kind == "S":
        return BYTES_WIRE_ID
    try:
        return NP_DTYPE_TO_WIRE[dtype]
    except KeyError:
        raise ValueError("Unsupported dtype for serialization: %r" % (dtype,))


def wire_to_dtype(wire_id):
    try:
        return WIRE_TO_NP_DTYPE[wire_id]
    except KeyError:
        raise ValueError("Unknown wire dtype id: %r" % (wire_id,))


def is_numerical_dtype(dtype):
    dtype = np.dtype(dtype)
    return dtype.kind in "fiub"
