"""Profiling helpers: XLA/TPU traces + the phase taxonomy.

The reference's tracing story is wall-clock accumulation per phase
(common/timing_utils.py {task_process, batch_process, get_model,
report_gradient}); timing_utils.py here keeps that taxonomy. This module
adds the TPU-native layer on top: `jax.profiler` device traces viewable
in TensorBoard/Perfetto, and per-step trace annotations.

    with profile_trace("/tmp/trace"):          # whole-program trace
        ...
    with step_annotation(step):                # names one train step
        state, loss = trainer.train_step(...)
"""

import contextlib

from elasticdl_tpu.common.log_utils import default_logger as logger


@contextlib.contextmanager
def profile_trace(log_dir, create_perfetto_link=False):
    """Capture a jax.profiler trace into `log_dir` for the duration of
    the block. Safe no-op if the profiler can't start (e.g. a second
    concurrent trace)."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(
            log_dir, create_perfetto_link=create_perfetto_link
        )
        started = True
        logger.info("Profiler trace started -> %s", log_dir)
    except Exception as e:
        logger.warning("Could not start profiler trace: %s", e)
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
            logger.info("Profiler trace written to %s", log_dir)


def step_annotation(step_num):
    """Label one training step in the device trace (shows up as
    `train_step` rows in the trace viewer)."""
    import jax

    return jax.profiler.StepTraceAnnotation("train_step",
                                            step_num=int(step_num))
