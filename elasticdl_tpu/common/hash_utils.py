"""Deterministic partitioning helpers.

Parity with the reference's elasticdl/python/common/hash_utils.py:17-63:
dense variables are placed by sha256-of-name mod N, embedding rows by id mod N.
In this framework the same functions partition embedding rows across the mesh's
`ep` axis shards and place host-spilled tables.
"""

import hashlib

import numpy as np


def string_to_id(name, bucket_num):
    """sha256(name) mod bucket_num (reference hash_utils.py:17-22)."""
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive, got %d" % bucket_num)
    digest = hashlib.sha256(name.encode("utf-8")).hexdigest()
    return int(digest, 16) % bucket_num


def int_to_id(value, bucket_num):
    """value mod bucket_num (reference hash_utils.py:25-27)."""
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive, got %d" % bucket_num)
    return int(value) % bucket_num


def scatter_ids(ids, bucket_num):
    """Partition an int array of ids into per-bucket index lists.

    Returns (bucket_ids, bucket_positions): for each bucket b,
    ``bucket_ids[b]`` holds the ids routed to b (id % bucket_num == b) and
    ``bucket_positions[b]`` their positions in the input array, so results can
    be scattered back (reference hash_utils.py `scatter_embedding_vector`
    behavior, vectorized).
    """
    ids = np.asarray(ids)
    buckets = ids % bucket_num
    bucket_ids, bucket_positions = [], []
    for b in range(bucket_num):
        mask = buckets == b
        bucket_ids.append(ids[mask])
        bucket_positions.append(np.nonzero(mask)[0])
    return bucket_ids, bucket_positions
