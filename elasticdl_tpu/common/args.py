"""Declarative argparse flag system for master/worker processes and the
client CLI.

Parity with the reference's three-layer arg stack
(elasticdl_client/common/args.py + elasticdl/python/common/args.py,
~817 LoC): the same declarative adders, the same propagation model —
parsed args are RE-SERIALIZED into child command lines
(`build_arguments_from_parsed_result`, reference
elasticdl_client/common/args.py:519-567, used by the master to build
worker pod commands at master/master.py:398-496) — minus the PS flag
groups (no parameter servers on TPU) plus the mesh/sharding flags the
TPU runtime adds.
"""

import argparse

# master-only flags that must not propagate into worker command lines
MASTER_ONLY_ARGS = {
    "port", "num_workers", "worker_image", "namespace",
    "worker_pod_priority", "worker_resource_request",
    "worker_resource_limit", "relaunch_on_worker_failure",
    "disable_relaunch", "task_timeout_check_interval", "cluster_spec",
    "image_pull_policy", "restart_policy", "volume", "need_tensorboard",
    "tensorboard_log_dir", "export_saved_model", "job_status_file",
    "job_state_dir",
}


def pos_int(arg):
    res = int(arg)
    if res <= 0:
        raise ValueError("Positive integer argument required, got %s" % res)
    return res


def non_neg_int(arg):
    res = int(arg)
    if res < 0:
        raise ValueError(
            "Non-negative integer argument required, got %s" % res
        )
    return res


def add_bool_param(parser, name, default, help):
    parser.add_argument(
        name,
        nargs="?",
        const=not default,
        default=default,
        type=lambda x: x.lower() in ["true", "yes", "t", "y"],
        help=help,
    )


def add_common_params(parser):
    """Flags shared by client, master and worker (reference
    add_common_params, elasticdl_client/common/args.py)."""
    parser.add_argument(
        "--job_name", default="elasticdl-job", help="Job name"
    )
    parser.add_argument(
        "--model_zoo", required=True,
        help="Directory containing the model-zoo modules",
    )
    parser.add_argument(
        "--model_def", required=True,
        help="Dotted path to the model function inside the zoo, e.g. "
             "mnist_functional_api.mnist_functional_api.custom_model",
    )
    parser.add_argument(
        "--model_params", default="",
        help="Model constructor kwargs, 'k1=v1; k2=v2'",
    )
    parser.add_argument("--minibatch_size", type=pos_int, default=32)
    parser.add_argument(
        "--grad_accum_steps", "--get_model_steps", dest="grad_accum_steps",
        type=pos_int, default=1,
        help="Apply the dense optimizer every N minibatches on the "
             "averaged gradient (the reference's local-update mode, "
             "--get_model_steps; worker.py:1007-1089)",
    )
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument(
        "--records_per_task", type=pos_int, default=256,
        help="Records per dynamic-sharding task",
    )
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument(
        "--data_reader_params", default="",
        help="Data reader kwargs, 'k1=v1; k2=v2'",
    )
    parser.add_argument("--evaluation_steps", type=non_neg_int, default=0)
    parser.add_argument(
        "--eval_start_delay_secs", type=non_neg_int, default=0
    )
    parser.add_argument("--eval_throttle_secs", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument(
        "--keep_checkpoint_max", type=non_neg_int, default=0
    )
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument(
        "--output", default="",
        help="Directory for the exported model at train end",
    )
    parser.add_argument(
        "--mesh_spec", default="",
        help="Device mesh axis sizes, e.g. 'dp=4,sp=2' (-1 fills)",
    )
    parser.add_argument(
        "--distribution_strategy", default="Local",
        choices=["Local", "AllreduceStrategy"],
        help="Local = single process; AllreduceStrategy = SPMD lockstep "
             "over jax.distributed (the reference's allreduce path)",
    )
    parser.add_argument("--log_level", default="INFO")
    parser.add_argument("--seed", type=int, default=0)
    add_bool_param(
        parser, "--use_go_ps", False,
        help="Accepted for reference CLI compatibility; ignored (there "
             "is no parameter server on TPU)",
    )


def add_master_params(parser):
    parser.add_argument("--port", type=non_neg_int, default=50001)
    parser.add_argument("--num_workers", type=non_neg_int, default=0)
    parser.add_argument(
        "--worker_image", default="", help="Worker container image"
    )
    parser.add_argument(
        "--namespace", default="default", help="Kubernetes namespace"
    )
    parser.add_argument(
        "--worker_pod_priority", default="",
        help="Priority class for worker pods; 'high=0.5' makes half the "
             "workers high-priority (reference "
             "k8s_instance_manager.py _parse_worker_pod_priority)",
    )
    parser.add_argument(
        "--worker_resource_request",
        default="cpu=1,memory=4096Mi",
        help="Worker resource requests, 'cpu=N,memory=XMi,google.com/tpu=N'",
    )
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument(
        "--relaunch_on_worker_failure", type=non_neg_int, default=3,
        help="Max relaunches per worker pod",
    )
    add_bool_param(
        parser, "--disable_relaunch", False,
        help="Never relaunch failed workers",
    )
    parser.add_argument(
        "--task_timeout_check_interval", type=pos_int, default=30
    )
    parser.add_argument(
        "--cluster_spec", default="",
        help="Python module customizing pod manifests before creation",
    )
    parser.add_argument(
        "--image_pull_policy", default="Always",
        choices=["Always", "IfNotPresent", "Never"],
    )
    parser.add_argument("--restart_policy", default="Never")
    parser.add_argument(
        "--volume", default="",
        help="Host volume spec 'host_path=/a,mount_path=/b'",
    )
    add_bool_param(
        parser, "--need_tensorboard", False,
        help="Start a TensorBoard service on the master",
    )
    parser.add_argument("--tensorboard_log_dir", default="")
    add_bool_param(
        parser, "--export_saved_model", False,
        help="Export the model at train end via the TRAIN_END_CALLBACK "
             "task",
    )
    parser.add_argument(
        "--job_status_file", default="",
        help="Write the job phase (Pending/Running/Succeeded/Failed) to "
             "this JSON file — the local-master twin of the k8s master-"
             "pod status label, polled by scripts/validate_job_status.py",
    )
    parser.add_argument(
        "--job_state_dir", default="",
        help="Directory for the master's write-ahead journal + compacted "
             "snapshot of dispatcher state (master/state_store.py). A "
             "relaunched master pointed at the same directory restores "
             "todo/doing/retry/epoch state exactly and resumes the job; "
             "empty disables journaling (the reference behavior).",
    )
    parser.add_argument(
        "--metrics_port", type=int, default=-1,
        help="Prometheus-text /metrics exposition for the master "
             "process (observability/metrics.py): task-queue depths, "
             "model version, restart/recovery counters. -1 resolves "
             "from EDL_METRICS_PORT (unset = off), 0 = ephemeral.",
    )


def add_worker_params(parser):
    parser.add_argument("--worker_id", type=non_neg_int, required=True)
    parser.add_argument(
        "--master_addr", required=True, help="host:port of the master"
    )
    parser.add_argument(
        "--job_type", default="training_only",
        choices=[
            "training_only",
            "training_with_evaluation",
            "evaluation_only",
            "prediction_only",
        ],
    )
    parser.add_argument(
        "--num_minibatches_per_task", type=pos_int, default=8
    )
    parser.add_argument(
        "--coordinator_addr", default="",
        help="jax.distributed coordinator (multi-host SPMD)",
    )
    parser.add_argument(
        "--num_processes", type=non_neg_int, default=0,
        help="jax.distributed world size (multi-host SPMD)",
    )
    parser.add_argument(
        "--process_id", type=non_neg_int, default=0,
        help="jax.distributed process index",
    )


def parse_master_args(args=None):
    parser = argparse.ArgumentParser(description="ElasticDL-TPU master")
    add_common_params(parser)
    add_master_params(parser)
    parsed, unknown = parser.parse_known_args(args=args)
    if unknown:
        import warnings

        warnings.warn("Unknown master args: %s" % unknown)
    return parsed


def parse_worker_args(args=None):
    parser = argparse.ArgumentParser(description="ElasticDL-TPU worker")
    add_common_params(parser)
    add_worker_params(parser)
    parsed, unknown = parser.parse_known_args(args=args)
    if unknown:
        import warnings

        warnings.warn("Unknown worker args: %s" % unknown)
    return parsed


def build_arguments_from_parsed_result(args, filter_args=None):
    """Reconstruct the command-line list from a parsed namespace — how
    flags propagate master → worker pods (reference
    elasticdl_client/common/args.py:519-545)."""
    items = vars(args).items()
    if filter_args:
        items = [(k, v) for k, v in items if k not in filter_args]
    arguments = []
    for key, value in sorted(items):
        if value is None or value == "":
            continue
        if isinstance(value, bool):
            value = "true" if value else "false"
        arguments.extend(["--" + key, str(value)])
    return arguments


def wrap_args_with_string(arguments):
    """Shell-quote an argument list into one string (reference
    wrap_python_args_with_string, args.py:548-559)."""
    import shlex

    return " ".join(shlex.quote(a) for a in arguments)


def parse_resource_spec(spec):
    """'cpu=1,memory=4096Mi,google.com/tpu=8' → dict (reference
    common/k8s_resource.py parse)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out
