"""Model-zoo module loading and spec discovery by convention.

Parity with the reference's elasticdl/python/common/model_utils.py:139-198: a
model definition is a Python module that exports, by name,

    custom_model()    -> a flax.linen.Module (reference: a Keras model)
    loss              -> loss(labels, predictions) scalar
    optimizer         -> optimizer(**kwargs) returning an optax transform
    dataset_fn        -> dataset_fn(dataset, mode, metadata) -> dataset
    eval_metrics_fn   -> dict {metric_name: fn(labels, predictions)}

plus optionally `callbacks()`, `custom_data_reader`,
`prediction_outputs_processor`, and `feature_shapes()` (TPU addition: static
shapes so the train step compiles once).
"""

import importlib
import importlib.util
import os



def load_module(module_file):
    spec = importlib.util.spec_from_file_location(module_file, module_file)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def get_module_file_path(model_zoo, spec_key):
    """'<pkg>.<module>.<name>' -> '<model_zoo>/<pkg>/<module>.py'
    (reference model_utils.py `get_module_file_path`)."""
    return os.path.join(model_zoo, *spec_key.split(".")[:-1]) + ".py"


def get_dict_from_params_str(params_str):
    """Parse 'k1=v1; k2=v2' model/reader params with Python literal values
    (reference: common/model_utils.py:79-94)."""
    if not params_str:
        return {}
    out = {}
    for kv in params_str.split(";"):
        kv = kv.strip()
        if not kv:
            continue
        k, _, v = kv.partition("=")
        k, v = k.strip(), v.strip()
        try:
            out[k] = eval(v, {"__builtins__": {}}, {})
        except Exception:
            out[k] = v
    return out


def format_params_str(params):
    """Inverse of get_dict_from_params_str: render a dict as the
    'k1=v1; k2=v2' wire format, repr-ing values so strings survive the
    eval on the parse side."""
    return "; ".join("%s=%r" % (k, v) for k, v in params.items())


def _get_spec_value(spec_key, model_zoo, default_module, required=False):
    """Resolve a spec item either from the model-def module (bare name) or a
    separate module path 'a.b.name' under model_zoo
    (reference model_utils.py:113-137)."""
    if spec_key is None:
        return None
    if "." in spec_key:
        module_file = get_module_file_path(model_zoo, spec_key)
        module = load_module(module_file).__dict__
        name = spec_key.split(".")[-1]
    else:
        module = default_module
        name = spec_key
    value = module.get(name, None)
    if required and value is None:
        raise ValueError(
            "Missing required spec key %s in the module" % spec_key
        )
    return value


class ModelSpec(object):
    """Resolved model-zoo spec (reference get_model_spec returns a tuple;
    a named object is kinder to callers)."""

    def __init__(
        self,
        model_fn,
        dataset_fn,
        loss,
        optimizer,
        eval_metrics_fn,
        prediction_outputs_processor=None,
        custom_data_reader=None,
        callbacks_fn=None,
        feature_shapes=None,
        module=None,
        host_embeddings_fn=None,
    ):
        self.model_fn = model_fn
        self.dataset_fn = dataset_fn
        self.loss = loss
        self.optimizer = optimizer
        self.eval_metrics_fn = eval_metrics_fn
        self.prediction_outputs_processor = prediction_outputs_processor
        self.custom_data_reader = custom_data_reader
        self.callbacks_fn = callbacks_fn
        self.feature_shapes = feature_shapes
        self.module = module
        # Optional zoo convention `host_embeddings()` declaring host-DRAM
        # resident tables (embedding/host_bridge.build_manager_from_spec).
        self.host_embeddings_fn = host_embeddings_fn

    def create_model(self, model_params_str=""):
        kwargs = get_dict_from_params_str(model_params_str)
        return self.model_fn(**kwargs)


def get_model_spec(
    model_zoo,
    model_def,
    dataset_fn="dataset_fn",
    loss="loss",
    optimizer="optimizer",
    eval_metrics_fn="eval_metrics_fn",
    prediction_outputs_processor="PredictionOutputsProcessor",
    custom_data_reader="custom_data_reader",
    callbacks="callbacks",
):
    """Load the model-def module and resolve all spec items by convention
    (reference model_utils.py:139-198)."""
    module_file = get_module_file_path(model_zoo, model_def)
    module = load_module(module_file).__dict__
    model_name = model_def.split(".")[-1]
    model_fn = module.get(model_name, None)
    if model_fn is None:
        raise ValueError(
            "Cannot find the model function %s in %s"
            % (model_name, module_file)
        )
    pop = module.get(prediction_outputs_processor, None) if isinstance(
        prediction_outputs_processor, str
    ) else prediction_outputs_processor
    return ModelSpec(
        model_fn=model_fn,
        # dataset_fn may be omitted when the data reader provides a
        # schema-driven default (resolve_dataset_fn; reference
        # worker.py:194-205 falls back to reader.default_dataset_fn)
        dataset_fn=_get_spec_value(dataset_fn, model_zoo, module),
        loss=_get_spec_value(loss, model_zoo, module, required=True),
        optimizer=_get_spec_value(optimizer, model_zoo, module, required=True),
        eval_metrics_fn=_get_spec_value(
            eval_metrics_fn, model_zoo, module, required=True
        ),
        prediction_outputs_processor=pop,
        custom_data_reader=_get_spec_value(
            custom_data_reader, model_zoo, module
        ),
        callbacks_fn=module.get(callbacks, None),
        feature_shapes=module.get("feature_shapes", None),
        module=module,
        host_embeddings_fn=module.get("host_embeddings", None),
    )


def load_model_spec_from_module(module):
    """Build a ModelSpec from an already-imported module object (used by
    tests and the local executor)."""
    d = module.__dict__
    return ModelSpec(
        model_fn=d["custom_model"],
        dataset_fn=d.get("dataset_fn"),
        loss=d["loss"],
        optimizer=d["optimizer"],
        eval_metrics_fn=d["eval_metrics_fn"],
        prediction_outputs_processor=d.get("PredictionOutputsProcessor"),
        custom_data_reader=d.get("custom_data_reader"),
        callbacks_fn=d.get("callbacks"),
        feature_shapes=d.get("feature_shapes"),
        module=module,
        host_embeddings_fn=d.get("host_embeddings"),
    )


def resolve_dataset_fn(spec, reader):
    """spec.dataset_fn, else the reader's schema-driven default — a
    reader (e.g. data/reader/odps_reader.ODPSDataReader) may derive a
    dataset_fn from table metadata (reference worker.py:194-205 falls
    back to data_reader.default_dataset_fn()). Resolved once and cached
    on the spec so the returned closure is stable across tasks."""
    if spec.dataset_fn is None:
        default = getattr(reader, "default_dataset_fn", None)
        if default is None:
            raise ValueError(
                "dataset_fn is required if the data reader used does "
                "not provide a default implementation of dataset_fn"
            )
        spec.dataset_fn = default()
    return spec.dataset_fn
