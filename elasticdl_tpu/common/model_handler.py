"""Strategy-dependent model handling.

The reference's ModelHandler (common/model_handler.py:148-466) clones a Keras
model, swapping native ``tf.keras.layers.Embedding`` for the PS-backed
ElasticDL layer when a table exceeds 2 MB, and performs the inverse rewrite
(plus checkpoint-weight restore) at export time.

On TPU there is no separate "distributed layer" to swap in: the framework's
``elasticdl_tpu.embedding.Embedding`` IS both — whether a table replicates or
shards over the (ep, fsdp) mesh axes is a *sharding decision*, made by
parallel/sharding.infer_state_pspec with the same 2 MB threshold
(constants.EMBEDDING_PARTITION_THRESHOLD_BYTES). The handler therefore keeps
the reference's API surface (get_model_handler / get_model_to_train /
get_model_to_export) while its work reduces to: pass the model through, and
gather + export weights (optionally from the latest checkpoint) at the end.
"""

from elasticdl_tpu.common.constants import DistributionStrategy
from elasticdl_tpu.common.log_utils import default_logger as logger


class ModelHandler(object):
    @classmethod
    def get_model_handler(
        cls, distribution_strategy=None, checkpoint_dir=None
    ):
        """Strategy → handler (reference model_handler.py:155-176).
        PARAMETER_SERVER maps to the mesh handler: the PS data plane's TPU
        equivalent is sharded-HBM embeddings + XLA collectives."""
        if distribution_strategy in (
            DistributionStrategy.PARAMETER_SERVER,
            DistributionStrategy.MESH,
            DistributionStrategy.ALLREDUCE,
        ):
            return MeshModelHandler(checkpoint_dir=checkpoint_dir)
        return LocalModelHandler(checkpoint_dir=checkpoint_dir)

    def __init__(self, checkpoint_dir=None):
        self._checkpoint_dir = checkpoint_dir

    def get_model_to_train(self, model):
        """Identity: the framework's Embedding layer serves local AND
        distributed execution; sharding is decided at init (see module
        docstring). Kept for API parity with the reference's rewrite."""
        return model

    def get_model_to_export(self, model, state, export_dir,
                            host_manager=None):
        """Gather weights (preferring the latest checkpoint when one exists,
        as the reference does — model_handler.py:247-273) and write the
        export artifact. `host_manager` carries the host-resident tier
        into the artifact (the reference restored PS-resident embedding
        rows into the exported model — its rows lived on PS pods; ours
        live in the host store)."""
        from elasticdl_tpu.api import exporter
        from elasticdl_tpu.checkpoint import get_latest_checkpoint_version

        if (
            self._checkpoint_dir
            and get_latest_checkpoint_version(self._checkpoint_dir) >= 0
        ):
            logger.info(
                "Exporting from checkpoint dir %s", self._checkpoint_dir
            )
            return exporter.export_from_checkpoint(
                model, state, self._checkpoint_dir, export_dir,
                host_manager=host_manager,
            )
        return exporter.export_model(
            model, state, export_dir, host_manager=host_manager
        )


class LocalModelHandler(ModelHandler):
    """Single-host strategy (reference model_handler.py:179-204)."""


class MeshModelHandler(ModelHandler):
    """Mesh (PS-equivalent) strategy (reference
    ParameterServerModelHandler, model_handler.py:207-466).

    The reference's handler did two jobs: (1) swap oversized native
    embedding layers for PS-backed ones at train time, (2) invert the
    swap + restore PS rows at export. On TPU, (1) is a sharding/tier
    decision the Embedding layer + infer_state_pspec make from the same
    2 MB threshold, and (2) is the host_manager plumbing in
    get_model_to_export. What remains strategy-specific is validation:
    the mesh path must refuse an export artifact that silently drops a
    distributed tier (sharded params that failed to gather, host tables
    missing from the payload)."""

    def get_model_to_export(self, model, state, export_dir,
                            host_manager=None):
        out = super().get_model_to_export(
            model, state, export_dir, host_manager=host_manager
        )
        self._validate_export(state, export_dir, host_manager)
        return out

    def _validate_export(self, state, export_dir, host_manager):
        import jax

        from elasticdl_tpu.api.exporter import load_exported

        if jax.process_index() != 0:
            # only process 0 writes the artifact; other processes may not
            # even share its filesystem
            return
        payload, _ = load_exported(export_dir)
        n_state = len(jax.tree.leaves(state.params))
        n_export = len(jax.tree.leaves(payload["params"]))
        if n_export != n_state:
            raise RuntimeError(
                "export dropped parameters: %d leaves exported, state "
                "has %d" % (n_export, n_state)
            )
        if host_manager:
            exported = set(payload.get("host_embeddings") or {})
            expected = set(host_manager.tables())
            if exported != expected:
                raise RuntimeError(
                    "export host-table mismatch: artifact has %s, "
                    "manager has %s"
                    % (sorted(exported), sorted(expected))
                )
