"""Bounded-retry layer for worker->master control-plane RPCs.

The reference had no master-outage story at all: worker/worker.py treated
any UNAVAILABLE/CANCELLED from an ever-connected master as "end of job"
and exited silently mid-epoch. This module replaces that heuristic with
explicit policy: per-RPC deadlines, exponential backoff with full jitter
(AWS-style: sleep = uniform(0, min(cap, base * 2**attempt))), and a
bounded reconnect window after which the caller gets the real error back
(so a genuinely dead master fails the worker loudly instead of hanging it
forever).

Transport-agnostic: `retry_call` retries any callable whose failures
satisfy `is_retryable`; gRPC specifics (which status codes are transient)
live in `is_transient_rpc_error` so the in-process servicer path and unit
tests can inject plain exceptions.
"""

import random
import time

from elasticdl_tpu.common.log_utils import default_logger as logger

# hoisted out of the classification hot path: is_transient_rpc_error
# runs on EVERY failed call in a retry loop, and the router's dispatch
# loop classifies per attempt — a per-call import is measurable there
try:
    import grpc as _grpc
except Exception:  # pragma: no cover - grpc is in the image
    _grpc = None


class RetryPolicy(object):
    """Backoff/deadline knobs for one class of RPCs.

    reconnect_window_secs bounds the TOTAL time spent retrying one logical
    call: a master restart (pod reschedule + journal replay) fits well
    inside the default 120 s; anything longer is treated as a real outage.
    """

    def __init__(
        self,
        rpc_timeout_secs=30.0,
        base_delay_secs=0.1,
        max_delay_secs=5.0,
        reconnect_window_secs=120.0,
    ):
        self.rpc_timeout_secs = rpc_timeout_secs
        self.base_delay_secs = base_delay_secs
        self.max_delay_secs = max_delay_secs
        self.reconnect_window_secs = reconnect_window_secs

    def backoff(self, attempt):
        """Full-jitter exponential backoff delay for `attempt` (0-based)."""
        cap = min(
            self.max_delay_secs, self.base_delay_secs * (2 ** attempt)
        )
        return random.uniform(0, cap)


def is_transient_rpc_error(exc):
    """True for gRPC statuses a server restart produces: the socket is
    gone (UNAVAILABLE), in-flight calls were torn down (CANCELLED), or
    a call outlived its deadline while the server replayed its journal
    (DEADLINE_EXCEEDED). Deliberately does NOT include
    RESOURCE_EXHAUSTED — that is backpressure from a LIVE server
    (`is_backpressure_rpc_error`): also retryable with backoff, but it
    should steer the retry toward capacity elsewhere (the router
    re-routes instead of counting it against the replica's breaker)."""
    if _grpc is None:  # pragma: no cover
        return False
    try:
        return isinstance(exc, _grpc.RpcError) and exc.code() in (
            _grpc.StatusCode.UNAVAILABLE,
            _grpc.StatusCode.CANCELLED,
            _grpc.StatusCode.DEADLINE_EXCEEDED,
        )
    except Exception:
        return False


def is_backpressure_rpc_error(exc):
    """True for RESOURCE_EXHAUSTED: the server is alive but shedding
    load (bounded admission queue full / shutdown drain). Retryable
    with backoff, and the signal to try a DIFFERENT replica — the
    server itself is healthy, its capacity is what's gone."""
    if _grpc is None:  # pragma: no cover
        return False
    try:
        return (
            isinstance(exc, _grpc.RpcError)
            and exc.code() == _grpc.StatusCode.RESOURCE_EXHAUSTED
        )
    except Exception:
        return False


def is_retryable_rpc_error(exc):
    """Transient OR backpressure: the union a multi-replica dispatcher
    retries (single-target callers keep is_transient_rpc_error — with
    one server, retrying into a full queue is just more load)."""
    return is_transient_rpc_error(exc) or is_backpressure_rpc_error(exc)


def retry_call(
    fn,
    policy=None,
    is_retryable=is_transient_rpc_error,
    on_retry=None,
    sleep=time.sleep,
    clock=time.monotonic,
    what="rpc",
):
    """Call `fn()` with bounded retries.

    Retries only failures `is_retryable` accepts, sleeping
    `policy.backoff(attempt)` between attempts, until
    `policy.reconnect_window_secs` has elapsed — then the last error
    propagates. `on_retry(attempt, exc)` fires before each sleep (the
    worker uses it to count rpc_retries and trigger re-registration).
    Returns (result, attempts_used)."""
    policy = policy or RetryPolicy()
    deadline = clock() + policy.reconnect_window_secs
    attempt = 0
    while True:
        try:
            return fn(), attempt
        except Exception as e:
            if not is_retryable(e):
                raise
            now = clock()
            if now >= deadline:
                logger.error(
                    "%s still failing after %d retries over %.0fs "
                    "reconnect window; giving up",
                    what, attempt, policy.reconnect_window_secs,
                )
                raise
            delay = policy.backoff(attempt)
            # never sleep past the window: the last attempt should land
            # just inside it, not arbitrarily later
            delay = min(delay, max(0.0, deadline - now))
            if on_retry is not None:
                on_retry(attempt, e)
            logger.warning(
                "%s failed (attempt %d, transient): retrying in %.2fs",
                what, attempt + 1, delay,
            )
            sleep(delay)
            attempt += 1
