"""Make the JAX_PLATFORMS env var mean what users think it means.

An ambient accelerator plugin (e.g. a tunneled PJRT plugin) can
force-set `jax_platforms` at `import jax`, silently overriding the
JAX_PLATFORMS environment variable — so `JAX_PLATFORMS=cpu
elasticdl-tpu train ...` would still route compute at the (possibly
unreachable) accelerator and hang. The config knob applied after
import wins over the plugin's import-time override; every process
entry point (client CLI, master, worker, LocalExecutor) calls this
before its first device use."""

import os


def honor_jax_platforms_env():
    """Re-apply JAX_PLATFORMS over any plugin's import-time override.
    No-op when the variable is unset (the ambient default — usually
    the accelerator — stays in charge). Safe to call repeatedly;
    must run before the first backend use to take effect."""
    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
