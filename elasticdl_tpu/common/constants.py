"""Framework-wide constants.

Parity with the reference's ``elasticdl/python/common/constants.py`` plus the
TPU-specific knobs this framework adds (mesh axis names, record format magic).
"""


class Mode(object):
    """Job modes (reference: common/constants.py `Mode`)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


class TaskExecCounterKey(object):
    FAIL_COUNT = "fail_count"


class GRPC(object):
    """Control-plane gRPC caps (reference: common/constants.py `GRPC`,
    go/pkg/ps/server.go:31-34 — 256 MB caps). The data plane in this framework
    never rides gRPC, so these only bound control messages (eval outputs,
    checkpoint metadata)."""

    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024


class WorkerEnv(object):
    MASTER_ADDR = "EDL_TPU_MASTER_ADDR"
    WORKER_ID = "EDL_TPU_WORKER_ID"
    WORKER_NUM = "EDL_TPU_WORKER_NUM"


class JobType(object):
    TRAINING_ONLY = "training_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"


class DistributionStrategy(object):
    """Distribution strategies.

    The reference supports LOCAL / PARAMETER_SERVER / ALLREDUCE
    (elasticdl_client/common/constants.py). On TPU the parameter-server data
    plane is subsumed by sharded-HBM embeddings + XLA collectives, so
    PARAMETER_SERVER is accepted as an alias for MESH (sharded embedding +
    allreduce dense) to keep CLI parity.
    """

    LOCAL = "Local"
    ALLREDUCE = "AllreduceStrategy"
    PARAMETER_SERVER = "ParameterServerStrategy"
    MESH = "MeshStrategy"


class MeshAxis(object):
    """Canonical mesh axis names, in order.

    dp    data parallel (batch)
    fsdp  fully-sharded data parallel (params/opt-state sharding over dp axis)
    ep    expert / embedding-shard axis (sparse tables are sharded over it)
    tp    tensor parallel
    sp    sequence / context parallel (ring attention)
    pp    pipeline parallel (layer stages, parallel/pipeline.py)
    """

    DP = "dp"
    FSDP = "fsdp"
    EP = "ep"
    TP = "tp"
    SP = "sp"
    PP = "pp"
    ALL = (DP, FSDP, EP, TP, SP, PP)


# Max retries for a dispatched task before the job fails
# (reference: master/task_dispatcher.py:27 `_MAX_TASK_RETRIES = 3`).
MAX_TASK_RETRIES = 3

# Max retries of a single minibatch on the worker
# (reference: worker/worker.py:62 `# The default maximum number of a minibatch retry ... 64`).
MAX_MINIBATCH_RETRY_NUM = 64

# Embedding tables at least this big are sharded over (ep, fsdp); smaller
# ones follow the plain auto rule (reference: the 2 MB cutoff below which an
# embedding layer stays native instead of moving to the PS —
# common/model_handler.py:98-102).
EMBEDDING_PARTITION_THRESHOLD_BYTES = 2 * 1024 * 1024

# Default number of records per dispatched task
# (reference: elasticdl_client/common/args.py `--records_per_task` default).
DEFAULT_RECORDS_PER_TASK = 64


class ReaderType(object):
    RECORDIO = "RecordIO"
    CSV = "CSV"
    TEXT = "Text"
    ODPS = "ODPS"


class SaveModelConfig(object):
    SAVED_MODEL_PATH = "saved_model_path"
