"""Per-phase wall-clock accumulation (reference: common/timing_utils.py:16-56).

Keeps the reference's phase taxonomy {task_process, batch_process, get_model,
report_gradient} and adds TPU phases {compile, device_put, step}.
"""

import time
from contextlib import contextmanager


class Timing(object):
    def __init__(self, enabled=True, logger=None):
        self._enabled = enabled
        self._logger = logger
        self.reset()

    def reset(self):
        self._start = {}
        self.totals = {}
        self.counts = {}

    def start_record_time(self, phase):
        if self._enabled:
            self._start[phase] = time.time()

    def end_record_time(self, phase):
        if self._enabled and phase in self._start:
            dt = time.time() - self._start.pop(phase)
            self.totals[phase] = self.totals.get(phase, 0.0) + dt
            self.counts[phase] = self.counts.get(phase, 0) + 1

    @contextmanager
    def record(self, phase):
        self.start_record_time(phase)
        try:
            yield
        finally:
            self.end_record_time(phase)

    def report_timing(self, reset=False):
        if self._enabled and self._logger:
            for phase, total in sorted(self.totals.items()):
                self._logger.debug(
                    "Timing %s: total=%.3fs count=%d avg=%.1fms",
                    phase,
                    total,
                    self.counts[phase],
                    1000.0 * total / max(1, self.counts[phase]),
                )
        if reset:
            self.reset()


def fetch_sync(tree):
    """Fetch one scalar that depends on `tree`'s first leaf — the only
    trustworthy device sync over tunneled PJRT plugins, where
    block_until_ready can return before execution finishes (observed
    reading >10 TB/s effective HBM on small ops). Shared by bench.py and
    the scripts/bench_* microbenchmarks so the workaround lives once.

    Assumes ONE jit executable produced the whole tree: fetching the
    first leaf is a barrier only because a single executable's output
    buffers complete together. Timing a multi-executable region (e.g.
    host-spill callbacks or separate sparse updates) needs one fetched
    scalar per distinct executable output, or it under-reports."""
    import jax
    import numpy as np

    leaf = jax.tree.leaves(tree)[0]
    return float(np.asarray(jax.device_get(leaf.reshape(-1)[0])))
