"""Minimal TensorBoard event-file writer, dependency-free.

TensorFlow isn't part of the TPU image, but TensorBoard's on-disk format
is just TFRecord-framed Event protos — small enough to hand-encode:
protobuf wire format for Event/Summary/Value (scalars only) plus the
masked-CRC32C record framing. Files written here load in stock
TensorBoard.

Used by master/tensorboard_service.py (the reference wrote summaries via
tf.summary — tensorboard_service.py:41-49)."""

import os
import struct
import time

# ------------------------------------------------------------- crc32c

_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for n in range(256):
        crc = n
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data):
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------- protobuf encode


def _varint(n):
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _len_delimited(field_num, payload):
    return _varint((field_num << 3) | 2) + _varint(len(payload)) + payload


def _double_field(field_num, value):
    return _varint((field_num << 3) | 1) + struct.pack("<d", value)


def _float_field(field_num, value):
    return _varint((field_num << 3) | 5) + struct.pack("<f", value)


def _int64_field(field_num, value):
    return _varint(field_num << 3) + _varint(value & (2**64 - 1))


def encode_scalar_event(tag, value, step, wall_time=None):
    """Event{wall_time, step, summary{value{tag, simple_value}}}"""
    summary_value = _len_delimited(1, tag.encode("utf-8")) + _float_field(
        2, float(value)
    )
    summary = _len_delimited(1, summary_value)
    event = (
        _double_field(1, wall_time if wall_time is not None else time.time())
        + _int64_field(2, int(step))
        + _len_delimited(5, summary)
    )
    return event


def encode_file_version_event(wall_time=None):
    """The header event every event file starts with."""
    event = _double_field(
        1, wall_time if wall_time is not None else time.time()
    ) + _len_delimited(3, b"brain.Event:2")
    return event


# -------------------------------------------------------- record frame


def frame_record(payload):
    """TFRecord framing: len(u64le) + masked_crc(len) + data +
    masked_crc(data)."""
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


class EventFileWriter(object):
    """Append scalar events to one `events.out.tfevents.*` file."""

    def __init__(self, log_dir, filename_suffix=""):
        os.makedirs(log_dir, exist_ok=True)
        name = "events.out.tfevents.%d.%s%s" % (
            int(time.time()), os.uname().nodename, filename_suffix
        )
        self.path = os.path.join(log_dir, name)
        self._file = open(self.path, "ab")
        self._file.write(frame_record(encode_file_version_event()))
        self._file.flush()

    def add_scalar(self, tag, value, step):
        self._file.write(
            frame_record(encode_scalar_event(tag, value, step))
        )
        self._file.flush()

    def close(self):
        self._file.close()
