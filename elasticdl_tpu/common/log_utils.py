"""Structured logger factory (reference: elasticdl/python/common/log_utils.py)."""

import logging
import sys

_DEFAULT_FMT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
)

_default_level = logging.INFO


def set_default_level(level):
    global _default_level
    _default_level = level


def get_logger(name, level=None, fmt=_DEFAULT_FMT):
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(fmt))
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel(level if level is not None else _default_level)
    return logger


default_logger = get_logger("elasticdl_tpu")
