"""Config/env-driven fault injection at the master's RPC boundary.

Drill tests need to manufacture exactly the failures the fault-tolerance
layer claims to survive — without patching internals. This module injects
them at the servicer boundary (both the in-process servicer path unit
tests use and the real gRPC server), and can SIGKILL the master process
itself for crash-recovery drills in local mode.

Spec grammar (EDL_FAULT_SPEC env var or the FaultInjector constructor),
semicolon-separated rules:

    <rpc>:<action>[:<count>[:<k>=<v>,...]]

    rpc     RPC/hook name (get_task, report_task_result, worker_launch,
            local_get_task, ...) or * for any
    action  drop   reject BEFORE the handler runs (request lost)
            error  run the handler, then reject (response lost — the
                   duplicate-side-effect case, e.g. a task report that
                   was applied but never acknowledged)
            delay  sleep secs=... then proceed
            kill   SIGKILL the current process (crash drill)
    count   how many calls the rule fires on (default 1; * = forever)
    kwargs  secs=<float> (delay), skip=<int> (let N calls through
            first), code=<grpc status name> (default UNAVAILABLE)

Examples:
    get_task:drop:3                three lost get_task requests
    report_task_result:error:1     one applied-but-unacked report
    get_task:kill:1:skip=5         master dies on its 6th get_task
    worker_launch:delay:*:secs=2   every worker launch takes +2 s
"""

import os
import signal
import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as logger

FAULT_SPEC_ENV = "EDL_FAULT_SPEC"

try:
    import grpc as _grpc
except Exception:  # pragma: no cover - grpc is in the image
    _grpc = None


if _grpc is not None:

    class InjectedRpcError(_grpc.RpcError):
        """Raised on the in-process servicer path; carries a status code
        like a real transport error so common/retry.py classifies it
        identically."""

        def __init__(self, code, details):
            super().__init__()
            self._code = code
            self._details = details

        def code(self):
            return self._code

        def details(self):
            return self._details

        def __str__(self):
            return "InjectedRpcError(%s, %r)" % (self._code, self._details)

else:  # pragma: no cover

    class InjectedRpcError(Exception):
        def __init__(self, code, details):
            super().__init__(details)
            self._code = code

        def code(self):
            return self._code


def _status_code(name):
    if _grpc is None:  # pragma: no cover
        return name
    return getattr(_grpc.StatusCode, name, _grpc.StatusCode.UNAVAILABLE)


class FaultRule(object):
    def __init__(self, rpc, action, count=1, skip=0, secs=0.0,
                 code="UNAVAILABLE"):
        if action not in ("drop", "error", "delay", "kill"):
            raise ValueError("unknown fault action %r" % action)
        self.rpc = rpc
        self.action = action
        self.count = count  # None = forever
        self.skip = skip
        self.secs = secs
        self.code = code
        self._seen = 0
        self._fired = 0

    def matches(self, rpc_name):
        return self.rpc in ("*", rpc_name)

    def consume(self):
        """One call against this rule; True if the fault fires."""
        self._seen += 1
        if self._seen <= self.skip:
            return False
        if self.count is not None and self._fired >= self.count:
            return False
        self._fired += 1
        return True

    @classmethod
    def parse(cls, text):
        parts = text.strip().split(":")
        if len(parts) < 2:
            raise ValueError("bad fault rule %r" % text)
        rpc, action = parts[0], parts[1]
        count = 1
        kwargs = {}
        if len(parts) > 2 and parts[2]:
            count = None if parts[2] == "*" else int(parts[2])
        if len(parts) > 3 and parts[3]:
            for kv in parts[3].split(","):
                k, _, v = kv.partition("=")
                if k == "secs":
                    kwargs["secs"] = float(v)
                elif k == "skip":
                    kwargs["skip"] = int(v)
                elif k == "code":
                    kwargs["code"] = v
                else:
                    raise ValueError("bad fault kwarg %r in %r" % (kv, text))
        return cls(rpc, action, count=count, **kwargs)


class FaultInjector(object):
    """Holds the active rules; `intercept` is the single choke point.

    Thread-safe: the gRPC thread pool calls intercept concurrently.
    """

    def __init__(self, spec="", rules=None, kill_fn=None):
        self._lock = threading.Lock()
        self.rules = list(rules or [])
        if spec:
            self.rules.extend(
                FaultRule.parse(r) for r in spec.split(";") if r.strip()
            )
        self.injected = {}  # rpc_name -> fired-fault count
        self._kill_fn = kill_fn or (
            lambda: os.kill(os.getpid(), signal.SIGKILL)
        )

    @classmethod
    def from_env(cls, env=None):
        """Injector from EDL_FAULT_SPEC, or None when unset (the
        zero-overhead production default)."""
        spec = (env or os.environ).get(FAULT_SPEC_ENV, "")
        return cls(spec=spec) if spec else None

    def _fire(self, rpc_name, when):
        with self._lock:
            for rule in self.rules:
                if not rule.matches(rpc_name):
                    continue
                # drop rejects pre-handler, error rejects post-handler;
                # delay/kill apply pre-handler
                pre = rule.action in ("drop", "delay", "kill")
                if (when == "before") != pre:
                    continue
                if rule.consume():
                    self.injected[rpc_name] = (
                        self.injected.get(rpc_name, 0) + 1
                    )
                    return rule
        return None

    def _trace_fault(self, rpc_name, rule, trace_id):
        """Mark the injected fault in the distributed trace: an
        instantaneous `fault_injected` span on the request's OWN trace
        when the RPC carried context (chaos drills then show the
        injected drop/delay as a causal node inside the request tree),
        on a fresh trace otherwise. Best-effort by design."""
        try:
            from elasticdl_tpu.observability.tracing import recorder

            recorder().start_span(
                "fault_injected", trace_id=trace_id or None,
                rpc=rpc_name, action=rule.action,
            ).finish("injected")
            if rule.action == "kill":
                # last chance to get the ring to disk: SIGKILL skips
                # every atexit/stop path
                recorder().flush()
        except Exception:  # pragma: no cover - never block the fault
            pass

    def intercept(self, rpc_name, context=None, when="before",
                  trace_id=""):
        """Apply the first matching armed rule. Raises (or aborts the
        gRPC context) for drop/error, sleeps for delay, SIGKILLs the
        process for kill, no-ops when nothing matches."""
        rule = self._fire(rpc_name, when)
        if rule is None:
            return
        self._trace_fault(rpc_name, rule, trace_id)
        if rule.action == "delay":
            logger.warning(
                "[fault] delaying %s by %.2fs", rpc_name, rule.secs
            )
            time.sleep(rule.secs)
            return
        if rule.action == "kill":
            logger.warning("[fault] SIGKILL self on %s", rpc_name)
            self._kill_fn()
            return
        logger.warning(
            "[fault] %s %s (%s)", rule.action, rpc_name, rule.code
        )
        code = _status_code(rule.code)
        details = "injected fault: %s %s" % (rule.action, rpc_name)
        if context is not None:
            context.abort(code, details)
        raise InjectedRpcError(code, details)


# RPCs the servicer wrapper intercepts (mirrors proto/service.py's table).
_SERVICER_RPCS = (
    "get_task",
    "report_task_result",
    "report_evaluation_metrics",
    "report_version",
    "register_worker",
)

# The routing tier's RPC surface (proto/service.py Router table). Names
# are distinct from the replica surface, so a spec like
# `router_generate:drop:1` fires at the router boundary and NEVER at a
# replica servicer — and vice versa.
ROUTER_RPCS = (
    "router_generate",
    "router_generate_stream",
    "router_status",
)

# The serving front-end's RPC surface (proto/service.py Serving +
# Router tables); serving processes wrap their servicers with this one
# tuple so overload and kill drills target the same choke point the
# master drills use. A servicer only exposes its own subset — the
# wrapper skips names it doesn't have — so one spec grammar covers both
# boundaries without cross-firing.
SERVING_RPCS = (
    "generate",
    "generate_stream",
    "server_status",
    # disaggregated prefill/decode handoff surface (serving/disagg.py):
    # the three transfer RPCs wrap on whichever servicer exposes them;
    # disagg_handoff is an intercept HOOK the router consults directly
    # before starting a transfer (the handoff is router-initiated — no
    # inbound RPC exists for the wrapper to see), so a drill can force
    # the fallback path with both replicas healthy
    "export_chain",
    "transfer_chain",
    "abort_transfer",
    "disagg_handoff",
    # explicit checkpoint swap (rollout controller handshake) plus the
    # checkpoint_read intercept HOOK the hot-reload watcher consults
    # before every filesystem read — a drill can manufacture a torn or
    # glacially slow checkpoint store without touching disk:
    #   checkpoint_read:error:*         every reload attempt fails
    #   checkpoint_read:delay:1:secs=5  one slow shard read
    "reload_checkpoint",
    "checkpoint_read",
) + ROUTER_RPCS

# The replica supervisor/autoscaler's process boundary
# (serving/autoscaler.py). These are intercept HOOKS like the master's
# worker_launch/worker_exit, not servicer methods: the supervisor calls
# intercept() directly at each lifecycle step, so a spec can
# manufacture exactly the failures its restart/backoff/circuit
# machinery claims to survive —
#   supervisor_spawn:drop:1          one spawn fails outright
#   supervisor_ready:delay:*:secs=2  every replica is slow to ready
#   supervisor_adopt:drop:1          one adoption is dropped (the seat
#                                    is reaped and respawned)
SUPERVISOR_RPCS = (
    "supervisor_spawn",
    "supervisor_ready",
    "supervisor_adopt",
    # the fleet rollout controller (serving/rollout.py), same direct
    # intercept() style: rollout_swap fires before each replica's
    # reload_checkpoint dispatch, rollout_judge before each canary
    # judgment evaluation —
    #   rollout_swap:kill:1:skip=1   the controller dies mid-wave (the
    #                                rollout drill's journal-resume
    #                                phase: a fresh controller must
    #                                finish the rollout with no
    #                                double-swap)
    #   rollout_swap:delay:*:secs=2  every swap is slow
    #   rollout_judge:drop:1         one judgment evaluation is skipped
    #                                (the timeout fail-safe path: no
    #                                verdict => no promotion)
    "rollout_swap",
    "rollout_judge",
)

# The multi-cell router tier's process boundary (serving/router_cell.py
# + router_main --cells). Direct intercept() hooks like the supervisor
# tuple: the cell supervisor intercepts `cell_spawn` per cell launch
# and each cell intercepts `cell_kill` at its heartbeat tick, so a
# chaos spec can SIGKILL a live router cell mid-load —
#   cell_kill:kill:1:skip=4    the cell dies on its 5th heartbeat (the
#                              router-kill drill phase: in-flight
#                              accepted requests must re-dispatch
#                              through a surviving cell)
#   cell_spawn:drop:1          one cell launch fails outright
CELL_HOOKS = (
    "cell_spawn",
    "cell_kill",
)

# The runtime-health plane's intercept hooks
# (observability/runtime_health.py + serving/server.py). Like the
# supervisor hooks these are direct intercept() call sites, not
# servicer methods — a spec manufactures exactly the failures the
# health plane claims to observe:
#   engine_step:delay:1:secs=600,skip=5   the scheduler wedges on its
#                                         6th decode tick (the stall
#                                         drill's injected stall: work
#                                         stays seated, tokens stop)
#   health_leak:drop:1                    the health thread leaks one
#                                         device buffer the byte
#                                         ledger cannot name — the
#                                         memory accountant must
#                                         convict it
HEALTH_RPCS = (
    "engine_step",
    "health_leak",
)


class FaultInjectingServicer(object):
    """Transparent servicer wrapper: same RPC surface, with
    injector.intercept applied before and after each handler. Non-RPC
    attributes (get_model_version, watchdog helpers, ...) proxy through
    so Master/EvaluationService wiring is unaffected. `rpcs` selects the
    intercepted surface (default: the Master table; serving processes
    pass SERVING_RPCS); names the servicer doesn't implement are
    skipped, so the replica server and the router share one tuple."""

    def __init__(self, servicer, injector, rpcs=_SERVICER_RPCS):
        self._servicer = servicer
        self._injector = injector
        for name in rpcs:
            if hasattr(servicer, name):
                setattr(self, name, self._wrap(name))

    def _wrap(self, name):
        handler = getattr(self._servicer, name)

        def rpc(request, _context=None):
            # requests carrying trace context get their injected
            # faults annotated INSIDE their own span tree
            trace_id = getattr(request, "trace_id", "")
            self._injector.intercept(name, context=_context,
                                     when="before", trace_id=trace_id)
            response = handler(request, _context)
            self._injector.intercept(name, context=_context,
                                     when="after", trace_id=trace_id)
            return response

        rpc.__name__ = name
        return rpc

    def __getattr__(self, name):
        return getattr(self._servicer, name)


def maybe_wrap_servicer(servicer, injector=None, rpcs=_SERVICER_RPCS):
    """Wrap when an injector is active (explicit or via EDL_FAULT_SPEC);
    otherwise return the servicer untouched."""
    injector = injector or FaultInjector.from_env()
    if injector is None or not injector.rules:
        return servicer
    logger.warning(
        "Fault injection ACTIVE on servicer %s: %s",
        type(servicer).__name__,
        [(r.rpc, r.action, r.count) for r in injector.rules],
    )
    return FaultInjectingServicer(servicer, injector, rpcs=rpcs)
