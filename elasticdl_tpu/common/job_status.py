"""Job status as a file — the local-master twin of the reference's
"master pod labels carry job status" contract (common/k8s_client.py
update_master_label; the CLI job monitor and scripts/
validate_job_status.py poll it). Phases mirror pod phases so the same
validator logic covers both the k8s and the no-cluster path.
"""

import json
import os
import tempfile
import time

PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

PHASES = (PENDING, RUNNING, SUCCEEDED, FAILED)
TERMINAL = (SUCCEEDED, FAILED)


def write_job_status(path, status, **extra):
    """Atomically write {"status": ..., "time": ..., **extra}. IO errors
    are swallowed (returning False): status reporting is best-effort and
    must never mask the actual job outcome — in particular not inside
    the master's failure handler, where an OSError here would replace
    the real traceback. Unknown phases still raise (caller bug)."""
    if not path:
        return False
    if status not in PHASES:
        raise ValueError("unknown job status %r (valid: %s)"
                         % (status, PHASES))
    payload = dict(extra, status=status, time=time.time())
    tmp = None
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=".job_status.")
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        tmp = None
        return True
    except OSError:
        import logging

        logging.getLogger(__name__).warning(
            "failed to write job status %r to %s", status, path,
            exc_info=True,
        )
        return False
    finally:
        if tmp is not None and os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def read_job_status(path):
    """The parsed status dict, or None when absent/partially written."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
