"""Host-DRAM embedding store: ctypes bindings over
elasticdl_tpu/native/host_embedding.cc, with a numpy fallback when the
shared object hasn't been built (`make -C elasticdl_tpu/native`).

This is the host-spill tier of the sparse embedding engine: tables too
large for HBM keep their rows here (the role PS pod RAM played in the
reference — ps/embedding_table.py / go/pkg/common/embedding_table.go),
with lazy deterministic row init and the sparse optimizer kernel family
applied host-side (go/pkg/kernel/capi/kernel_api.cc)."""

import ctypes
import os
import threading

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "libhostembedding.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        c_i64 = ctypes.c_int64
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_i64p = ctypes.POINTER(ctypes.c_int64)
        lib.host_embedding_new.restype = ctypes.c_void_p
        lib.host_embedding_new.argtypes = [
            c_i64, ctypes.c_uint64, ctypes.c_float, ctypes.c_float,
        ]
        lib.host_embedding_free.argtypes = [ctypes.c_void_p]
        lib.host_embedding_dim.restype = c_i64
        lib.host_embedding_dim.argtypes = [ctypes.c_void_p]
        lib.host_embedding_size.restype = c_i64
        lib.host_embedding_size.argtypes = [ctypes.c_void_p]
        lib.host_embedding_clear.argtypes = [ctypes.c_void_p]
        lib.host_embedding_lookup.argtypes = [
            ctypes.c_void_p, c_i64p, c_i64, c_f32p,
        ]
        lib.host_embedding_set.argtypes = [
            ctypes.c_void_p, c_i64p, c_i64, c_f32p,
        ]
        lib.host_embedding_export.restype = c_i64
        lib.host_embedding_export.argtypes = [
            ctypes.c_void_p, c_i64p, c_f32p, c_i64,
        ]
        lib.host_embedding_sgd.argtypes = [
            ctypes.c_void_p, c_i64p, c_f32p, c_i64, ctypes.c_float,
        ]
        lib.host_embedding_momentum.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, c_i64p, c_f32p, c_i64,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
        ]
        lib.host_embedding_adam.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, c_i64p,
            c_f32p, c_i64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, c_i64,
        ]
        lib.host_embedding_adagrad.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, c_i64p, c_f32p, c_i64,
            ctypes.c_float, ctypes.c_float,
        ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


def _as_ids(ids):
    return np.ascontiguousarray(ids, dtype=np.int64)


def _as_rows(values, dim):
    out = np.ascontiguousarray(values, dtype=np.float32)
    return out.reshape(-1, dim)


class _NativeStore(object):
    def __init__(self, dim, seed, init_low, init_high):
        self._lib = _load()
        self._handle = self._lib.host_embedding_new(
            dim, seed, init_low, init_high
        )
        self.dim = dim

    def __del__(self):
        if getattr(self, "_handle", None) and _LIB is not None:
            self._lib.host_embedding_free(self._handle)
            self._handle = None

    @staticmethod
    def _ptr(arr, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    def lookup(self, ids):
        ids = _as_ids(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        self._lib.host_embedding_lookup(
            self._handle,
            self._ptr(ids, ctypes.c_int64),
            len(ids),
            self._ptr(out, ctypes.c_float),
        )
        return out

    def set_rows(self, ids, values):
        ids = _as_ids(ids)
        values = _as_rows(values, self.dim)
        self._lib.host_embedding_set(
            self._handle,
            self._ptr(ids, ctypes.c_int64),
            len(ids),
            self._ptr(values, ctypes.c_float),
        )

    def __len__(self):
        return int(self._lib.host_embedding_size(self._handle))

    def clear(self):
        self._lib.host_embedding_clear(self._handle)

    def export_rows(self):
        n = len(self)
        ids = np.empty((n,), np.int64)
        values = np.empty((n, self.dim), np.float32)
        written = 0
        if n:
            written = self._lib.host_embedding_export(
                self._handle,
                self._ptr(ids, ctypes.c_int64),
                self._ptr(values, ctypes.c_float),
                n,
            )
        return ids[:written], values[:written]

    def sgd(self, ids, grads, lr):
        ids = _as_ids(ids)
        grads = _as_rows(grads, self.dim)
        self._lib.host_embedding_sgd(
            self._handle, self._ptr(ids, ctypes.c_int64),
            self._ptr(grads, ctypes.c_float), len(ids), lr,
        )

    def momentum(self, vel, ids, grads, lr, mu=0.9, nesterov=False):
        ids = _as_ids(ids)
        grads = _as_rows(grads, self.dim)
        self._lib.host_embedding_momentum(
            self._handle, vel._handle, self._ptr(ids, ctypes.c_int64),
            self._ptr(grads, ctypes.c_float), len(ids), lr, mu,
            1 if nesterov else 0,
        )

    def adam(self, m, v, ids, grads, lr, beta1=0.9, beta2=0.999,
             eps=1e-8, step=1):
        ids = _as_ids(ids)
        grads = _as_rows(grads, self.dim)
        self._lib.host_embedding_adam(
            self._handle, m._handle, v._handle,
            self._ptr(ids, ctypes.c_int64),
            self._ptr(grads, ctypes.c_float), len(ids),
            lr, beta1, beta2, eps, step,
        )

    def adagrad(self, accum, ids, grads, lr, eps=1e-10):
        ids = _as_ids(ids)
        grads = _as_rows(grads, self.dim)
        self._lib.host_embedding_adagrad(
            self._handle, accum._handle,
            self._ptr(ids, ctypes.c_int64),
            self._ptr(grads, ctypes.c_float), len(ids), lr, eps,
        )


_MASK64 = (1 << 64) - 1


def _splitmix64_row(seed, row_id, dim, low, high):
    """Identical algorithm to the C++ store's init_row (splitmix64 over
    seed ^ id*golden), so both backends initialize the same row."""
    state = (seed ^ ((row_id * 0x9E3779B97F4A7C15) & _MASK64)) & _MASK64
    out = np.empty((dim,), np.float32)
    span = high - low
    for i in range(dim):
        state = (state + 0x9E3779B97F4A7C15) & _MASK64
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
        z = z ^ (z >> 31)
        frac = (z >> 11) * (1.0 / 9007199254740992.0)
        out[i] = low + np.float32(frac) * span
    return out


class _PythonStore(object):
    """Same semantics in numpy (lazy deterministic init, sparse
    updates); the no-native fallback."""

    def __init__(self, dim, seed, init_low, init_high):
        self.dim = dim
        self._seed = seed
        self._low = init_low
        self._high = init_high
        self._rows = {}
        self._lock = threading.Lock()

    def _init_row(self, row_id):
        return _splitmix64_row(
            self._seed, row_id, self.dim, self._low, self._high
        )

    def _get(self, row_id):
        row = self._rows.get(row_id)
        if row is None:
            with self._lock:
                row = self._rows.setdefault(
                    row_id, self._init_row(row_id)
                )
        return row

    def lookup(self, ids):
        return np.stack([self._get(int(i)) for i in _as_ids(ids)])

    def set_rows(self, ids, values):
        values = _as_rows(values, self.dim)
        with self._lock:
            for i, row_id in enumerate(_as_ids(ids)):
                self._rows[int(row_id)] = values[i].copy()

    def __len__(self):
        return len(self._rows)

    def clear(self):
        with self._lock:
            self._rows.clear()

    def export_rows(self):
        if not self._rows:
            return (np.empty((0,), np.int64),
                    np.empty((0, self.dim), np.float32))
        ids = np.fromiter(self._rows, np.int64, len(self._rows))
        return ids, np.stack([self._rows[int(i)] for i in ids])

    def sgd(self, ids, grads, lr):
        grads = _as_rows(grads, self.dim)
        for i, row_id in enumerate(_as_ids(ids)):
            self._get(int(row_id))[:] -= lr * grads[i]

    def momentum(self, vel, ids, grads, lr, mu=0.9, nesterov=False):
        grads = _as_rows(grads, self.dim)
        for i, row_id in enumerate(_as_ids(ids)):
            p = self._get(int(row_id))
            v = vel._get(int(row_id))
            v[:] = mu * v + grads[i]
            p[:] -= lr * ((mu * v + grads[i]) if nesterov else v)

    def adam(self, m, v, ids, grads, lr, beta1=0.9, beta2=0.999,
             eps=1e-8, step=1):
        grads = _as_rows(grads, self.dim)
        alpha = lr * np.sqrt(1 - beta2**step) / (1 - beta1**step)
        for i, row_id in enumerate(_as_ids(ids)):
            p = self._get(int(row_id))
            mi = m._get(int(row_id))
            vi = v._get(int(row_id))
            mi[:] = beta1 * mi + (1 - beta1) * grads[i]
            vi[:] = beta2 * vi + (1 - beta2) * grads[i] ** 2
            p[:] -= alpha * mi / (np.sqrt(vi) + eps)

    def adagrad(self, accum, ids, grads, lr, eps=1e-10):
        grads = _as_rows(grads, self.dim)
        for i, row_id in enumerate(_as_ids(ids)):
            p = self._get(int(row_id))
            a = accum._get(int(row_id))
            a[:] += grads[i] ** 2
            p[:] -= lr * grads[i] / (np.sqrt(a) + eps)


def HostEmbeddingStore(dim, seed=0, init_low=-0.05, init_high=0.05,
                       force_python=False):
    """Factory: native store when libhostembedding.so is built, numpy
    fallback otherwise. Default init matches the reference's Go table
    (uniform [-0.05, 0.05], embedding_table.go:50-54)."""
    if not force_python and available():
        return _NativeStore(dim, seed, init_low, init_high)
    return _PythonStore(dim, seed, init_low, init_high)
