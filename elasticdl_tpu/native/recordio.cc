// Native TRec scanner: the C++ fast path for the framework's record format
// (layout defined in elasticdl_tpu/data/record_format.py — keep in sync).
//
// The reference framework's hot native loop is its Go/C++ PS kernel stack
// (reference: go/pkg/kernel/capi/kernel_api.cc); on TPU the optimizer math
// lives inside XLA, so the native speedup that still matters host-side is
// the data plane: this scanner feeds the input pipeline without Python
// per-record overhead. Exposed as a C ABI consumed via ctypes
// (elasticdl_tpu/native/recordio_native.py).
//
//   file  := MAGIC(8) VERSION(u32) record* footer
//   record:= len(u64) crc32(u32) payload[len]
//   footer:= offsets[count](u64 each) count(u64) FOOT_MAGIC(8)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include <zlib.h>

namespace {

constexpr char kMagic[] = "TRECIO\x00\x01";
constexpr char kFootMagic[] = "TRECEND\x00";
constexpr size_t kMagicLen = 8;
constexpr size_t kFootLen = 8;

struct TrecFile {
  FILE* f = nullptr;
  std::vector<uint64_t> offsets;
};

bool ReadU64At(FILE* f, long pos, uint64_t* out) {
  if (fseek(f, pos, SEEK_SET) != 0) return false;
  unsigned char buf[8];
  if (fread(buf, 1, 8, f) != 8) return false;
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | buf[i];  // little-endian
  *out = v;
  return true;
}

}  // namespace

extern "C" {

// Opens `path`, validates magic + footer, loads the offset index.
// Returns an opaque handle or nullptr on failure.
void* trec_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
  long size = ftell(f);
  long tail = static_cast<long>(8 + kFootLen);
  if (size < static_cast<long>(kMagicLen + 4) + tail) { fclose(f); return nullptr; }

  char magic[kMagicLen];
  if (fseek(f, 0, SEEK_SET) != 0 || fread(magic, 1, kMagicLen, f) != kMagicLen ||
      memcmp(magic, kMagic, kMagicLen) != 0) {
    fclose(f);
    return nullptr;
  }
  char foot[kFootLen];
  if (fseek(f, size - static_cast<long>(kFootLen), SEEK_SET) != 0 ||
      fread(foot, 1, kFootLen, f) != kFootLen ||
      memcmp(foot, kFootMagic, kFootLen) != 0) {
    fclose(f);
    return nullptr;
  }
  uint64_t count = 0;
  if (!ReadU64At(f, size - tail, &count)) { fclose(f); return nullptr; }
  long index_start = size - tail - static_cast<long>(count) * 8;
  if (index_start < static_cast<long>(kMagicLen + 4)) { fclose(f); return nullptr; }

  auto* tf = new TrecFile;
  tf->f = f;
  tf->offsets.resize(count);
  for (uint64_t i = 0; i < count; ++i) {
    if (!ReadU64At(f, index_start + static_cast<long>(i) * 8, &tf->offsets[i])) {
      fclose(f);
      delete tf;
      return nullptr;
    }
  }
  return tf;
}

long trec_count(void* handle) {
  if (!handle) return -1;
  return static_cast<long>(static_cast<TrecFile*>(handle)->offsets.size());
}

// Reads record `index` into a malloc'd buffer (*out). Returns payload length,
// or -1 on error. Caller frees with trec_free_buf.
long trec_read(void* handle, long index, char** out) {
  if (!handle || !out) return -1;
  auto* tf = static_cast<TrecFile*>(handle);
  if (index < 0 || static_cast<size_t>(index) >= tf->offsets.size()) return -1;
  if (fseek(tf->f, static_cast<long>(tf->offsets[index]), SEEK_SET) != 0) return -1;

  unsigned char hdr[12];  // len(u64) crc32(u32), little-endian
  if (fread(hdr, 1, 12, tf->f) != 12) return -1;
  uint64_t len = 0;
  for (int i = 7; i >= 0; --i) len = (len << 8) | hdr[i];
  uint32_t crc = 0;
  for (int i = 11; i >= 8; --i) crc = (crc << 8) | hdr[i];
  if (len > (1ull << 33)) return -1;  // sanity cap, matches gRPC-era limits

  char* buf = static_cast<char*>(malloc(len ? len : 1));
  if (!buf) return -1;
  if (len && fread(buf, 1, len, tf->f) != len) { free(buf); return -1; }
  uint32_t actual = static_cast<uint32_t>(
      crc32(0L, reinterpret_cast<const Bytef*>(buf), static_cast<uInt>(len)));
  if (actual != crc) { free(buf); return -1; }
  *out = buf;
  return static_cast<long>(len);
}

void trec_free_buf(char* buf) { free(buf); }

void trec_close(void* handle) {
  if (!handle) return;
  auto* tf = static_cast<TrecFile*>(handle);
  if (tf->f) fclose(tf->f);
  delete tf;
}

}  // extern "C"
