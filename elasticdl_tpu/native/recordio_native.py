"""ctypes bindings for the C++ TRec scanner (elasticdl_tpu/native/recordio.cc).

The native library is optional: readers fall back to the pure-Python codec in
elasticdl_tpu/data/record_format.py when the shared object has not been built
(`make -C elasticdl_tpu/native`). This mirrors the reference's split between
its Python PS and the Go/C++ fast path (SURVEY.md §2.4) — same format, same
semantics, faster scan.
"""

import ctypes
import os

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "libtrecio.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.trec_open.restype = ctypes.c_void_p
        lib.trec_open.argtypes = [ctypes.c_char_p]
        lib.trec_count.restype = ctypes.c_long
        lib.trec_count.argtypes = [ctypes.c_void_p]
        lib.trec_read.restype = ctypes.c_long
        lib.trec_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.trec_free_buf.argtypes = [ctypes.c_char_p]
        lib.trec_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


def record_count(path):
    lib = _load()
    h = lib.trec_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        return int(lib.trec_count(h))
    finally:
        lib.trec_close(h)


def scan(path, start, count):
    """Yield `count` record payloads starting at record `start`."""
    lib = _load()
    h = lib.trec_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        total = int(lib.trec_count(h))
        end = total if count < 0 else min(total, start + count)
        for i in range(start, end):
            buf = ctypes.c_char_p()
            n = lib.trec_read(h, i, ctypes.byref(buf))
            if n < 0:
                raise IOError("read error in %s at record %d" % (path, i))
            try:
                yield ctypes.string_at(buf, n)
            finally:
                lib.trec_free_buf(buf)
    finally:
        lib.trec_close(h)
