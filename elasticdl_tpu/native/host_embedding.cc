// Host-DRAM embedding store with fused optimizer kernels.
//
// The TPU-native replacement for the reference's Go PS embedding table +
// C++ Eigen kernels (go/pkg/common/embedding_table.go:22-88 lazy-init
// row map; go/pkg/kernel/capi/kernel_api.cc:6-96 SGD/Momentum/Adam/
// Adagrad): tables too large for HBM live in host DRAM behind this
// store; workers batch-lookup rows for the device and batch-apply
// gradients back, with the same lazy row initialization (uniform
// [-0.05, 0.05], matching embedding_table.go:50-54) and sparse
// optimizer semantics (only touched rows and their slots move).
//
// C API (extern "C") consumed via ctypes from
// elasticdl_tpu/native/host_embedding.py.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <unordered_map>
#include <vector>

namespace {

// splitmix64: tiny, portable PRNG implemented identically in the numpy
// fallback (native/host_embedding.py _splitmix64) so both backends
// lazily initialize the same (seed, id) to the same row.
inline uint64_t splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

struct Store {
  int64_t dim;
  uint64_t seed;
  float init_low;
  float init_high;
  // row id -> contiguous [dim] row; slot tables are separate Stores.
  std::unordered_map<int64_t, std::vector<float>> rows;
  mutable std::shared_mutex mu;

  Store(int64_t d, uint64_t s, float lo, float hi)
      : dim(d), seed(s), init_low(lo), init_high(hi) {}

  // Deterministic per-(seed, id) lazy init so restarts, replicas, and
  // the numpy fallback all agree without coordination.
  void init_row(int64_t id, std::vector<float>* row) const {
    row->resize(dim);
    uint64_t state = seed ^ static_cast<uint64_t>(id) * 0x9E3779B97F4A7C15ULL;
    const float span = init_high - init_low;
    for (int64_t i = 0; i < dim; ++i) {
      // top 53 bits -> uniform double in [0, 1)
      double frac = static_cast<double>(splitmix64(&state) >> 11)
                    * (1.0 / 9007199254740992.0);
      (*row)[i] = init_low + static_cast<float>(frac) * span;
    }
  }

  // Caller must hold `mu` exclusively: batch ops lock once per call
  // (per-store, like the reference Go table's RWMutex —
  // embedding_table.go:27) and row references never escape the lock.
  std::vector<float>& get_or_init_locked(int64_t id) {
    auto [it, inserted] = rows.try_emplace(id);
    if (inserted) init_row(id, &it->second);
    return it->second;
  }
};

}  // namespace

extern "C" {

void* host_embedding_new(int64_t dim, uint64_t seed, float init_low,
                         float init_high) {
  return new Store(dim, seed, init_low, init_high);
}

void host_embedding_free(void* handle) {
  delete static_cast<Store*>(handle);
}

int64_t host_embedding_dim(void* handle) {
  return static_cast<Store*>(handle)->dim;
}

void host_embedding_clear(void* handle) {
  Store* store = static_cast<Store*>(handle);
  std::unique_lock<std::shared_mutex> lock(store->mu);
  store->rows.clear();
}

int64_t host_embedding_size(void* handle) {
  Store* store = static_cast<Store*>(handle);
  std::shared_lock<std::shared_mutex> lock(store->mu);
  return static_cast<int64_t>(store->rows.size());
}

// out: [n, dim] row-major. Lazily initializes missing rows.
void host_embedding_lookup(void* handle, const int64_t* ids, int64_t n,
                           float* out) {
  Store* store = static_cast<Store*>(handle);
  std::unique_lock<std::shared_mutex> lock(store->mu);
  for (int64_t i = 0; i < n; ++i) {
    const std::vector<float>& row = store->get_or_init_locked(ids[i]);
    std::memcpy(out + i * store->dim, row.data(),
                store->dim * sizeof(float));
  }
}

// Writes rows verbatim (checkpoint restore path).
void host_embedding_set(void* handle, const int64_t* ids, int64_t n,
                        const float* values) {
  Store* store = static_cast<Store*>(handle);
  std::unique_lock<std::shared_mutex> lock(store->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto& row = store->rows[ids[i]];
    row.assign(values + i * store->dim, values + (i + 1) * store->dim);
  }
}

// Export up to `capacity` rows into caller buffers; returns the number
// written (the table may have grown since host_embedding_size()).
int64_t host_embedding_export(void* handle, int64_t* ids_out,
                              float* values_out, int64_t capacity) {
  Store* store = static_cast<Store*>(handle);
  std::shared_lock<std::shared_mutex> lock(store->mu);
  int64_t i = 0;
  for (const auto& kv : store->rows) {
    if (i >= capacity) break;
    ids_out[i] = kv.first;
    std::memcpy(values_out + i * store->dim, kv.second.data(),
                store->dim * sizeof(float));
    ++i;
  }
  return i;
}

// ---- sparse optimizer kernels: param store + slot stores passed as
// handles, ids deduplicated by the caller (kernel_api.cc family).

void host_embedding_sgd(void* param_h, const int64_t* ids,
                        const float* grads, int64_t n, float lr) {
  Store* param = static_cast<Store*>(param_h);
  std::unique_lock<std::shared_mutex> lock(param->mu);
  const int64_t dim = param->dim;
  for (int64_t i = 0; i < n; ++i) {
    std::vector<float>& p = param->get_or_init_locked(ids[i]);
    const float* g = grads + i * dim;
    for (int64_t k = 0; k < dim; ++k) p[k] -= lr * g[k];
  }
}

void host_embedding_momentum(void* param_h, void* vel_h,
                             const int64_t* ids, const float* grads,
                             int64_t n, float lr, float mu,
                             int nesterov) {
  Store* param = static_cast<Store*>(param_h);
  Store* vel = static_cast<Store*>(vel_h);
  // scoped_lock's deadlock-avoidance covers concurrent checkpoints
  // locking individual stores
  std::scoped_lock lock(param->mu, vel->mu);
  const int64_t dim = param->dim;
  for (int64_t i = 0; i < n; ++i) {
    std::vector<float>& p = param->get_or_init_locked(ids[i]);
    std::vector<float>& v = vel->get_or_init_locked(ids[i]);
    const float* g = grads + i * dim;
    for (int64_t k = 0; k < dim; ++k) {
      v[k] = mu * v[k] + g[k];
      p[k] -= lr * (nesterov ? mu * v[k] + g[k] : v[k]);
    }
  }
}

void host_embedding_adam(void* param_h, void* m_h, void* v_h,
                         const int64_t* ids, const float* grads,
                         int64_t n, float lr, float beta1, float beta2,
                         float eps, int64_t step) {
  Store* param = static_cast<Store*>(param_h);
  Store* m_store = static_cast<Store*>(m_h);
  Store* v_store = static_cast<Store*>(v_h);
  std::scoped_lock lock(param->mu, m_store->mu, v_store->mu);
  const int64_t dim = param->dim;
  const double t = static_cast<double>(step);
  const float alpha = static_cast<float>(
      lr * std::sqrt(1.0 - std::pow(beta2, t)) /
      (1.0 - std::pow(beta1, t)));
  for (int64_t i = 0; i < n; ++i) {
    std::vector<float>& p = param->get_or_init_locked(ids[i]);
    std::vector<float>& m = m_store->get_or_init_locked(ids[i]);
    std::vector<float>& v = v_store->get_or_init_locked(ids[i]);
    const float* g = grads + i * dim;
    for (int64_t k = 0; k < dim; ++k) {
      m[k] = beta1 * m[k] + (1.0f - beta1) * g[k];
      v[k] = beta2 * v[k] + (1.0f - beta2) * g[k] * g[k];
      p[k] -= alpha * m[k] / (std::sqrt(v[k]) + eps);
    }
  }
}

void host_embedding_adagrad(void* param_h, void* accum_h,
                            const int64_t* ids, const float* grads,
                            int64_t n, float lr, float eps) {
  Store* param = static_cast<Store*>(param_h);
  Store* accum = static_cast<Store*>(accum_h);
  std::scoped_lock lock(param->mu, accum->mu);
  const int64_t dim = param->dim;
  for (int64_t i = 0; i < n; ++i) {
    std::vector<float>& p = param->get_or_init_locked(ids[i]);
    std::vector<float>& a = accum->get_or_init_locked(ids[i]);
    const float* g = grads + i * dim;
    for (int64_t k = 0; k < dim; ++k) {
      a[k] += g[k] * g[k];
      p[k] -= lr * g[k] / (std::sqrt(a[k]) + eps);
    }
  }
}

}  // extern "C"
