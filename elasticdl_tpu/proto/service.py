"""Hand-rolled gRPC service binding for the Master service.

The environment ships `protoc` without the grpc python plugin, so instead of
generated `*_pb2_grpc.py` stubs this module declares the method table once
and derives both the server-side generic handler and the client stub from it.
Functionally equivalent to the reference's generated elasticdl_pb2_grpc
(MasterServicer / MasterStub).
"""

import grpc

from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.proto import elasticdl_pb2 as pb

SERVICE_NAME = "elasticdl_tpu.Master"
SERVING_SERVICE_NAME = "elasticdl_tpu.Serving"
ROUTER_SERVICE_NAME = "elasticdl_tpu.Router"

# method name -> (request class, response class)
_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.Task),
    "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
    "report_evaluation_metrics": (
        pb.ReportEvaluationMetricsRequest,
        pb.Empty,
    ),
    "report_version": (pb.ReportVersionRequest, pb.Empty),
    "register_worker": (
        pb.RegisterWorkerRequest,
        pb.RegisterWorkerResponse,
    ),
}

# method name -> (request class, response class, server-streaming?)
_SERVING_METHODS = {
    "generate": (pb.GenerateRequest, pb.GenerateResponse, False),
    "generate_stream": (pb.GenerateRequest, pb.TokenChunk, True),
    "server_status": (
        pb.ServerStatusRequest,
        pb.ServerStatusResponse,
        False,
    ),
    # disaggregated prefill/decode handoff (serving/disagg.py): the
    # export response IS the transfer payload the decode side imports
    "export_chain": (
        pb.ExportChainRequest,
        pb.TransferChainRequest,
        False,
    ),
    "transfer_chain": (
        pb.TransferChainRequest,
        pb.TransferChainResponse,
        False,
    ),
    "abort_transfer": (
        pb.AbortTransferRequest,
        pb.TransferChainResponse,
        False,
    ),
    # explicit checkpoint swap (serving/rollout.py handshake): load
    # exactly the named version — newer or older — on the scheduler
    # thread, draining advertised for the duration
    "reload_checkpoint": (
        pb.ReloadCheckpointRequest,
        pb.ReloadCheckpointResponse,
        False,
    ),
}

# the routing tier's surface (serving/router.py); names are distinct
# from the replica surface so fault-injection specs can target one
# boundary without the other
_ROUTER_METHODS = {
    "router_generate": (pb.GenerateRequest, pb.GenerateResponse, False),
    "router_generate_stream": (pb.GenerateRequest, pb.TokenChunk, True),
    "router_status": (
        pb.RouterStatusRequest,
        pb.RouterStatusResponse,
        False,
    ),
}


def add_master_servicer_to_server(servicer, server):
    handlers = {}
    for name, (req_cls, resp_cls) in _METHODS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def _add_servicer(servicer, server, service_name, methods):
    handlers = {}
    for name, (req_cls, resp_cls, streaming) in methods.items():
        make = (
            grpc.unary_stream_rpc_method_handler
            if streaming
            else grpc.unary_unary_rpc_method_handler
        )
        handlers[name] = make(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_serving_servicer_to_server(servicer, server):
    _add_servicer(servicer, server, SERVING_SERVICE_NAME, _SERVING_METHODS)


def add_router_servicer_to_server(servicer, server):
    _add_servicer(servicer, server, ROUTER_SERVICE_NAME, _ROUTER_METHODS)


class MasterStub(object):
    def __init__(self, channel):
        for name, (req_cls, resp_cls) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    "/%s/%s" % (SERVICE_NAME, name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


class ServingStub(object):
    def __init__(self, channel):
        for name, (req_cls, resp_cls, streaming) in (
            _SERVING_METHODS.items()
        ):
            make = channel.unary_stream if streaming else channel.unary_unary
            setattr(
                self,
                name,
                make(
                    "/%s/%s" % (SERVING_SERVICE_NAME, name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


class RouterStub(object):
    def __init__(self, channel):
        for name, (req_cls, resp_cls, streaming) in (
            _ROUTER_METHODS.items()
        ):
            make = channel.unary_stream if streaming else channel.unary_unary
            setattr(
                self,
                name,
                make(
                    "/%s/%s" % (ROUTER_SERVICE_NAME, name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


def build_channel(addr):
    """Insecure channel with the control-plane message caps (reference:
    common/grpc_utils.py:19-30)."""
    return grpc.insecure_channel(
        addr,
        options=[
            ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
            (
                "grpc.max_receive_message_length",
                GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
            ),
        ],
    )


def build_server(thread_pool):
    return grpc.server(
        thread_pool,
        options=[
            ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
            (
                "grpc.max_receive_message_length",
                GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
            ),
        ],
    )
