"""Conversions between wire enums and the framework's TaskType strings."""

from elasticdl_tpu.master.task_dispatcher import TaskType
from elasticdl_tpu.proto import elasticdl_pb2 as pb

TASK_TYPE_TO_PB = {
    TaskType.TRAINING: pb.TRAINING,
    TaskType.EVALUATION: pb.EVALUATION,
    TaskType.PREDICTION: pb.PREDICTION,
    TaskType.WAIT: pb.WAIT,
    TaskType.TRAIN_END_CALLBACK: pb.TRAIN_END_CALLBACK,
}
PB_TO_TASK_TYPE = {v: k for k, v in TASK_TYPE_TO_PB.items()}


def task_type_to_pb(task_type):
    return TASK_TYPE_TO_PB[task_type]


def task_type_from_pb(pb_type):
    return PB_TO_TASK_TYPE.get(pb_type)
