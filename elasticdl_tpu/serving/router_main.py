"""Routing-tier process entrypoint.

Stands the health-checked router (serving/router.py) in front of N
generation-server replicas and serves RouterGenerate /
RouterGenerateStream / RouterStatus until SIGTERM/SIGINT. Pure
control-plane: no jax, no model — the process starts in milliseconds
and can sit in front of replicas on any mix of hosts.

    python -m elasticdl_tpu.serving.router_main \\
        --replica localhost:50051 --replica localhost:50052 \\
        --replica localhost:50053 --port 50050

With --autoscale the fleet is ELASTIC instead of static: the replica
supervisor (serving/autoscaler.py) spawns `elasticdl_tpu.serving.main`
replicas itself (pass their flags through --replica_args), replaces
crashed ones, and scales the count between --min_replicas and
--max_replicas on the router's own load signals — journaling every
lifecycle transition to --journal_dir so a supervisor restart
re-adopts the live fleet instead of orphaning or double-spawning it:

    python -m elasticdl_tpu.serving.router_main --port 50050 \\
        --autoscale --min_replicas 1 --max_replicas 4 \\
        --journal_dir /var/lib/edl/fleet \\
        --replica_args "--model_zoo model_zoo \\
            --model_def transformer_lm.transformer_lm.custom_model \\
            --port 0 --num_slots 4"

Fault injection at the router boundary uses the same EDL_FAULT_SPEC
grammar as every other drill, under the router RPC names:
EDL_FAULT_SPEC='router_generate:error:2' rejects two routed calls
without touching any replica; the supervisor's process boundary
listens on the supervisor_spawn / supervisor_ready / supervisor_adopt
hooks (spawn-fail, slow-ready, adopt-drop).
"""

import argparse
import os
import shlex
import signal
import sys
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.serving.router import Router, RouterConfig


def parse_router_args(args=None):
    parser = argparse.ArgumentParser(
        description="elasticdl-tpu serving router"
    )
    parser.add_argument("--replica", action="append", default=[],
                        help="replica address host:port (repeatable; "
                             "optional with --autoscale)")
    parser.add_argument("--port", type=int, default=50050)
    parser.add_argument("--poll_secs", type=float, default=0.5)
    parser.add_argument("--poll_timeout_secs", type=float, default=2.0)
    parser.add_argument("--lease_secs", type=float, default=2.5)
    parser.add_argument("--breaker_threshold", type=int, default=3)
    parser.add_argument("--breaker_cooldown_secs", type=float,
                        default=2.0)
    parser.add_argument("--hedge_delay_ms", type=float, default=0.0,
                        help="0 disables hedged duplicate dispatch")
    parser.add_argument("--dispatch_timeout_secs", type=float,
                        default=120.0)
    parser.add_argument("--redispatch_window_secs", type=float,
                        default=30.0)
    parser.add_argument("--tensorboard_log_dir", default="")
    # live metrics plane: Prometheus /metrics exposition + the SLO
    # burn-rate engine's declared objectives (observability/slo.py).
    # -1 resolves metrics_port from EDL_METRICS_PORT (unset = off);
    # 0 = ephemeral, printed as `METRICS_READY port=N`
    parser.add_argument("--metrics_port", type=int, default=-1)
    parser.add_argument("--slo_ttft_p99_ms", type=float,
                        default=30000.0)
    parser.add_argument("--slo_e2e_p99_ms", type=float,
                        default=60000.0)
    parser.add_argument("--slo_latency_goal", type=float, default=0.01,
                        help="allowed fraction of requests over a "
                             "latency threshold (the error budget)")
    parser.add_argument("--slo_goodput_goal", type=float, default=0.02,
                        help="allowed failed fraction (shed+errors "
                             "over routed)")
    parser.add_argument("--slo_fast_window_secs", type=float,
                        default=30.0)
    parser.add_argument("--slo_slow_window_secs", type=float,
                        default=120.0)
    # ---- elastic fleet (serving/autoscaler.py) ----
    parser.add_argument("--autoscale", action="store_true",
                        help="own the replica fleet: spawn/replace/"
                             "drain elasticdl_tpu.serving.main "
                             "processes instead of fronting a static "
                             "--replica list")
    parser.add_argument("--replica_args", default="",
                        help="flags for the spawned serving.main "
                             "processes (one shell-quoted string)")
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--max_replicas", type=int, default=4)
    parser.add_argument("--journal_dir", default="",
                        help="supervisor WAL dir; restarts re-adopt "
                             "the live fleet from it")
    parser.add_argument("--decide_secs", type=float, default=0.5)
    parser.add_argument("--up_queue_wait_ms", type=float, default=200.0)
    parser.add_argument("--up_window_secs", type=float, default=2.0)
    parser.add_argument("--down_window_secs", type=float, default=6.0)
    parser.add_argument("--scale_cooldown_secs", type=float,
                        default=5.0)
    parser.add_argument("--max_restarts", type=int, default=3)
    parsed = parser.parse_args(args)
    if not parsed.replica and not parsed.autoscale:
        parser.error("at least one --replica is required "
                     "(or pass --autoscale)")
    if parsed.autoscale and not parsed.replica_args:
        parser.error("--autoscale needs --replica_args to know how to "
                     "launch replicas")
    return parsed


def build_router(args):
    return Router(
        args.replica,
        RouterConfig(
            poll_secs=args.poll_secs,
            poll_timeout_secs=args.poll_timeout_secs,
            lease_secs=args.lease_secs,
            breaker_threshold=args.breaker_threshold,
            breaker_cooldown_secs=args.breaker_cooldown_secs,
            hedge_delay_secs=args.hedge_delay_ms / 1000.0,
            dispatch_timeout_secs=args.dispatch_timeout_secs,
            redispatch_window_secs=args.redispatch_window_secs,
            port=args.port,
            telemetry_dir=args.tensorboard_log_dir,
            metrics_port=(None if args.metrics_port < 0
                          else args.metrics_port),
            slo_ttft_p99_ms=args.slo_ttft_p99_ms,
            slo_e2e_p99_ms=args.slo_e2e_p99_ms,
            slo_latency_goal=args.slo_latency_goal,
            slo_goodput_goal=args.slo_goodput_goal,
            slo_fast_window_secs=args.slo_fast_window_secs,
            slo_slow_window_secs=args.slo_slow_window_secs,
        ),
    )


def build_supervisor(args, router):
    from elasticdl_tpu.serving.autoscaler import (
        AutoscalerConfig,
        ReplicaSupervisor,
        SubprocessReplicaLauncher,
    )

    journal_dir = args.journal_dir or os.path.join(
        ".", "edl_fleet_%d" % os.getpid()
    )
    launcher = SubprocessReplicaLauncher(
        shlex.split(args.replica_args),
        log_dir=os.path.join(journal_dir, "logs"),
    )
    supervisor = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            decide_secs=args.decide_secs,
            up_queue_wait_ms=args.up_queue_wait_ms,
            up_window_secs=args.up_window_secs,
            down_window_secs=args.down_window_secs,
            cooldown_secs=args.scale_cooldown_secs,
            max_restarts=args.max_restarts,
            journal_dir=journal_dir,
        ),
    )
    router.set_autoscaler(supervisor)
    return supervisor


def main(argv=None):
    args = parse_router_args(argv)
    # SIGUSR2 -> all-thread stack dump: a live wedged router can
    # always be interrogated without killing it
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    router = build_router(args).start()
    supervisor = None
    if args.autoscale:
        supervisor = build_supervisor(args, router).start()
    # name this process's span recorder; spans export to
    # $EDL_TRACE_DIR on stop (plus an atexit backstop)
    from elasticdl_tpu.observability.tracing import configure

    configure(service="router:%d" % router.port)
    done = threading.Event()

    def _graceful(_signum, _frame):
        logger.info("signal received: stopping router")
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    if router.metrics is not None:
        print("METRICS_READY port=%d" % router.metrics.port,
              flush=True)
    print("ROUTER_READY port=%d" % router.port, flush=True)
    done.wait()
    # supervisor first: it drains and retires the fleet it owns; the
    # router keeps answering status RPCs until the roster is gone
    if supervisor is not None:
        supervisor.stop()
    router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
