"""Routing-tier process entrypoint.

Stands the health-checked router (serving/router.py) in front of N
generation-server replicas and serves RouterGenerate /
RouterGenerateStream / RouterStatus until SIGTERM/SIGINT. Pure
control-plane: no jax, no model — the process starts in milliseconds
and can sit in front of replicas on any mix of hosts.

    python -m elasticdl_tpu.serving.router_main \\
        --replica localhost:50051 --replica localhost:50052 \\
        --replica localhost:50053 --port 50050

With --autoscale the fleet is ELASTIC instead of static: the replica
supervisor (serving/autoscaler.py) spawns `elasticdl_tpu.serving.main`
replicas itself (pass their flags through --replica_args), replaces
crashed ones, and scales the count between --min_replicas and
--max_replicas on the router's own load signals — journaling every
lifecycle transition to --journal_dir so a supervisor restart
re-adopts the live fleet instead of orphaning or double-spawning it:

    python -m elasticdl_tpu.serving.router_main --port 50050 \\
        --autoscale --min_replicas 1 --max_replicas 4 \\
        --journal_dir /var/lib/edl/fleet \\
        --replica_args "--model_zoo model_zoo \\
            --model_def transformer_lm.transformer_lm.custom_model \\
            --port 0 --num_slots 4"

With --cells N (> 1) the process becomes a CELL SUPERVISOR instead of
a router: it spawns N router cells (serving/router_cell.py) on ports
--port .. --port+N-1, all sharing one replica registry through the
write-ahead journal in --cell_journal_dir, and restarts a cell that
dies. Each cell is this same entrypoint with an explicit --cell_id,
so a cell can equally be launched by hand (or by a drill) without the
supervisor:

    python -m elasticdl_tpu.serving.router_main --cells 2 \\
        --replica localhost:50051 --replica localhost:50052 \\
        --port 50050 --cell_journal_dir /var/lib/edl/cells

Clients reach the tier through the CellFront (consistent-hash by
prefix fingerprint, ring-successor reroute on cell death) or any
single cell directly — every cell serves the full Router surface.

With --rollout V the process also owns a ROLLOUT CONTROLLER
(serving/rollout.py): the fleet rolls to checkpoint version V through
canary -> greedy-parity + SLO-burn judgment -> progressive waves ->
commit, journaling every transition to --rollout_journal_dir so a
router restart resumes the rollout mid-wave (no --rollout needed the
second time) with no replica double-swapped or left on a mixed
version. A failed judgment — parity drift, fast-window burn, or no
verdict inside the judge timeout — rolls every swapped replica back
in reverse order automatically:

    python -m elasticdl_tpu.serving.router_main --port 50050 \\
        --replica localhost:50051 --replica localhost:50052 \\
        --rollout 7 --rollout_checkpoint_dir /ckpt \\
        --rollout_journal_dir /var/lib/edl/rollout

Fault injection at the router boundary uses the same EDL_FAULT_SPEC
grammar as every other drill, under the router RPC names:
EDL_FAULT_SPEC='router_generate:error:2' rejects two routed calls
without touching any replica; the supervisor's process boundary
listens on the supervisor_spawn / supervisor_ready / supervisor_adopt
hooks (spawn-fail, slow-ready, adopt-drop); the cell tier listens on
cell_spawn (supervisor launch path) and cell_kill (each cell's
heartbeat tick — `cell_kill:kill:1:skip=4` SIGKILLs a live cell, the
router-kill chaos phase).
"""

import argparse
import os
import shlex
import signal
import sys
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.serving.router import Router, RouterConfig


def parse_router_args(args=None):
    parser = argparse.ArgumentParser(
        description="elasticdl-tpu serving router"
    )
    parser.add_argument("--replica", action="append", default=[],
                        help="replica address host:port (repeatable; "
                             "optional with --autoscale)")
    parser.add_argument("--port", type=int, default=50050)
    parser.add_argument("--poll_secs", type=float, default=0.5)
    parser.add_argument("--poll_timeout_secs", type=float, default=2.0)
    parser.add_argument("--lease_secs", type=float, default=2.5)
    parser.add_argument("--breaker_threshold", type=int, default=3)
    parser.add_argument("--breaker_cooldown_secs", type=float,
                        default=2.0)
    parser.add_argument("--hedge_delay_ms", type=float, default=0.0,
                        help="0 disables hedged duplicate dispatch")
    parser.add_argument("--dispatch_timeout_secs", type=float,
                        default=120.0)
    parser.add_argument("--redispatch_window_secs", type=float,
                        default=30.0)
    parser.add_argument("--tensorboard_log_dir", default="")
    # live metrics plane: Prometheus /metrics exposition + the SLO
    # burn-rate engine's declared objectives (observability/slo.py).
    # -1 resolves metrics_port from EDL_METRICS_PORT (unset = off);
    # 0 = ephemeral, printed as `METRICS_READY port=N`
    parser.add_argument("--metrics_port", type=int, default=-1)
    parser.add_argument("--slo_ttft_p99_ms", type=float,
                        default=30000.0)
    parser.add_argument("--slo_e2e_p99_ms", type=float,
                        default=60000.0)
    parser.add_argument("--slo_latency_goal", type=float, default=0.01,
                        help="allowed fraction of requests over a "
                             "latency threshold (the error budget)")
    parser.add_argument("--slo_goodput_goal", type=float, default=0.02,
                        help="allowed failed fraction (shed+errors "
                             "over routed)")
    parser.add_argument("--slo_fast_window_secs", type=float,
                        default=30.0)
    parser.add_argument("--slo_slow_window_secs", type=float,
                        default=120.0)
    # ---- prefix-affine dispatch (serving/prefix_affinity.py) ----
    parser.add_argument("--affinity", type=int, default=1,
                        help="1 = prefix-affine dispatch (decays to "
                             "least-loaded), 0 = prefix-blind")
    parser.add_argument("--affinity_block_tokens", type=int,
                        default=16,
                        help="KV block size the fingerprint chains "
                             "over (match the replicas' "
                             "--kv_block_size)")
    parser.add_argument("--affinity_ttl_secs", type=float,
                        default=60.0)
    parser.add_argument("--affinity_load_margin", type=float,
                        default=2.0,
                        help="max load-score excess over the least-"
                             "loaded candidate an affine target may "
                             "carry before affinity decays")
    parser.add_argument("--disagg", type=int, default=1,
                        help="1 = orchestrate prefill->decode chain "
                             "handoffs when a replica advertises "
                             "--role prefill (serving/disagg.py), "
                             "0 = treat every replica as unified")
    parser.add_argument("--disagg_timeout_secs", type=float,
                        default=10.0,
                        help="per-leg deadline for the handoff RPCs "
                             "(prefill generate / export / import)")
    # ---- multi-cell tier (serving/router_cell.py) ----
    parser.add_argument("--cells", type=int, default=1,
                        help="> 1: supervise N router cells on ports "
                             "--port..--port+N-1 sharing "
                             "--cell_journal_dir")
    parser.add_argument("--cell_id", type=int, default=-1,
                        help="this process's cell id (assigned by the "
                             "cell supervisor; -1 = standalone)")
    parser.add_argument("--cell_journal_dir", default="",
                        help="shared registry WAL dir; a (re)started "
                             "cell replays the fleet view from it")
    # ---- elastic fleet (serving/autoscaler.py) ----
    parser.add_argument("--autoscale", action="store_true",
                        help="own the replica fleet: spawn/replace/"
                             "drain elasticdl_tpu.serving.main "
                             "processes instead of fronting a static "
                             "--replica list")
    parser.add_argument("--replica_args", default="",
                        help="flags for the spawned serving.main "
                             "processes (one shell-quoted string)")
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--max_replicas", type=int, default=4)
    parser.add_argument("--journal_dir", default="",
                        help="supervisor WAL dir; restarts re-adopt "
                             "the live fleet from it")
    parser.add_argument("--decide_secs", type=float, default=0.5)
    parser.add_argument("--up_queue_wait_ms", type=float, default=200.0)
    parser.add_argument("--up_window_secs", type=float, default=2.0)
    parser.add_argument("--down_window_secs", type=float, default=6.0)
    parser.add_argument("--scale_cooldown_secs", type=float,
                        default=5.0)
    parser.add_argument("--max_restarts", type=int, default=3)
    # ---- zero-downtime model rollout (serving/rollout.py) ----
    parser.add_argument("--rollout_journal_dir", default="",
                        help="enable the rollout controller, journaling "
                             "every wave transition here; a restarted "
                             "router resumes an unfinished rollout "
                             "from this journal even without --rollout")
    parser.add_argument("--rollout_checkpoint_dir", default="",
                        help="checkpoint store the fleet reads (match "
                             "the replicas' --checkpoint_dir)")
    parser.add_argument("--rollout", type=int, default=-1,
                        help=">= 0: roll the fleet to this checkpoint "
                             "version (canary -> judge -> waves -> "
                             "commit); -1 only resumes a journaled "
                             "rollout, if one is in flight")
    parser.add_argument("--rollout_wave_size", type=int, default=1,
                        help="replicas swapped per progressive wave "
                             "after the canary passes judgment")
    parser.add_argument("--rollout_soak_secs", type=float, default=3.0,
                        help="burn-rate observation window per wave "
                             "(and the canary's minimum soak)")
    parser.add_argument("--rollout_judge_timeout_secs", type=float,
                        default=60.0,
                        help="no canary verdict within this window is "
                             "itself a verdict: no promotion")
    parser.add_argument("--rollout_parity_prompts", default="1,2,3",
                        help="pinned greedy-parity prompt set: "
                             "semicolon-separated comma-lists of token "
                             "ids, e.g. '1,2,3;4,5'")
    parsed = parser.parse_args(args)
    if (not parsed.replica and not parsed.autoscale
            and not parsed.cell_journal_dir):
        parser.error("at least one --replica is required (or pass "
                     "--autoscale, or --cell_journal_dir to replay "
                     "the fleet from a sibling cell's journal)")
    if parsed.autoscale and not parsed.replica_args:
        parser.error("--autoscale needs --replica_args to know how to "
                     "launch replicas")
    if parsed.rollout >= 0 and not parsed.rollout_journal_dir:
        parser.error("--rollout needs --rollout_journal_dir: an "
                     "unjournaled fleet swap cannot survive a "
                     "controller crash")
    if (parsed.rollout >= 0 and not parsed.rollout_checkpoint_dir):
        parser.error("--rollout needs --rollout_checkpoint_dir to "
                     "verify the target checkpoint before any swap")
    return parsed


def build_router(args):
    config = RouterConfig(
        poll_secs=args.poll_secs,
        poll_timeout_secs=args.poll_timeout_secs,
        lease_secs=args.lease_secs,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_secs=args.breaker_cooldown_secs,
        hedge_delay_secs=args.hedge_delay_ms / 1000.0,
        dispatch_timeout_secs=args.dispatch_timeout_secs,
        redispatch_window_secs=args.redispatch_window_secs,
        port=args.port,
        telemetry_dir=args.tensorboard_log_dir,
        metrics_port=(None if args.metrics_port < 0
                      else args.metrics_port),
        slo_ttft_p99_ms=args.slo_ttft_p99_ms,
        slo_e2e_p99_ms=args.slo_e2e_p99_ms,
        slo_latency_goal=args.slo_latency_goal,
        slo_goodput_goal=args.slo_goodput_goal,
        slo_fast_window_secs=args.slo_fast_window_secs,
        slo_slow_window_secs=args.slo_slow_window_secs,
        affinity=bool(args.affinity),
        affinity_block_tokens=args.affinity_block_tokens,
        affinity_ttl_secs=args.affinity_ttl_secs,
        affinity_load_margin=args.affinity_load_margin,
        disagg=bool(args.disagg),
        disagg_timeout_secs=args.disagg_timeout_secs,
        cell_id=max(0, args.cell_id),
        cells=max(1, args.cells),
    )
    if args.cell_journal_dir:
        from elasticdl_tpu.serving.router_cell import RouterCell

        return RouterCell(args.replica, config,
                          journal_dir=args.cell_journal_dir)
    return Router(args.replica, config)


def build_supervisor(args, router):
    from elasticdl_tpu.serving.autoscaler import (
        AutoscalerConfig,
        ReplicaSupervisor,
        SubprocessReplicaLauncher,
    )

    journal_dir = args.journal_dir or os.path.join(
        ".", "edl_fleet_%d" % os.getpid()
    )
    launcher = SubprocessReplicaLauncher(
        shlex.split(args.replica_args),
        log_dir=os.path.join(journal_dir, "logs"),
    )
    supervisor = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            decide_secs=args.decide_secs,
            up_queue_wait_ms=args.up_queue_wait_ms,
            up_window_secs=args.up_window_secs,
            down_window_secs=args.down_window_secs,
            cooldown_secs=args.scale_cooldown_secs,
            max_restarts=args.max_restarts,
            journal_dir=journal_dir,
        ),
    )
    router.set_autoscaler(supervisor)
    return supervisor


def _cell_child_argv(args, cell_id):
    """The child cell's command line: this very entrypoint with an
    explicit --cell_id (so the child runs as ONE cell, never recurses
    into the supervisor branch), its own port, and the shared journal
    dir. Flags the tier shares pass through verbatim."""
    argv = [
        sys.executable, "-m", "elasticdl_tpu.serving.router_main",
        "--cell_id", str(cell_id),
        "--cells", str(args.cells),
        "--port", str(args.port + cell_id),
        "--cell_journal_dir", args.cell_journal_dir,
        "--poll_secs", str(args.poll_secs),
        "--poll_timeout_secs", str(args.poll_timeout_secs),
        "--lease_secs", str(args.lease_secs),
        "--breaker_threshold", str(args.breaker_threshold),
        "--breaker_cooldown_secs", str(args.breaker_cooldown_secs),
        "--dispatch_timeout_secs", str(args.dispatch_timeout_secs),
        "--redispatch_window_secs", str(args.redispatch_window_secs),
        "--affinity", str(args.affinity),
        "--affinity_block_tokens", str(args.affinity_block_tokens),
        "--affinity_ttl_secs", str(args.affinity_ttl_secs),
        "--affinity_load_margin", str(args.affinity_load_margin),
        "--disagg", str(int(args.disagg)),
        "--disagg_timeout_secs", str(args.disagg_timeout_secs),
    ]
    for addr in args.replica:
        argv += ["--replica", addr]
    return argv


class CellRoster(object):
    """The cell supervisor's process roster, under the same resource
    discipline as the replica supervisor's seats (edl-lint EDL501):
    every spawn_cell() MUST settle in adopt() (the cell joins the
    roster) or retire() (terminate + wait) on every path — an
    unadopted cell is an orphan router no journal remembers, and a
    retired-but-unwaited one is a zombie pinned until the supervisor
    exits. Child stdout/stderr go to per-cell log FILES (not pipes):
    the cells outlive any supervisor wedge and their ready lines stay
    greppable post-mortem."""

    def __init__(self, args, log_dir=None):
        self._args = args
        self._log_dir = log_dir or os.path.join(
            args.cell_journal_dir, "logs"
        )
        os.makedirs(self._log_dir, exist_ok=True)
        self._cells = {}  # cell_id -> subprocess.Popen
        self.restarts = {}  # cell_id -> count

    def spawn_cell(self, cell_id):
        import subprocess

        log_path = os.path.join(self._log_dir,
                                "cell_%d.log" % cell_id)
        log = open(log_path, "a")
        try:
            proc = subprocess.Popen(
                _cell_child_argv(self._args, cell_id),
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            # the child owns the descriptor now (or the spawn failed);
            # either way the parent's handle must not leak
            log.close()
        proc.cell_id = cell_id
        return proc

    def adopt(self, proc):
        self._cells[proc.cell_id] = proc
        logger.info("cell %d adopted (pid %d, port %d)",
                    proc.cell_id, proc.pid,
                    self._args.port + proc.cell_id)

    def retire(self, proc):
        self._cells.pop(proc.cell_id, None)
        if proc.poll() is None:
            proc.terminate()
        try:
            proc.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 - escalate to SIGKILL
            proc.kill()
            proc.wait()

    def live(self):
        return dict(self._cells)

    def reap_dead(self):
        """Cells that exited on their own (already waited — no zombie
        survives this call). Returns their ids."""
        dead = [cid for cid, p in self._cells.items()
                if p.poll() is not None]
        for cid in dead:
            self._cells.pop(cid)
        return dead


#: a cell that dies more than this many times stays down — the same
#: give-up bar the replica supervisor's restart circuit enforces
MAX_CELL_RESTARTS = 3


def launch_cells(args):
    """Supervisor mode (--cells N): spawn one router cell per id on
    ports --port..--port+N-1, restart a dead cell (bounded), SIGTERM
    the roster on shutdown. The registry journal — not this process —
    carries the fleet view, so a supervisor crash orphans nothing a
    restarted cell can't replay."""
    from elasticdl_tpu.common.fault_injection import FaultInjector

    if not args.cell_journal_dir:
        args.cell_journal_dir = os.path.join(
            ".", "edl_cells_%d" % os.getpid()
        )
    os.makedirs(args.cell_journal_dir, exist_ok=True)
    injector = FaultInjector.from_env()
    roster = CellRoster(args)

    def spawn_adopted(cell_id):
        if injector is not None:
            # cell_spawn hook: a `cell_spawn:drop` rule fails this
            # launch the way a bad node would
            injector.intercept("cell_spawn", context=None,
                               when="before")
        proc = roster.spawn_cell(cell_id)
        try:
            roster.adopt(proc)
        except Exception:
            roster.retire(proc)
            raise
        return proc

    for i in range(args.cells):
        spawn_adopted(i)
        print("CELL_STARTED cell=%d port=%d" % (i, args.port + i),
              flush=True)
    print("ROUTER_CELLS_READY count=%d" % args.cells, flush=True)
    done = threading.Event()

    def _graceful(_signum, _frame):
        logger.info("signal received: stopping %d router cells",
                    len(roster.live()))
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    while not done.wait(0.5):
        for cell_id in roster.reap_dead():
            n = roster.restarts.get(cell_id, 0)
            if n >= MAX_CELL_RESTARTS:
                logger.error(
                    "cell %d exceeded %d restarts; leaving it down "
                    "(surviving cells keep serving)",
                    cell_id, MAX_CELL_RESTARTS,
                )
                continue
            roster.restarts[cell_id] = n + 1
            logger.warning("cell %d died; restarting (%d/%d)",
                           cell_id, n + 1, MAX_CELL_RESTARTS)
            try:
                spawn_adopted(cell_id)
            except Exception as e:  # noqa: BLE001 - retried next tick
                logger.error("cell %d respawn failed: %r", cell_id, e)
    for proc in roster.live().values():
        roster.retire(proc)
    return 0


def main(argv=None):
    args = parse_router_args(argv)
    if args.cells > 1 and args.cell_id < 0:
        return launch_cells(args)
    # SIGUSR2 -> all-thread stack dump: a live wedged router can
    # always be interrogated without killing it
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    router = build_router(args).start()
    supervisor = None
    if args.autoscale:
        supervisor = build_supervisor(args, router).start()
    rollout = None
    if args.rollout_journal_dir:
        from elasticdl_tpu.serving.rollout import build_rollout

        rollout = build_rollout(args, router)
        router.set_rollout(rollout)
        if args.rollout >= 0:
            # deferred: the first decide tick that finds a registered
            # fleet opens the rollout (the autoscaler may still be
            # spawning replicas when we get here)
            rollout.request(args.rollout)
        rollout.start()
    # name this process's span recorder; spans export to
    # $EDL_TRACE_DIR on stop (plus an atexit backstop)
    from elasticdl_tpu.observability.tracing import configure

    configure(service="router:%d" % router.port)
    done = threading.Event()

    def _graceful(_signum, _frame):
        logger.info("signal received: stopping router")
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    if router.metrics is not None:
        print("METRICS_READY port=%d" % router.metrics.port,
              flush=True)
    if args.cell_id >= 0:
        # its own line: launch_ready parses `port=` as the LAST token
        # of the READY line, so cell annotations must not ride it
        print("ROUTER_CELL cell=%d cells=%d" % (args.cell_id,
                                                args.cells),
              flush=True)
    print("ROUTER_READY port=%d" % router.port, flush=True)
    done.wait()
    # rollout controller first (it calls INTO the fleet), then the
    # supervisor (it drains and retires the fleet it owns); the router
    # keeps answering status RPCs until the roster is gone
    if rollout is not None:
        rollout.stop()
    if supervisor is not None:
        supervisor.stop()
    router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
