"""Routing-tier process entrypoint.

Stands the health-checked router (serving/router.py) in front of N
generation-server replicas and serves RouterGenerate /
RouterGenerateStream / RouterStatus until SIGTERM/SIGINT. Pure
control-plane: no jax, no model — the process starts in milliseconds
and can sit in front of replicas on any mix of hosts.

    python -m elasticdl_tpu.serving.router_main \\
        --replica localhost:50051 --replica localhost:50052 \\
        --replica localhost:50053 --port 50050

Fault injection at the router boundary uses the same EDL_FAULT_SPEC
grammar as every other drill, under the router RPC names:
EDL_FAULT_SPEC='router_generate:error:2' rejects two routed calls
without touching any replica.
"""

import argparse
import signal
import sys
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.serving.router import Router, RouterConfig


def parse_router_args(args=None):
    parser = argparse.ArgumentParser(
        description="elasticdl-tpu serving router"
    )
    parser.add_argument("--replica", action="append", default=[],
                        help="replica address host:port (repeatable)")
    parser.add_argument("--port", type=int, default=50050)
    parser.add_argument("--poll_secs", type=float, default=0.5)
    parser.add_argument("--poll_timeout_secs", type=float, default=2.0)
    parser.add_argument("--lease_secs", type=float, default=2.5)
    parser.add_argument("--breaker_threshold", type=int, default=3)
    parser.add_argument("--breaker_cooldown_secs", type=float,
                        default=2.0)
    parser.add_argument("--hedge_delay_ms", type=float, default=0.0,
                        help="0 disables hedged duplicate dispatch")
    parser.add_argument("--dispatch_timeout_secs", type=float,
                        default=120.0)
    parser.add_argument("--redispatch_window_secs", type=float,
                        default=30.0)
    parser.add_argument("--tensorboard_log_dir", default="")
    parsed = parser.parse_args(args)
    if not parsed.replica:
        parser.error("at least one --replica is required")
    return parsed


def build_router(args):
    return Router(
        args.replica,
        RouterConfig(
            poll_secs=args.poll_secs,
            poll_timeout_secs=args.poll_timeout_secs,
            lease_secs=args.lease_secs,
            breaker_threshold=args.breaker_threshold,
            breaker_cooldown_secs=args.breaker_cooldown_secs,
            hedge_delay_secs=args.hedge_delay_ms / 1000.0,
            dispatch_timeout_secs=args.dispatch_timeout_secs,
            redispatch_window_secs=args.redispatch_window_secs,
            port=args.port,
            telemetry_dir=args.tensorboard_log_dir,
        ),
    )


def main(argv=None):
    args = parse_router_args(argv)
    router = build_router(args).start()
    # name this process's span recorder; spans export to
    # $EDL_TRACE_DIR on stop (plus an atexit backstop)
    from elasticdl_tpu.observability.tracing import configure

    configure(service="router:%d" % router.port)
    done = threading.Event()

    def _graceful(_signum, _frame):
        logger.info("signal received: stopping router")
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    print("ROUTER_READY port=%d" % router.port, flush=True)
    done.wait()
    router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
