"""Replica supervisor/autoscaler: the serving fleet's elasticity core.

The router (serving/router.py) owns the replica REGISTRY — leases,
breakers, load signals — but the fleet behind it is static: a traffic
burst can only shed, and a dead replica is only removed, never
replaced. This module closes the loop the way the master's instance
manager does for worker pods (master/instance_manager.py): a
supervisor that owns GenerationServer replica PROCESSES, holds a
desired-count target, and converges the live roster onto it.

    ReplicaSupervisor ──spawn/SIGTERM/SIGKILL──> replica processes
          │  ^                                        │
          │  └── lease/queue-wait/KV signals ── Router registry
          └───── add_replica / remove_replica ───────┘

One single-threaded decide loop (no watcher threads — every state
transition happens inside `decide_once`, which makes the whole state
machine clockable and unit-testable) runs three passes per tick:

* **poll** — each seat's process is polled for exit and readiness.
  A STARTING seat that prints its `SERVING_READY port=N` line is
  ADOPTED: registered with the live router, journal first. A LIVE
  seat that exits is REAPED and replaced; so is a wedged one, via two
  signals of very different confidence: a replica that SELF-REPORTS
  `health_state == "stalled"` (its runtime-health watchdog,
  observability/runtime_health.py — direct evidence, served off
  threads the wedged scheduler cannot starve) is killed after
  seconds (`stalled_kill_after_secs`), while a replica that merely
  goes silent (lease expired / breaker stuck open — indirect
  evidence that under overload can also mean "busy") keeps the
  deliberately conservative `wedged_after_secs` window. A DRAINING
  seat that exits is RETIRED: unregistered, channel closed.

* **reconcile** — deficit (roster below target) spawns one replica
  per tick, gated by a full-jitter exponential backoff after failures
  and a `max_restarts` consecutive-failure CIRCUIT: a replica that
  cannot come up (bad flags, poisoned checkpoint) must not be
  respawned in a hot loop forever. Surplus drains one replica per
  tick: SIGTERM (the replica advertises `draining`, finishes its
  in-flight work, exits 0), wait for the exit, then retire — never a
  kill of live work on the scale-down path.

* **policy** — the scaling decision itself, driven purely by signals
  the router already aggregates from heartbeats: sustained queue-wait
  EWMA / queue depth above threshold for `up_window_secs` raises the
  target; a fleet that is sustained-idle (no queued, no in-flight,
  queue wait ~0, optional free-KV headroom) for `down_window_secs`
  lowers it. Flapping is structurally impossible: decisions require
  the fleet to be SETTLED (no seat starting or draining), every
  decision starts a `cooldown_secs` dead time, both windows must be
  SUSTAINED (any counter-signal resets them), and min/max bounds cap
  the target.

**Crash-safe supervision**: every lifecycle transition (`spawn` ->
`launched` -> `adopt`, `begin_drain` -> `retire`, `reap`, target
changes) is write-ahead journaled through the master's WAL machinery
(master/state_store.py: journal.jsonl + compacted snapshot, torn-line
tolerant). A supervisor that crashes and restarts replays the journal
and RE-ADOPTS still-alive replicas — attaching to their pids and
re-reading their log files for the ready line — instead of orphaning
or double-spawning them; a seat whose pid died during the outage is
reaped and respawned through the normal deficit path.

Fault injection: the supervisor's three process-boundary hooks are
interceptable under SUPERVISOR_RPCS (common/fault_injection.py) —
`supervisor_spawn` (spawn-fail), `supervisor_ready` (slow-ready), and
`supervisor_adopt` (adopt-drop) — so chaos specs can drill the
failure handling exactly like the servicer boundaries.

Drill: scripts/run_autoscale_drill.py ramps Poisson load through the
real stack and asserts scale-up, SIGKILL replacement, drain-based
scale-down, supervisor crash-recovery, zero accepted-request loss and
a bounded p99 TTFT across every replica-count change.
"""

import os
import random
import signal
import subprocess
import sys
import threading
import time

from elasticdl_tpu.analysis.typestate import JournalProtocol
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.master.state_store import JobStateStore
from elasticdl_tpu.proto import elasticdl_pb2 as pb

STARTING = "starting"
LIVE = "live"
DRAINING = "draining"

#: journal protocol declaration, verified by edl-lint EDL701-704
#: (write/replay closure, payload-schema drift, transition legality,
#: crash-point recoverability) and walked by the spec-derived
#: crash-replay battery in tests. The machine is PER SEAT (entity_key)
#: except for `target`, which is global fleet intent; `absent` and
#: `allocated` name the windows where the journal knows a seat id but
#: no process exists yet.
PROTOCOL = JournalProtocol(
    name="autoscaler",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=("absent", "allocated", STARTING, LIVE, DRAINING),
    initial="absent",
    events={
        "target": {"requires": ("n",), "optional": ("why",)},
        "spawn": {"entity_key": "seat", "from": ("absent",),
                  "to": "allocated"},
        "launched": {"entity_key": "seat", "from": ("allocated",),
                     "to": STARTING, "requires": ("pid",),
                     "optional": ("log",)},
        "adopt": {"entity_key": "seat", "from": (STARTING,),
                  "to": LIVE, "requires": ("pid", "address")},
        "begin_drain": {"entity_key": "seat",
                        "from": (STARTING, LIVE), "to": DRAINING,
                        "optional": ("why",)},
        # `retire` is from-any: supervisor stop retires every seat
        # regardless of phase, not just draining ones
        "retire": {"entity_key": "seat", "from": "*", "to": "absent",
                   "optional": ("rc", "why")},
        "reap": {"entity_key": "seat", "from": "*", "to": "absent",
                 "requires": ("why", "cause")},
    },
    recoverable={
        "absent": "nothing to resume",
        "allocated": "spawn either reached `launched` or the deficit "
                     "path respawns the capacity",
        STARTING: "re-attach the pid and poll readiness from the log",
        LIVE: "re-adopt and re-register with the router",
        DRAINING: "the exit retires it; drain timeout kills stragglers",
    },
)


class AutoscalerConfig(object):
    """Policy + supervision knobs. The scale-up window should be a few
    heartbeat periods (the queue-wait EWMA only moves when polls land);
    cooldown_secs must exceed the router's poll period by enough that a
    decision's effect is VISIBLE in the signals before the next
    decision is allowed — that, plus the settled-fleet gate, is what
    makes flapping structurally impossible rather than merely
    unlikely."""

    def __init__(self, min_replicas=1, max_replicas=4,
                 decide_secs=0.5,
                 up_queue_wait_ms=200.0, up_queue_depth=4,
                 up_window_secs=2.0, up_free_kv_blocks=0,
                 idle_queue_wait_ms=25.0, down_window_secs=6.0,
                 down_free_kv_blocks=0,
                 cooldown_secs=5.0,
                 ready_timeout_secs=180.0, drain_timeout_secs=60.0,
                 wedged_after_secs=30.0, stalled_kill_after_secs=3.0,
                 max_restarts=3, base_delay_secs=0.2,
                 max_delay_secs=5.0,
                 journal_dir="", snapshot_every=100):
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.decide_secs = float(decide_secs)
        self.up_queue_wait_ms = float(up_queue_wait_ms)
        self.up_queue_depth = int(up_queue_depth)
        self.up_window_secs = float(up_window_secs)
        # the decode pool's own scale-up signal in a disaggregated
        # fleet (serving/disagg.py): free+cached paged-KV headroom
        # across decode-capable replicas below this floor is pressure,
        # even while queues look healthy — imported chains and new
        # seats will soon stop fitting. 0 disables (dense pools report
        # no block counts; unified fleets scale on queue-wait alone).
        self.up_free_kv_blocks = int(up_free_kv_blocks)
        self.idle_queue_wait_ms = float(idle_queue_wait_ms)
        self.down_window_secs = float(down_window_secs)
        # scale-down additionally requires this much free paged-KV
        # headroom across the fleet (0 disables the gate — the dense
        # pool reports no block counts)
        self.down_free_kv_blocks = int(down_free_kv_blocks)
        self.cooldown_secs = float(cooldown_secs)
        self.ready_timeout_secs = float(ready_timeout_secs)
        self.drain_timeout_secs = float(drain_timeout_secs)
        self.wedged_after_secs = float(wedged_after_secs)
        # the runtime-health fast path: a replica that SELF-REPORTS
        # `health_state == "stalled"` (its progress watchdog, served
        # off gRPC threads the wedged scheduler cannot starve) is
        # killed after this much SUSTAINED self-report — seconds, not
        # the 30 s lease heuristic, because the evidence is direct:
        # the replica itself says work is seated and nothing commits.
        # The lease-decay path stays as the fallback for pre-health
        # replicas (health_state == "") and for processes too far
        # gone to answer status at all.
        self.stalled_kill_after_secs = float(stalled_kill_after_secs)
        self.max_restarts = int(max_restarts)
        self.base_delay_secs = float(base_delay_secs)
        self.max_delay_secs = float(max_delay_secs)
        self.journal_dir = journal_dir
        self.snapshot_every = int(snapshot_every)


# ----------------------------------------------------------- launchers


def _pid_alive(pid):
    """Liveness for a pid we may or may not be the parent of: reap a
    child zombie via waitpid, fall back to signal 0 + /proc Z-state
    for non-children. Returns (alive, returncode_or_None)."""
    try:
        done, status = os.waitpid(pid, os.WNOHANG)
        if done == 0:
            return True, None
        if hasattr(os, "waitstatus_to_exitcode"):
            return False, os.waitstatus_to_exitcode(status)
        return False, status
    except ChildProcessError:
        pass
    except OSError:
        return False, None
    try:
        os.kill(pid, 0)
    except OSError:
        return False, None
    try:
        with open("/proc/%d/stat" % pid) as f:
            if f.read().split(")")[-1].split()[0] == "Z":
                return False, None
    except (OSError, IndexError):
        pass
    return True, None


def _scan_ready_line(log_path, marker):
    """Port from the `<marker> port=N` line in a replica's log file,
    or None. The log FILE (not a pipe) is what makes readiness
    recoverable: a supervisor that crashed before the line appeared
    can still learn the port after a restart."""
    try:
        with open(log_path, errors="replace") as f:
            for line in f:
                if line.startswith(marker):
                    return int(line.strip().split("port=")[1])
    except (OSError, ValueError, IndexError):
        return None
    return None


class _SpawnedHandle(object):
    """A replica process this supervisor launched (Popen-backed)."""

    def __init__(self, proc, log_path, marker, host):
        self._proc = proc
        self.pid = proc.pid
        self.log_path = log_path
        self._marker = marker
        self._host = host

    def poll(self):
        return self._proc.poll()

    def ready(self):
        port = _scan_ready_line(self.log_path, self._marker)
        return None if port is None else "%s:%d" % (self._host, port)

    def terminate(self):
        if self._proc.poll() is None:
            self._proc.terminate()

    def kill(self):
        if self._proc.poll() is None:
            self._proc.kill()


class _AttachedHandle(object):
    """A replica process inherited from a DEAD supervisor: no Popen,
    just a pid to watch (and its log file for the ready line)."""

    def __init__(self, pid, log_path, marker, host):
        self.pid = pid
        self.log_path = log_path
        self._marker = marker
        self._host = host
        self._rc = None
        self._dead = False

    def poll(self):
        if self._dead:
            return self._rc if self._rc is not None else 1
        alive, rc = _pid_alive(self.pid)
        if alive:
            return None
        self._dead = True
        self._rc = rc
        return self._rc if self._rc is not None else 1

    def ready(self):
        if not self.log_path:
            return None
        port = _scan_ready_line(self.log_path, self._marker)
        return None if port is None else "%s:%d" % (self._host, port)

    def _signal(self, sig):
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self):
        self._signal(signal.SIGTERM)

    def kill(self):
        self._signal(signal.SIGKILL)


class SubprocessReplicaLauncher(object):
    """Launches `python -m elasticdl_tpu.serving.main <replica_args>`
    replicas with stdout+stderr to a per-seat LOG FILE under log_dir —
    never a pipe: a pipe dies with the supervisor, a file survives it,
    which is what lets a restarted supervisor re-read the ready line
    of a replica spawned by its dead predecessor."""

    def __init__(self, replica_args, log_dir, env=None,
                 ready_marker="SERVING_READY", host="localhost",
                 cwd=None):
        self.replica_args = list(replica_args)
        self.log_dir = log_dir
        self.env = dict(env) if env is not None else None
        self.ready_marker = ready_marker
        self.host = host
        self.cwd = cwd
        os.makedirs(log_dir, exist_ok=True)

    def _log_path(self, seat_id):
        return os.path.join(self.log_dir, "replica-%d.log" % seat_id)

    def spawn(self, seat_id):
        cmd = (
            [sys.executable, "-m", "elasticdl_tpu.serving.main"]
            + self.replica_args
        )
        log_path = self._log_path(seat_id)
        log = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                cmd, cwd=self.cwd, env=self.env,
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()  # the child holds its own fd now
        return _SpawnedHandle(proc, log_path, self.ready_marker,
                              self.host)

    def attach(self, seat_id, pid, log_path):
        return _AttachedHandle(pid, log_path, self.ready_marker,
                               self.host)


# ---------------------------------------------------------- supervisor


class _Seat(object):
    """One replica slot in the roster: a process handle plus its
    lifecycle state (starting -> live -> draining -> gone)."""

    __slots__ = ("seat_id", "handle", "state", "address",
                 "spawned_at", "drain_since", "unhealthy_since",
                 "stalled_since")

    def __init__(self, seat_id, handle, state, spawned_at, address=""):
        self.seat_id = seat_id
        self.handle = handle
        self.state = state
        self.address = address
        self.spawned_at = spawned_at
        self.drain_since = None
        self.unhealthy_since = None
        # sustained self-reported stall window (runtime health plane)
        self.stalled_since = None


class ReplicaSupervisor(object):
    """Desired-state supervisor over replica processes + the live
    Router registry. All state transitions run inside `decide_once`
    under one lock; `status_block()` (served through router_status)
    reads under the same lock. Constructing over a journal_dir that
    already has state RECOVERS: still-alive replicas are re-adopted,
    dead ones reaped — never double-spawned, never orphaned."""

    def __init__(self, router, launcher, config=None,
                 clock=time.monotonic, injector=None, rng=None):
        from elasticdl_tpu.common.fault_injection import FaultInjector

        self.config = config or AutoscalerConfig()
        self._router = router
        self._launcher = launcher
        self._clock = clock
        # EDL_FAULT_SPEC arms the supervisor_spawn / supervisor_ready /
        # supervisor_adopt hooks (SUPERVISOR_RPCS) unless an explicit
        # injector is handed in
        self._injector = injector or FaultInjector.from_env()
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._seats = {}
        self._next_seat = 0
        self.target = self.config.min_replicas
        # decision bookkeeping (status_block surfaces all of it)
        self.scale_ups = 0
        self.scale_downs = 0
        self.replacements = 0
        self.spawn_failures = 0
        self.circuit_open = False
        self.last_decision = "init"
        self.last_reason = "supervisor created"
        self.last_decision_at = self._clock()
        self.supervisor_restarts = 0
        # last-logged SLO burn advisory (read-only consumption of the
        # router's burn-rate engine: logged next to the queue-wait
        # policy, never acted on — the signal earns trust in drills
        # before it steers the target)
        self._slo_alerting = ()
        # hysteresis state
        self._above_since = None
        self._idle_since = None
        self._idle_routed = None  # routed count at idle-window start
        self._cooldown_until = 0.0
        self._consec_failures = 0
        self._next_spawn_at = 0.0
        self._stop = threading.Event()
        self._thread = None
        self._store = None
        self._compact_pending = False
        if self.config.journal_dir:
            self._store = JobStateStore(
                self.config.journal_dir,
                snapshot_every=self.config.snapshot_every,
            )
            if self._store.has_state():
                self._recover()
            else:
                self._journal({"ev": "target", "n": self.target,
                               "why": "init"})

    # ------------------------------------------------------- journaling

    def _journal(self, event):
        if self._store is None:
            return
        if self._store.append(event):
            # compaction is DEFERRED to the end of the decide tick:
            # a snapshot taken mid-transition (event journaled, roster
            # not yet mutated) would truncate the journal while
            # silently dropping the in-flight seat — an orphan on
            # recovery
            self._compact_pending = True

    def _maybe_compact(self):
        if self._store is not None and self._compact_pending:
            self._store.write_snapshot(self._state_dict())
            self._compact_pending = False

    def _state_dict(self):
        seats = {}
        for seat in self._seats.values():
            seats[str(seat.seat_id)] = {
                "state": seat.state,
                "pid": seat.handle.pid,
                "address": seat.address,
                "log": getattr(seat.handle, "log_path", ""),
            }
        return {
            "target": self.target,
            "next_seat": self._next_seat,
            "seats": seats,
            "counters": {
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "replacements": self.replacements,
                "spawn_failures": self.spawn_failures,
            },
        }

    @staticmethod
    def _apply_event(state, ev):
        """Replay one journal event onto a snapshot dict. Idempotent
        under replay: transitions for unknown seats are no-ops (the
        snapshot already incorporates them)."""
        kind = ev.get("ev")
        seats = state["seats"]
        sid = str(ev.get("seat", ""))
        counters = state.setdefault("counters", {})

        def bump(name):
            counters[name] = int(counters.get(name, 0)) + 1

        if kind == "target":
            state["target"] = int(ev["n"])
            # decision counters replay from the journal too, so a
            # recovered supervisor reports the roster's full history,
            # not just what happened since the last snapshot
            if ev.get("why") == "scale_up":
                bump("scale_ups")
            elif ev.get("why") == "scale_down":
                bump("scale_downs")
        elif kind == "spawn":
            state["next_seat"] = max(
                state.get("next_seat", 0), int(ev["seat"]) + 1
            )
        elif kind == "launched":
            seats[sid] = {"state": STARTING, "pid": int(ev["pid"]),
                          "address": "", "log": ev.get("log", "")}
        elif kind == "adopt":
            if sid in seats:
                seats[sid]["state"] = LIVE
                seats[sid]["address"] = ev.get("address", "")
        elif kind == "begin_drain":
            if sid in seats:
                seats[sid]["state"] = DRAINING
        elif kind in ("retire", "reap"):
            if kind == "reap":
                # the explicit `cause` key wins; the why-prefix match
                # only decodes journals written before it existed
                cause = ev.get("cause")
                if cause is None:
                    why = str(ev.get("why", ""))
                    if why.startswith("exited"):
                        cause = "replacement"
                    elif why == "dead at recovery":
                        cause = "recovery"
                    else:
                        cause = "spawn_failure"
                if cause == "replacement":
                    bump("replacements")  # unplanned live death
                elif cause == "spawn_failure":
                    bump("spawn_failures")
            seats.pop(sid, None)

    def _recover(self):
        """Rebuild the roster from the journal and RE-ADOPT replicas
        that survived the supervisor outage: attach to their pids, read
        their log files for the address, re-register with the router.
        Dead pids are reaped; the deficit path respawns them."""
        snapshot, events = self._store.load()
        state = snapshot or {"target": self.target, "next_seat": 0,
                             "seats": {}, "counters": {}}
        for ev in events:
            self._apply_event(state, ev)
        self.target = max(
            self.config.min_replicas,
            min(self.config.max_replicas, int(state.get("target", 0))),
        )
        self._next_seat = int(state.get("next_seat", 0))
        counters = state.get("counters", {})
        self.scale_ups = int(counters.get("scale_ups", 0))
        self.scale_downs = int(counters.get("scale_downs", 0))
        self.replacements = int(counters.get("replacements", 0))
        self.spawn_failures = int(counters.get("spawn_failures", 0))
        self.supervisor_restarts = self._store.restart_count
        now = self._clock()
        for sid_text, info in sorted(state.get("seats", {}).items(),
                                     key=lambda kv: int(kv[0])):
            sid = int(sid_text)
            handle = self._launcher.attach(
                sid, int(info["pid"]), info.get("log", "")
            )
            if handle.poll() is not None:
                # died during the outage: reap now (including its
                # stale router registration — the lease would decay
                # it from ROTATION, but the registry entry and its
                # channel must not leak); respawn via the deficit path
                self._journal({"ev": "reap", "seat": sid,
                               "why": "dead at recovery",
                               "cause": "recovery"})
                if info.get("address"):
                    self._router.remove_replica(info["address"])
                continue
            seat = _Seat(sid, handle, info.get("state", STARTING),
                         spawned_at=now,
                         address=info.get("address", ""))
            if seat.state == STARTING:
                # the replica may have become ready while we were
                # dead — the log file remembers
                address = handle.ready()
                if address:
                    seat.address = address
                    seat.state = LIVE
                    self._journal({"ev": "adopt", "seat": sid,
                                   "pid": handle.pid,
                                   "address": address})
            if seat.state in (LIVE, DRAINING) and seat.address:
                self._router.add_replica(seat.address)
            self._seats[sid] = seat
            logger.info(
                "autoscaler recovery: re-adopted seat %d pid %d (%s, "
                "%s)", sid, handle.pid, seat.state,
                seat.address or "no address yet",
            )
        self._record(now, "recover",
                     "re-adopted %d seats from the journal"
                     % len(self._seats))
        self._maybe_compact()

    # -------------------------------------------------------- lifecycle

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="replica-supervisor"
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.decide_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("autoscaler decide tick failed")
            self._stop.wait(self.config.decide_secs)

    def stop(self, grace=60.0):
        """Graceful shutdown: SIGTERM every replica, wait for drains,
        retire the roster, close the journal."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            for seat in self._seats.values():
                seat.handle.terminate()
            deadline = time.monotonic() + grace
            while time.monotonic() < deadline and any(
                s.handle.poll() is None for s in self._seats.values()
            ):
                time.sleep(0.1)
            for seat in list(self._seats.values()):
                if seat.handle.poll() is None:
                    seat.handle.kill()
                self._journal({"ev": "retire", "seat": seat.seat_id,
                               "why": "supervisor stop"})
                if seat.address:
                    self._router.remove_replica(seat.address)
                del self._seats[seat.seat_id]
            self._maybe_compact()
            if self._store is not None:
                self._store.close()

    def abandon(self):
        """Stop deciding WITHOUT journaling or touching any replica —
        the crash-recovery drills' stand-in for supervisor process
        death: the journal and the replica processes are left exactly
        as a SIGKILL would leave them."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._store is not None:
            self._store.close()

    # ------------------------------------------------------ decide tick

    def decide_once(self):
        with self._lock:
            now = self._clock()
            self._poll_seats(now)
            self._policy(now)
            self._reconcile(now)
            self._maybe_compact()

    def _intercept(self, name):
        if self._injector is not None:
            self._injector.intercept(name)

    def _record(self, now, decision, reason):
        self.last_decision = decision
        self.last_reason = reason
        self.last_decision_at = now
        logger.info("autoscaler: %s (%s)", decision, reason)

    # ---- pass 1: seat lifecycle

    def _poll_seats(self, now):
        for seat in list(self._seats.values()):
            rc = seat.handle.poll()
            if seat.state == STARTING:
                self._poll_starting(seat, rc, now)
            elif seat.state == LIVE:
                self._poll_live(seat, rc, now)
            else:  # DRAINING
                self._poll_draining(seat, rc, now)

    def _poll_starting(self, seat, rc, now):
        if rc is not None:
            self._spawn_failed(
                seat, now, "died before ready (rc=%s)" % rc
            )
            return
        if now - seat.spawned_at > self.config.ready_timeout_secs:
            seat.handle.kill()
            self._spawn_failed(
                seat, now,
                "not ready after %.0fs" % self.config.ready_timeout_secs,
            )
            return
        address = seat.handle.ready()
        if not address:
            return
        try:
            # slow-ready faults delay here; adopt-drop faults abort
            # the adoption — the seat is reaped and respawned through
            # the backoff/circuit path like any other spawn failure
            self._intercept("supervisor_ready")
            self._intercept("supervisor_adopt")
        except Exception as e:  # noqa: BLE001 - injected faults
            seat.handle.kill()
            self._spawn_failed(seat, now, "adopt failed: %r" % e)
            return
        seat.address = address
        seat.state = LIVE
        self._journal({"ev": "adopt", "seat": seat.seat_id,
                       "pid": seat.handle.pid, "address": address})
        self._router.add_replica(address)
        self._consec_failures = 0
        logger.info("autoscaler: adopted seat %d -> %s (pid %d)",
                    seat.seat_id, address, seat.handle.pid)

    def _poll_live(self, seat, rc, now):
        if rc is not None:
            self._reap_live(seat, now, "exited rc=%s" % rc)
            return
        rep = self._router_view().get(seat.address)
        # PREFERRED wedge signal — the replica's own runtime-health
        # self-report (observability/runtime_health.py): its progress
        # watchdog declares `stalled` from a thread the wedged
        # scheduler cannot starve, and the evidence is direct (work
        # seated, nothing committing), so the kill budget is seconds.
        # Replicas that don't advertise health (health_state == "")
        # never enter this branch — they keep the conservative
        # lease-decay path below.
        self_stalled = (
            rep is not None and rep.health_state == "stalled"
        )
        if self_stalled:
            if seat.stalled_since is None:
                seat.stalled_since = now
            elif (now - seat.stalled_since
                    >= self.config.stalled_kill_after_secs):
                logger.warning(
                    "autoscaler: seat %d (%s) SELF-REPORTS stalled "
                    "for %.1fs (last_progress_age %.0fms) — killing "
                    "for replacement", seat.seat_id, seat.address,
                    now - seat.stalled_since,
                    rep.last_progress_age_ms,
                )
                seat.handle.kill()  # the exit lands in a later tick
                return
        else:
            seat.stalled_since = None
        # FALLBACK wedge detection: the process is alive but the
        # router cannot renew its lease (SIGSTOP, hard hang) or its
        # breaker never leaves OPEN — either way it serves nothing;
        # replace it. wedged_after_secs must be CONSERVATIVE (default
        # 30s): under hard overload a replica's status RPC can starve
        # behind blocked generate handlers, and shooting the fleet's
        # busiest replica at peak load is the one failure mode worse
        # than a hung one — the lease must stay dead for a long,
        # deliberate window before the supervisor reaches for SIGKILL
        unhealthy = rep is not None and (
            not rep.lease_ok(now) or rep.breaker.state == "open"
        )
        if not unhealthy:
            seat.unhealthy_since = None
            return
        if seat.unhealthy_since is None:
            seat.unhealthy_since = now
            return
        if now - seat.unhealthy_since >= self.config.wedged_after_secs:
            logger.warning(
                "autoscaler: seat %d (%s) wedged for %.1fs — killing "
                "for replacement", seat.seat_id, seat.address,
                now - seat.unhealthy_since,
            )
            seat.handle.kill()  # the exit lands in a later tick

    def _poll_draining(self, seat, rc, now):
        if rc is not None:
            self._journal({"ev": "retire", "seat": seat.seat_id,
                           "rc": rc})
            if seat.address:
                self._router.remove_replica(seat.address)
            del self._seats[seat.seat_id]
            logger.info("autoscaler: retired seat %d (rc=%s)",
                        seat.seat_id, rc)
            return
        if (seat.drain_since is not None
                and now - seat.drain_since
                > self.config.drain_timeout_secs):
            logger.warning(
                "autoscaler: seat %d drain exceeded %.0fs — killing",
                seat.seat_id, self.config.drain_timeout_secs,
            )
            seat.handle.kill()

    def _spawn_failed(self, seat, now, why):
        self._journal({"ev": "reap", "seat": seat.seat_id, "why": why,
                       "cause": "spawn_failure"})
        del self._seats[seat.seat_id]
        self.spawn_failures += 1
        self._consec_failures += 1
        if self._consec_failures >= self.config.max_restarts:
            if not self.circuit_open:
                self.circuit_open = True
                self._record(
                    now, "circuit_open",
                    "%d consecutive spawn failures (last: %s)"
                    % (self._consec_failures, why),
                )
                logger.error(
                    "autoscaler: restart circuit OPEN after %d "
                    "consecutive failures — no more respawns until "
                    "the supervisor is restarted", self._consec_failures,
                )
            return
        delay = self._backoff(self._consec_failures - 1)
        self._next_spawn_at = now + delay
        logger.warning(
            "autoscaler: seat %d spawn failed (%s); retry in %.2fs "
            "(failure %d/%d)", seat.seat_id, why, delay,
            self._consec_failures, self.config.max_restarts,
        )

    def _reap_live(self, seat, now, why):
        """Unplanned loss of a LIVE replica: reap it; the deficit path
        respawns the capacity (bounded by the same backoff/circuit)."""
        self._journal({"ev": "reap", "seat": seat.seat_id, "why": why,
                       "cause": "replacement"})
        if seat.address:
            self._router.remove_replica(seat.address)
        del self._seats[seat.seat_id]
        self.replacements += 1
        self._record(now, "replace",
                     "seat %d %s" % (seat.seat_id, why))

    def _backoff(self, attempt):
        """Full-jitter exponential backoff (AWS-style), on the
        supervisor's own rng so tests can pin it."""
        cap = min(self.config.max_delay_secs,
                  self.config.base_delay_secs * (2 ** attempt))
        return self._rng.uniform(0, cap)

    # ---- pass 2: scaling policy

    def _router_view(self):
        return {r.address: r for r in self._router.replicas()}

    def _slo_advisory(self):
        """Log the router's SLO burn-rate signal READ-ONLY, on every
        change of the alerting set: the operator sees 'the error
        budget is burning' in the same log as the scaling decisions,
        while the decisions themselves stay on the PR 9 queue-wait
        policy. Routers without the engine (old tests' fakes) are
        silently fine."""
        reports = getattr(self._router, "slo_reports", None)
        if reports is None:
            return
        reports = reports()
        alerting = tuple(sorted(
            r["name"] for r in reports if r["alerting"]
        ))
        if alerting == self._slo_alerting:
            return
        if alerting:
            detail = "; ".join(
                "%s fast=%.1fx slow=%.1fx" % (
                    r["name"], r["fast_burn"], r["slow_burn"]
                )
                for r in reports if r["alerting"]
            )
            logger.warning(
                "autoscaler: SLO burn advisory — %s (advisory only; "
                "scaling stays on the queue-wait policy)", detail,
            )
        else:
            logger.info(
                "autoscaler: SLO burn advisory cleared (%s back "
                "under budget)", ", ".join(self._slo_alerting),
            )
        self._slo_alerting = alerting

    def _policy(self, now):
        self._slo_advisory()
        n_starting = sum(1 for s in self._seats.values()
                         if s.state == STARTING)
        n_draining = sum(1 for s in self._seats.values()
                         if s.state == DRAINING)
        live = [s for s in self._seats.values() if s.state == LIVE]
        # decisions only on a SETTLED fleet: while a spawn or a drain
        # is still in flight the last decision's effect is not yet in
        # the signals, and acting again would be acting blind
        if n_starting or n_draining or not live:
            self._above_since = None
            self._idle_since = None
            self._idle_routed = None
            return
        view = self._router_view()
        sigs = [view[s.address] for s in live if s.address in view]
        if not sigs:
            self._above_since = None
            self._idle_since = None
            self._idle_routed = None
            return
        cfg = self.config
        # a disaggregated fleet prices each phase off its OWN signal
        # (serving/disagg.py): prompt pressure queues on the prefill
        # pool, so when one exists the wait/depth terms read only that
        # pool; the decode pool's pressure is KV headroom, read below
        prefill_sigs = [r for r in sigs
                        if getattr(r, "role", "") == "prefill"]
        decode_sigs = [r for r in sigs
                       if getattr(r, "role", "") != "prefill"]
        wait_sigs = prefill_sigs or sigs
        busiest_wait = max(r.queue_wait_ms for r in wait_sigs)
        deepest_queue = max(r.queue_depth for r in wait_sigs)
        quiet = all(
            r.queue_depth == 0 and r.inflight == 0
            and r.active_slots == 0
            for r in sigs
        )
        kv_pressure = False
        if cfg.up_free_kv_blocks > 0 and decode_sigs:
            # free+cached counts as headroom (parked refcount-0
            # chains are evictable on demand), same reading as the
            # scale-down gate below
            kv_pressure = sum(
                r.kv_blocks_free + r.kv_blocks_cached
                for r in decode_sigs
            ) < cfg.up_free_kv_blocks
        # the wait EWMA is a LAGGING signal: alone (frozen from a
        # burst that already ended) it is not pressure — there must be
        # actual work present. quiet and pressure are thus mutually
        # exclusive by construction (the KV term excepted: exhausted
        # headroom is pressure even on a momentarily quiet fleet).
        pressure = ((not quiet
                     and busiest_wait >= cfg.up_queue_wait_ms)
                    or deepest_queue >= cfg.up_queue_depth
                    or kv_pressure)
        # the queue-wait EWMA only moves when requests flow: after a
        # burst stops dead it FREEZES at its last (high) value, so the
        # EWMA gate alone would block scale-down forever. Zero routed
        # traffic across the whole idle window is equally hard
        # evidence of idleness — either satisfies the gate.
        routed = self._router.telemetry.snapshot()["routed"]
        ewma_ok = busiest_wait <= cfg.idle_queue_wait_ms
        no_traffic = (self._idle_routed is not None
                      and routed == self._idle_routed)
        idle = quiet and (ewma_ok or no_traffic)
        if cfg.down_free_kv_blocks > 0:
            # reclaimable cached blocks (refcount-0 prefix chains
            # parked by the shared pool) count as headroom: they are
            # evictable on demand — with sharing on, a drained fleet
            # parks EVERYTHING cached and free alone would read zero
            idle = idle and sum(
                r.kv_blocks_free + r.kv_blocks_cached for r in sigs
            ) >= cfg.down_free_kv_blocks
        self._above_since = (
            (self._above_since or now) if pressure else None
        )
        if quiet:
            if self._idle_routed is None:
                self._idle_routed = routed
        else:
            self._idle_routed = None
        self._idle_since = (self._idle_since or now) if idle else None
        if now < self._cooldown_until:
            return
        if (self._above_since is not None
                and now - self._above_since >= cfg.up_window_secs
                and self.target < cfg.max_replicas):
            self.target += 1
            self.scale_ups += 1
            self._cooldown_until = now + cfg.cooldown_secs
            self._above_since = None
            self._record(
                now, "scale_up",
                "queue_wait %.0fms / depth %d%s sustained %.1fs -> "
                "target %d" % (busiest_wait, deepest_queue,
                               " / decode KV headroom low"
                               if kv_pressure else "",
                               cfg.up_window_secs, self.target),
            )
            self._journal({"ev": "target", "n": self.target,
                           "why": "scale_up"})
        elif (self._idle_since is not None
                and now - self._idle_since >= cfg.down_window_secs
                and self.target > cfg.min_replicas):
            self.target -= 1
            self.scale_downs += 1
            self._cooldown_until = now + cfg.cooldown_secs
            self._idle_since = None
            self._record(
                now, "scale_down",
                "fleet idle %.1fs -> target %d"
                % (cfg.down_window_secs, self.target),
            )
            self._journal({"ev": "target", "n": self.target,
                           "why": "scale_down"})

    # ---- pass 3: converge roster onto target

    def _reconcile(self, now):
        active = [s for s in self._seats.values()
                  if s.state in (STARTING, LIVE)]
        if len(active) < self.target:
            if self.circuit_open or now < self._next_spawn_at:
                return
            self._spawn(now)
        elif len(active) > self.target:
            self._shrink_one(now)

    def _spawn(self, now):
        seat_id = self._next_seat
        self._next_seat += 1
        self._journal({"ev": "spawn", "seat": seat_id})
        try:
            self._intercept("supervisor_spawn")
            handle = self._launcher.spawn(seat_id)
        except Exception as e:  # noqa: BLE001 - spawn-fail drills
            self._journal({"ev": "reap", "seat": seat_id,
                           "why": "spawn raised: %r" % e,
                           "cause": "spawn_failure"})
            self.spawn_failures += 1
            self._consec_failures += 1
            if self._consec_failures >= self.config.max_restarts:
                if not self.circuit_open:
                    self.circuit_open = True
                    self._record(
                        now, "circuit_open",
                        "%d consecutive spawn failures (last: %r)"
                        % (self._consec_failures, e),
                    )
            else:
                self._next_spawn_at = now + self._backoff(
                    self._consec_failures - 1
                )
            logger.warning("autoscaler: spawn of seat %d failed: %r",
                           seat_id, e)
            return
        self._journal({"ev": "launched", "seat": seat_id,
                       "pid": handle.pid,
                       "log": getattr(handle, "log_path", "")})
        self._seats[seat_id] = _Seat(seat_id, handle, STARTING,
                                     spawned_at=now)
        logger.info("autoscaler: spawned seat %d (pid %d)",
                    seat_id, handle.pid)

    def _shrink_one(self, now):
        # prefer aborting a seat that never went live — no work to
        # drain — then the least-loaded live seat, newest first
        starting = [s for s in self._seats.values()
                    if s.state == STARTING]
        if starting:
            seat = max(starting, key=lambda s: s.seat_id)
            seat.handle.kill()
            seat.state = DRAINING  # the exit retires it
            seat.drain_since = now
            self._journal({"ev": "begin_drain", "seat": seat.seat_id,
                           "why": "surplus before ready"})
            return
        view = self._router_view()

        def load(seat):
            rep = view.get(seat.address)
            if rep is None:
                return (0, -seat.seat_id)
            return (rep.queue_depth + rep.active_slots + rep.inflight,
                    -seat.seat_id)

        live = [s for s in self._seats.values() if s.state == LIVE]
        if not live:
            return
        seat = min(live, key=load)
        self._begin_drain(seat, now)

    def _begin_drain(self, seat, now):
        self._journal({"ev": "begin_drain", "seat": seat.seat_id})
        seat.state = DRAINING
        seat.drain_since = now
        # SIGTERM -> the replica closes admission, advertises
        # `draining` (the router takes it out of rotation for NEW
        # requests), finishes in-flight work and exits 0; the exit is
        # what retires the seat
        seat.handle.terminate()
        logger.info("autoscaler: draining seat %d (%s)",
                    seat.seat_id, seat.address)

    # ----------------------------------------------------------- status

    def counts(self):
        with self._lock:
            return {
                state: sum(1 for s in self._seats.values()
                           if s.state == state)
                for state in (STARTING, LIVE, DRAINING)
            }

    def roster(self):
        """Snapshot of the seats (drills/tests/operator tooling)."""
        with self._lock:
            return [
                {"seat": s.seat_id, "state": s.state,
                 "pid": s.handle.pid, "address": s.address}
                for s in sorted(self._seats.values(),
                                key=lambda s: s.seat_id)
            ]

    def status_block(self):
        """The router_status autoscaler block (pb.AutoscalerStatus)."""
        with self._lock:
            now = self._clock()
            n = {state: 0 for state in (STARTING, LIVE, DRAINING)}
            for seat in self._seats.values():
                n[seat.state] += 1
            return pb.AutoscalerStatus(
                enabled=True,
                target=self.target,
                live=n[LIVE],
                starting=n[STARTING],
                draining=n[DRAINING],
                scale_ups=self.scale_ups,
                scale_downs=self.scale_downs,
                replacements=self.replacements,
                spawn_failures=self.spawn_failures,
                circuit_open=self.circuit_open,
                last_decision=self.last_decision,
                last_reason=self.last_reason,
                last_decision_age_secs=max(
                    0.0, now - self.last_decision_at
                ),
                supervisor_restarts=self.supervisor_restarts,
            )
