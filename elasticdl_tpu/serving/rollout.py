"""Zero-downtime fleet-wide model rollout: a journaled wave controller.

Hot reload (serving/hot_reload.py) is a per-replica affair; this module
makes it a FLEET operation — the production story elasticdl was about,
elasticity of the *model*, not just the fleet. The controller takes a
target checkpoint version and drives every registered replica through

    stage -> canary -> judge -> progressive waves -> commit

with every transition write-ahead journaled through the same
JobStateStore the master roster and the replica supervisor trust, so a
controller crash+restart resumes mid-wave with no double-swap and no
replica left on a mixed version.

Judgment is evidence-based, never a timer:

* **stage** — the checkpoint must pass verify_checkpoint (shard-set
  completeness + sha256 digests) BEFORE any replica swaps: a torn or
  bit-flipped checkpoint aborts with zero fleet impact. The controller
  then records the parity baseline: the pinned prompt set generated
  greedily on the canary while it still serves the OLD version.
* **canary** — plan[0] reloads via the explicit reload_checkpoint RPC
  while the router steers new traffic away (hold_replica ahead of the
  replica's own `draining` advertisement).
* **judge** — the canary must (a) reproduce the recorded old-version
  tokens on the pinned prompts (greedy parity: silent weight corruption
  shows up as token drift long before it shows up in latency), and
  (b) survive a soak window with the fast-window SLO burn below the
  failure threshold (slow-burn-only is NOT a failure — the slow window
  reflects history that predates the canary). No verdict inside
  judge_timeout_secs is itself a verdict: no promotion.
* **waves** — the rest of the plan swaps in wave_size chunks, each wave
  soaked against the multi-window alert (both burns > 1.0). An alert
  pauses the rollout and rolls back every already-swapped replica in
  REVERSE swap order, canary last — the replica that has served the new
  version longest is the last to lose it, maximizing the evidence
  window if the operator wants to inspect.

The wave lifecycle is an edl-lint EDL501 pair: every `begin_wave` must
settle with `commit_wave` or `rollback_wave` on the same receiver, and
every `stage_checkpoint` with `activate` or `discard` (CheckpointStager
below). The controller's own calls go through `self.` receivers —
cross-tick lifecycles are the lint rule's documented escape — but any
external driver inherits the discipline.

Ownership mirrors the autoscaler: router_main owns the controller's
lifecycle, the controller calls INTO the router (hold/release,
replicas, slo_reports) and never the reverse while a router lock is
held. `abandon()` stops deciding WITHOUT journaling — the rollout
drill's stand-in for controller SIGKILL.
"""

import threading
import time

from elasticdl_tpu.analysis.typestate import JournalProtocol
from elasticdl_tpu.checkpoint.saver import verify_checkpoint
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.master.state_store import JobStateStore
from elasticdl_tpu.proto import elasticdl_pb2 as pb

# lifecycle phases; terminal ones price the controller at zero
STAGING = "staging"
CANARY = "canary"
JUDGING = "judging"
WAVE = "wave"
ROLLING_BACK = "rolling_back"
IDLE = "idle"
COMMITTED = "committed"
ROLLED_BACK = "rolled_back"
ABORTED = "aborted"
TERMINAL = (IDLE, COMMITTED, ROLLED_BACK, ABORTED)

#: Declared journal protocol: the single source of truth edl-lint
#: (EDL701-EDL704) verifies _apply_event() and every _journal() site
#: against, and the machine the spec-derived crash-point replay
#: battery walks (tests/test_protocol_batteries.py). ``swap_start`` is
#: informational by design: recovery re-derives swap truth from the
#: replicas' own advertised model_version at the next tick (see
#: _recover), so the event exists for forensics, not replay. Every
#: state is recoverable — the decide loop resumes from any journaled
#: phase — which is exactly the crash-point-closure property EDL704
#: holds future edits to.
PROTOCOL = JournalProtocol(
    name="rollout",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=(IDLE, STAGING, CANARY, JUDGING, WAVE, ROLLING_BACK,
            COMMITTED, ROLLED_BACK, ABORTED),
    initial=IDLE,
    terminal=TERMINAL,
    events={
        "begin": {"from": TERMINAL, "to": STAGING,
                  "requires": ("target", "old", "plan"),
                  "optional": ("dir",)},
        "phase": {"to_key": "to", "optional": ("why",)},
        "staged": {"from": (STAGING,),
                   "optional": ("baseline", "manifest")},
        "swap_start": {"informational": True,
                       "requires": ("addr", "to")},
        "swap_done": {"requires": ("addr", "to", "ok"),
                      "optional": ("note", "why")},
        "judge": {"from": (JUDGING,), "requires": ("verdict",)},
        "wave_begin": {"from": (WAVE,),
                       "requires": ("wave", "addrs")},
        "wave_commit": {"from": (WAVE,), "requires": ("wave",)},
        "wave_rollback": {"from": (WAVE,), "requires": ("wave",)},
        "commit": {"from": (WAVE,), "to": COMMITTED},
    },
    transitions={
        STAGING: (CANARY, ABORTED),
        CANARY: (JUDGING, ROLLING_BACK, ABORTED),
        JUDGING: (WAVE, ROLLING_BACK, ABORTED),
        WAVE: (ROLLING_BACK, ABORTED),
        ROLLING_BACK: (ROLLED_BACK,),
    },
    recoverable={
        IDLE: "nothing in flight",
        STAGING: "re-stage the checkpoint (staging is idempotent)",
        CANARY: "re-swap the canary; advertised versions dedupe",
        JUDGING: "judgment restarts; the soak clock re-arms",
        WAVE: "wave membership is journaled; resume the open wave",
        ROLLING_BACK: "re-walk swapped[] in reverse; no-ops dedupe",
        COMMITTED: "terminal",
        ROLLED_BACK: "terminal",
        ABORTED: "terminal",
    },
)


def burn_verdict(reports, fast_burn_fail=1.0):
    """Canary burn judgment over one slo_reports() evaluation.

    Returns (failed, reason). The rule is deliberately asymmetric:
    a FAST-window burn above the threshold fails (the fast window is
    dominated by canary-era samples), while a slow-burn-only breach
    passes — the slow window averages over history the canary never
    touched, and failing on it would veto every rollout that follows a
    rough patch. Windows with no samples are silent, not passing
    evidence; the timeout fail-safe covers the nothing-measured case.
    """
    for r in reports:
        if (float(r.get("fast_burn", 0.0)) > fast_burn_fail
                and int(r.get("fast_samples", 0)) > 0):
            return True, "%s fast burn %.2f > %.2f" % (
                r.get("name", "?"), r["fast_burn"], fast_burn_fail
            )
    return False, ""


def wave_alerting(reports):
    """Objectives in multi-window alert (both burns > 1.0) — the wave
    pause trigger. Stricter than the canary rule on purpose: by wave
    time the new version has already passed judgment once, so only the
    page-worthy signal (fast AND slow burning) reverses the fleet."""
    return [r.get("name", "?") for r in reports if r.get("alerting")]


def parity_verdict(baseline, actual, min_match=1.0):
    """Greedy-parity judgment: actual[i] must reproduce baseline[i]
    exactly for at least min_match of the pinned prompts. Returns
    (failed, matched, total). min_match < 1.0 is the operator's knob
    for rollouts whose weights legitimately changed; the default treats
    any drift as corruption, which is right for replica-sync rollouts
    of the SAME training lineage."""
    total = len(baseline)
    if total == 0:
        return False, 0, 0
    matched = sum(
        1 for want, got in zip(baseline, actual)
        if list(want) == list(got)
    )
    return (matched < min_match * total), matched, total


class CheckpointStager(object):
    """The stage_checkpoint -> activate | discard lifecycle (EDL501
    pair): stage verifies the target version's integrity manifest and
    holds it; activate hands the manifest to the caller as the staged
    artifact's acceptance; discard closes the failure path. Nothing is
    copied — replicas read the checkpoint store themselves — so the
    'resource' is the acceptance obligation, like abort_transfer's."""

    def __init__(self, checkpoint_dir, injector=None):
        self._dir = checkpoint_dir
        self._injector = injector
        self._manifest = None
        self._error = None

    def stage_checkpoint(self, version):
        """Verify `version` end to end. Returns True when it staged
        clean; the failure detail waits on discard()."""
        if self._injector is not None:
            self._injector.intercept("checkpoint_read")
        try:
            self._manifest = verify_checkpoint(self._dir, version)
            return True
        except Exception as e:  # noqa: BLE001 - structured verdict
            self._error = e
            return False

    def activate(self):
        """Accept the staged checkpoint; returns its manifest."""
        if self._manifest is None:
            raise RuntimeError("activate() without a staged checkpoint")
        manifest, self._manifest = self._manifest, None
        return manifest

    def discard(self):
        """Close the failure path; returns the verification error."""
        error, self._error, self._manifest = self._error, None, None
        return error


class RolloutConfig(object):
    """Knobs for the wave controller. checkpoint_dir is the store every
    replica reads (the same --checkpoint_dir they watch); journal_dir
    enables write-ahead journaling + crash recovery."""

    def __init__(self, checkpoint_dir="", journal_dir="",
                 snapshot_every=64, decide_secs=0.5, wave_size=1,
                 soak_secs=3.0, judge_timeout_secs=60.0,
                 swap_timeout_secs=120.0, parity_prompts=((1, 2, 3),),
                 parity_max_tokens=8, parity_min_match=1.0,
                 fast_burn_fail=1.0, rpc_timeout_secs=30.0):
        self.checkpoint_dir = checkpoint_dir
        self.journal_dir = journal_dir
        self.snapshot_every = int(snapshot_every)
        self.decide_secs = float(decide_secs)
        self.wave_size = max(1, int(wave_size))
        self.soak_secs = float(soak_secs)
        self.judge_timeout_secs = float(judge_timeout_secs)
        self.swap_timeout_secs = float(swap_timeout_secs)
        self.parity_prompts = tuple(
            tuple(int(t) for t in p) for p in parity_prompts
        )
        self.parity_max_tokens = int(parity_max_tokens)
        self.parity_min_match = float(parity_min_match)
        self.fast_burn_fail = float(fast_burn_fail)
        self.rpc_timeout_secs = float(rpc_timeout_secs)


class RolloutController(object):
    """The journaled canary -> judge -> waves -> commit state machine.

    swap_fn(address, version) -> (ok, serving_version, error) and
    generate_fn(address, prompt, max_tokens) -> [tokens] are injectable
    for unit tests; the defaults speak the real Serving RPC surface.
    reports_fn defaults to router.slo_reports (the PR 12 burn engine's
    cached heartbeat evaluation, consumed read-only)."""

    def __init__(self, router, config=None, clock=time.monotonic,
                 injector=None, swap_fn=None, generate_fn=None,
                 reports_fn=None):
        from elasticdl_tpu.common.fault_injection import FaultInjector

        self.config = config or RolloutConfig()
        self._router = router
        self._clock = clock
        self._injector = injector or FaultInjector.from_env()
        self._swap_fn = swap_fn or self._default_swap
        self._generate_fn = generate_fn or self._default_generate
        self._reports_fn = reports_fn or router.slo_reports
        self._lock = threading.Lock()
        # rollout state (journal-backed; _state_dict is the schema)
        self.phase = IDLE
        self.target_version = 0
        self.old_version = 0
        self.plan = []
        self.versions = {}
        self.swapped = []  # swap order, rollback reverses it
        self.baseline = []
        self.verdict = ""
        self.wave = 0
        self.wave_committed = 0
        self.wave_addrs = []
        self.last_error = ""
        self.swaps = 0
        self.rollbacks = 0
        self.rollout_restarts = 0
        self._pending_target = None
        # in-memory only (soak windows restart conservatively after a
        # controller crash — a resumed judge re-earns its verdict)
        self._judge_started = None
        self._parity_ok = False
        self._soak_until = None
        self._stop = threading.Event()
        self._thread = None
        self._store = None
        self._compact_pending = False
        if self.config.journal_dir:
            self._store = JobStateStore(
                self.config.journal_dir,
                snapshot_every=self.config.snapshot_every,
            )
            if self._store.has_state():
                self._recover()

    # ------------------------------------------------------- journaling

    def _journal(self, event):
        if self._store is None:
            return
        if self._store.append(event):
            # compaction is DEFERRED to the end of the decide tick —
            # same rule as the supervisor: a snapshot taken between an
            # event landing and the in-memory transition completing
            # would truncate the journal around a half-applied swap
            self._compact_pending = True

    def _maybe_compact(self):
        if self._store is not None and self._compact_pending:
            self._store.write_snapshot(self._state_dict())
            self._compact_pending = False

    def _state_dict(self):
        return {
            "phase": self.phase,
            "target": self.target_version,
            "old": self.old_version,
            "dir": self.config.checkpoint_dir,
            "plan": list(self.plan),
            "versions": dict(self.versions),
            "swapped": list(self.swapped),
            "baseline": [list(t) for t in self.baseline],
            "verdict": self.verdict,
            "wave": self.wave,
            "wave_committed": self.wave_committed,
            "wave_addrs": list(self.wave_addrs),
            "last_error": self.last_error,
            "counters": {
                "swaps": self.swaps,
                "rollbacks": self.rollbacks,
            },
        }

    @staticmethod
    def _apply_event(state, ev):
        """Replay one journal event onto a snapshot dict. Idempotent
        under replay: a swap_done for an address already at the target
        version only rewrites the same value, and the swapped list is
        set-deduplicated — the no-double-swap invariant holds however
        many times the tail of the journal replays."""
        kind = ev.get("ev")
        counters = state.setdefault("counters", {})
        if kind == "begin":
            state.update(
                phase=STAGING, target=int(ev["target"]),
                old=int(ev["old"]), plan=list(ev["plan"]),
                dir=ev.get("dir", ""),
                versions={a: int(ev["old"]) for a in ev["plan"]},
                swapped=[], baseline=[], verdict="", wave=0,
                wave_committed=0, wave_addrs=[], last_error="",
            )
        elif kind == "phase":
            state["phase"] = ev["to"]
            if "why" in ev:
                state["last_error"] = ev["why"]
        elif kind == "staged":
            state["baseline"] = [list(t) for t in ev.get("baseline", [])]
        elif kind == "swap_done":
            if not ev.get("ok"):
                return
            addr, to = ev["addr"], int(ev["to"])
            state.setdefault("versions", {})[addr] = to
            swapped = state.setdefault("swapped", [])
            if to == int(state.get("target", -1)):
                if addr not in swapped:
                    swapped.append(addr)
                counters["swaps"] = int(counters.get("swaps", 0)) + 1
            else:
                if addr in swapped:
                    swapped.remove(addr)
                if ev.get("why") == "rollback":
                    counters["rollbacks"] = (
                        int(counters.get("rollbacks", 0)) + 1
                    )
        elif kind == "judge":
            state["verdict"] = ev["verdict"]
        elif kind == "wave_begin":
            state["wave"] = int(ev["wave"])
            state["wave_addrs"] = list(ev["addrs"])
        elif kind == "wave_commit":
            state["wave_committed"] = int(ev["wave"])
            state["wave_addrs"] = []
        elif kind == "wave_rollback":
            state["wave_addrs"] = []
        elif kind == "commit":
            # first-sweep EDL701 fix: a crash between the commit event
            # and the phase transition used to replay back into WAVE
            # and re-run the commit path; the event now IS the
            # transition, so the journal prefix [..., commit] recovers
            # straight to COMMITTED
            state["phase"] = COMMITTED

    def _recover(self):
        """Rebuild the rollout from the journal: snapshot + event
        replay, then resume deciding from the recovered phase. Swap
        truth is double-checked against the replicas' own advertised
        model_version at the next tick, so an event journaled but not
        yet acted on (or acted on but not yet journaled) converges
        without a second reload landing."""
        snapshot, events = self._store.load()
        state = snapshot or self._state_dict()
        for ev in events:
            self._apply_event(state, ev)
        self.phase = state.get("phase", IDLE)
        self.target_version = int(state.get("target", 0))
        self.old_version = int(state.get("old", 0))
        if state.get("dir"):
            # the begin event carries the checkpoint store, so a bare
            # --rollout_journal_dir restart resumes without re-stating
            # --rollout_checkpoint_dir (or --rollout itself)
            self.config.checkpoint_dir = state["dir"]
        self.plan = list(state.get("plan", []))
        self.versions = dict(state.get("versions", {}))
        self.swapped = list(state.get("swapped", []))
        self.baseline = [list(t) for t in state.get("baseline", [])]
        self.verdict = state.get("verdict", "")
        self.wave = int(state.get("wave", 0))
        self.wave_committed = int(state.get("wave_committed", 0))
        self.wave_addrs = list(state.get("wave_addrs", []))
        self.last_error = state.get("last_error", "")
        counters = state.get("counters", {})
        self.swaps = int(counters.get("swaps", 0))
        self.rollbacks = int(counters.get("rollbacks", 0))
        self.rollout_restarts = self._store.restart_count
        logger.info(
            "rollout controller recovered: phase=%s target=%d "
            "swapped=%d/%d (restart #%d)", self.phase,
            self.target_version, len(self.swapped), len(self.plan),
            self.rollout_restarts,
        )
        self._maybe_compact()

    # -------------------------------------------------------- lifecycle

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rollout-controller"
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.decide_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("rollout decide tick failed")
            self._stop.wait(self.config.decide_secs)

    def stop(self):
        """Graceful shutdown: stop deciding, release any held replica,
        close the journal. An in-flight rollout stays journaled — the
        next controller over this journal_dir resumes it."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        for addr in list(self._router.held_replicas()):
            self._router.release_replica(addr)
        with self._lock:
            self._maybe_compact()
            if self._store is not None:
                self._store.close()

    def abandon(self):
        """Stop deciding WITHOUT journaling or releasing anything —
        the rollout drill's stand-in for controller SIGKILL: journal
        and fleet are left exactly as a kill would leave them."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._store is not None:
            self._store.close()

    def _intercept(self, name):
        if self._injector is not None:
            self._injector.intercept(name)

    # ------------------------------------------------------- public API

    def begin(self, target_version):
        """Start a rollout to `target_version`. The plan is the fleet
        as registered right now, sorted by address; plan[0] is the
        canary. Returns False (with last_error set) when a rollout is
        already in flight or no replicas are registered."""
        with self._lock:
            return self._begin_locked(target_version)

    def request(self, target_version):
        """Deferred begin: the rollout starts at the first decide tick
        that finds a registered fleet — the CLI path, where --rollout
        is parsed long before the autoscaler has spawned anything."""
        with self._lock:
            self._pending_target = int(target_version)

    def _begin_locked(self, target_version):
        if self.phase not in TERMINAL:
            self.last_error = (
                "rollout already in flight (phase=%s)" % self.phase
            )
            return False
        reps = {r.address: r for r in self._router.replicas()}
        plan = sorted(reps)
        if not plan:
            self.last_error = "no replicas registered"
            return False
        old = int(reps[plan[0]].model_version)
        ev = {"ev": "begin", "target": int(target_version),
              "old": old, "plan": plan,
              "dir": self.config.checkpoint_dir}
        self._journal(ev)
        self._apply_to_self(ev)
        self._judge_started = None
        self._parity_ok = False
        self._soak_until = None
        logger.info(
            "rollout begin: version-%d -> version-%d over %d "
            "replicas (canary %s)", old, int(target_version),
            len(plan), plan[0],
        )
        return True

    def decide_once(self):
        with self._lock:
            if (self._pending_target is not None
                    and self.phase in TERMINAL):
                # already-satisfied request (a restart re-passing the
                # same --rollout over a committed journal) is a no-op
                if (self.phase == COMMITTED
                        and self._pending_target == self.target_version):
                    self._pending_target = None
                elif self._begin_locked(self._pending_target):
                    self._pending_target = None
            if self.phase == STAGING:
                self._tick_staging()
            elif self.phase == CANARY:
                self._tick_canary()
            elif self.phase == JUDGING:
                self._tick_judging()
            elif self.phase == WAVE:
                self._tick_wave()
            elif self.phase == ROLLING_BACK:
                self._tick_rollback()
            self._maybe_compact()

    def status_block(self):
        with self._lock:
            waves_total = 0
            if self.plan:
                rest = len(self.plan) - 1
                waves_total = 1 + (
                    (rest + self.config.wave_size - 1)
                    // self.config.wave_size
                )
            return pb.RolloutStatus(
                enabled=True,
                phase=self.phase,
                target_version=self.target_version,
                old_version=self.old_version,
                wave=self.wave,
                waves_total=waves_total,
                swapped=len(self.swapped),
                fleet=len(self.plan),
                canary=self.plan[0] if self.plan else "",
                verdict=self.verdict,
                last_error=self.last_error,
                rollbacks=self.rollbacks,
                rollout_restarts=self.rollout_restarts,
            )

    # ------------------------------------------------------ wave API
    # (EDL501 pair: begin_wave settles with commit_wave|rollback_wave)

    def begin_wave(self, wave, addrs):
        """Open wave `wave` over `addrs` and swap each member to the
        target version. Returns True when every member converged.
        Idempotent under resume: members already advertising the
        target are journaled as done without a second reload."""
        if self.wave != wave or list(self.wave_addrs) != list(addrs):
            self._journal({"ev": "wave_begin", "wave": wave,
                           "addrs": list(addrs)})
            self.wave = wave
            self.wave_addrs = list(addrs)
        return self._swap_unit(addrs, self.target_version)

    def commit_wave(self, wave):
        self._journal({"ev": "wave_commit", "wave": wave})
        self.wave_committed = wave
        self.wave_addrs = []
        self._soak_until = None

    def rollback_wave(self, wave, why):
        """Close the wave on the failure path and turn the whole
        rollout around: journal the pause evidence, then enter the
        reverse-order rollback of every swapped replica."""
        self._journal({"ev": "wave_rollback", "wave": wave})
        self.wave_addrs = []
        self._soak_until = None
        self._enter_rollback(why)

    # ------------------------------------------------------ phase ticks

    def _tick_staging(self):
        cfg = self.config
        stager = CheckpointStager(cfg.checkpoint_dir, self._injector)
        if not stager.stage_checkpoint(self.target_version):
            err = stager.discard()
            self._abort("checkpoint failed verification: %s" % err)
            return
        manifest = stager.activate()
        # parity baseline: the pinned prompts generated greedily on the
        # canary while it still serves the OLD version — recorded
        # before any swap so judgment compares against ground truth
        baseline = []
        canary = self.plan[0]
        try:
            for prompt in cfg.parity_prompts:
                baseline.append(list(self._generate_fn(
                    canary, list(prompt), cfg.parity_max_tokens
                )))
        except Exception as e:  # noqa: BLE001 - staging must not raise
            self._abort("parity baseline generation failed: %r" % e)
            return
        ev = {"ev": "staged", "baseline": baseline,
              "manifest": manifest}
        self._journal(ev)
        self._apply_to_self(ev)
        self._set_phase(CANARY)
        logger.info(
            "rollout staged version-%d (%d digests verified), "
            "baseline over %d prompts", self.target_version,
            manifest.get("verified_digests", 0), len(baseline),
        )

    def _tick_canary(self):
        if self._swap_unit([self.plan[0]], self.target_version):
            self._judge_started = None
            self._parity_ok = False
            self._set_phase(JUDGING)
        else:
            self._enter_rollback(
                "canary swap failed: %s" % self.last_error
            )

    def _tick_judging(self):
        cfg = self.config
        now = self._clock()
        if self._judge_started is None:
            self._judge_started = now
        if now - self._judge_started > cfg.judge_timeout_secs:
            # the fail-safe: no verdict IS a verdict — no promotion
            self._judge("timeout", "no verdict within %.0fs"
                        % cfg.judge_timeout_secs)
            return
        try:
            self._intercept("rollout_judge")
            if not self._parity_ok:
                actual = [
                    list(self._generate_fn(
                        self.plan[0], list(p), cfg.parity_max_tokens
                    ))
                    for p in cfg.parity_prompts
                ]
                failed, matched, total = parity_verdict(
                    self.baseline, actual, cfg.parity_min_match
                )
                if failed:
                    self._judge(
                        "parity_fail",
                        "canary reproduced %d/%d pinned prompts"
                        % (matched, total),
                    )
                    return
                self._parity_ok = True
            failed, reason = burn_verdict(
                self._reports_fn(), cfg.fast_burn_fail
            )
            if failed:
                self._judge("burn_fail", reason)
                return
        except Exception as e:  # noqa: BLE001 - no evidence this tick
            # an injected/judge-path failure yields NO verdict; the
            # timeout above converts sustained silence into a fail
            logger.warning("rollout judge evaluation failed: %r", e)
            return
        if now - self._judge_started >= cfg.soak_secs:
            self._judge("pass", "")

    def _judge(self, verdict, why):
        self._journal({"ev": "judge", "verdict": verdict})
        self.verdict = verdict
        if verdict == "pass":
            logger.info("rollout canary judged: pass")
            self._set_phase(WAVE)
        else:
            logger.warning("rollout canary judged: %s (%s)",
                           verdict, why)
            self._enter_rollback("canary %s: %s" % (verdict, why))

    def _tick_wave(self):
        cfg = self.config
        # resume or open the next wave: 1-based over plan[1:] chunks
        rest = self.plan[1:]
        if self.wave_addrs:
            wave, addrs = self.wave, list(self.wave_addrs)
        else:
            wave = self.wave_committed + 1
            lo = (wave - 1) * cfg.wave_size
            addrs = rest[lo:lo + cfg.wave_size]
            if not addrs:
                ev = {"ev": "commit"}
                self._journal(ev)
                self._apply_to_self(ev)
                logger.info(
                    "rollout committed: fleet of %d on version-%d "
                    "(%d swaps)", len(self.plan), self.target_version,
                    self.swaps,
                )
                return
        if not self.begin_wave(wave, addrs):
            self.rollback_wave(
                wave, "wave %d swap failed: %s" % (wave, self.last_error)
            )
            return
        now = self._clock()
        if self._soak_until is None:
            self._soak_until = now + cfg.soak_secs
        alerting = wave_alerting(self._reports_fn())
        if alerting:
            self.rollback_wave(
                wave, "SLO burn alert during wave %d: %s"
                % (wave, ", ".join(alerting)),
            )
            return
        if now >= self._soak_until:
            self.commit_wave(wave)

    def _tick_rollback(self):
        # reverse swap order, canary last
        pending = [a for a in reversed(self.swapped)]
        for addr in pending:
            if not self._swap_one(addr, self.old_version,
                                  why="rollback"):
                # a replica that cannot roll back keeps its
                # reload_failed latch advertised; retry next tick
                logger.error(
                    "rollout rollback of %s blocked: %s",
                    addr, self.last_error,
                )
                return
        self._set_phase(ROLLED_BACK)
        logger.warning(
            "rollout rolled back: fleet of %d uniform on version-%d",
            len(self.plan), self.old_version,
        )

    # ------------------------------------------------------- swap plumbing

    def _swap_unit(self, addrs, to_version):
        """Swap every address to `to_version`; True when all converged.
        Skips members whose journaled or ADVERTISED version already
        matches — the advertised check is what makes resume-after-kill
        single-swap: a reload that landed before the crash but after
        the swap_start journal entry is recognized, not repeated."""
        for addr in addrs:
            if not self._swap_one(addr, to_version):
                return False
        return True

    def _swap_one(self, addr, to_version, why=""):
        if self.versions.get(addr) == to_version:
            return True
        reps = {r.address: r for r in self._router.replicas()}
        rep = reps.get(addr)
        if rep is None:
            # left the fleet mid-rollout (autoscaler scale-down); its
            # replacement spawns on whatever the checkpoint dir's
            # latest is — nothing to swap here
            ev = {"ev": "swap_done", "addr": addr, "to": to_version,
                  "ok": True, "note": "gone"}
            self._journal(ev)
            self._apply_to_self(ev)
            return True
        if (int(rep.model_version) == to_version
                and not rep.reload_failed):
            ev = {"ev": "swap_done", "addr": addr, "to": to_version,
                  "ok": True, "note": "already-serving"}
            if why:
                ev["why"] = why
            self._journal(ev)
            self._apply_to_self(ev)
            return True
        self._journal({"ev": "swap_start", "addr": addr,
                       "to": to_version})
        self._router.hold_replica(addr)
        try:
            self._intercept("rollout_swap")
            ok, serving, error = self._swap_fn(addr, to_version)
        except Exception as e:  # noqa: BLE001 - structured failure
            ok, serving, error = False, -1, "%r" % (e,)
        finally:
            self._router.release_replica(addr)
        ev = {"ev": "swap_done", "addr": addr, "to": to_version,
              "ok": bool(ok)}
        if why:
            ev["why"] = why
        self._journal(ev)
        self._apply_to_self(ev)
        if not ok:
            self.last_error = "swap %s -> version-%d: %s" % (
                addr, to_version, error
            )
            logger.error("rollout %s", self.last_error)
        return bool(ok)

    # ------------------------------------------------------- transitions

    def _apply_to_self(self, ev):
        """Route an event through the SAME replay function recovery
        uses, then adopt the result — one transition code path, so
        live state and recovered state cannot drift."""
        state = self._state_dict()
        self._apply_event(state, ev)
        self.phase = state["phase"]
        self.target_version = int(state["target"])
        self.old_version = int(state["old"])
        self.plan = list(state["plan"])
        self.versions = dict(state["versions"])
        self.swapped = list(state["swapped"])
        self.baseline = [list(t) for t in state["baseline"]]
        self.verdict = state["verdict"]
        self.wave = int(state["wave"])
        self.wave_committed = int(state["wave_committed"])
        self.wave_addrs = list(state["wave_addrs"])
        self.last_error = state["last_error"]
        self.swaps = int(state["counters"].get("swaps", 0))
        self.rollbacks = int(state["counters"].get("rollbacks", 0))

    def _set_phase(self, phase, why=None):
        ev = {"ev": "phase", "to": phase}
        if why is not None:
            ev["why"] = why
        self._journal(ev)
        self._apply_to_self(ev)

    def _enter_rollback(self, why):
        logger.warning("rollout pausing + rolling back: %s", why)
        if self.swapped:
            self._set_phase(ROLLING_BACK, why=why)
        else:
            # nothing swapped yet — the fleet never left the old
            # version, so this is an abort, not a rollback
            self._abort(why)

    def _abort(self, why):
        logger.error("rollout aborted: %s", why)
        self._set_phase(ABORTED, why=why)

    # ------------------------------------------------------- default RPCs

    def _default_swap(self, address, version):
        from elasticdl_tpu.proto.service import (
            ServingStub,
            build_channel,
        )

        channel = build_channel(address)
        try:
            resp = ServingStub(channel).reload_checkpoint(
                pb.ReloadCheckpointRequest(version=version),
                timeout=self.config.swap_timeout_secs,
            )
            return bool(resp.ok), int(resp.model_version), resp.error
        finally:
            channel.close()

    def _default_generate(self, address, prompt, max_tokens):
        from elasticdl_tpu.proto.service import (
            ServingStub,
            build_channel,
        )

        channel = build_channel(address)
        try:
            resp = ServingStub(channel).generate(
                pb.GenerateRequest(
                    prompt=list(prompt), max_new_tokens=max_tokens,
                    temperature=0.0,  # greedy: parity needs determinism
                ),
                timeout=self.config.rpc_timeout_secs,
            )
            return list(resp.tokens)
        finally:
            channel.close()


def build_rollout(args, router):
    """router_main helper: construct the controller from CLI args (None
    when no --rollout_journal_dir was given — the rollout plane is
    opt-in and idle-priced, exactly like the autoscaler)."""
    if not getattr(args, "rollout_journal_dir", ""):
        return None
    prompts = parse_parity_prompts(
        getattr(args, "rollout_parity_prompts", "")
    )
    cfg = RolloutConfig(
        checkpoint_dir=args.rollout_checkpoint_dir,
        journal_dir=args.rollout_journal_dir,
        wave_size=args.rollout_wave_size,
        soak_secs=args.rollout_soak_secs,
        judge_timeout_secs=args.rollout_judge_timeout_secs,
        parity_prompts=prompts or ((1, 2, 3),),
    )
    return RolloutController(router, cfg)


def parse_parity_prompts(text):
    """CLI grammar for the pinned prompt set: semicolon-separated
    comma-lists of token ids — "1,2,3;4,5" -> ((1,2,3),(4,5))."""
    prompts = []
    for part in (text or "").split(";"):
        part = part.strip()
        if not part:
            continue
        prompts.append(
            tuple(int(t) for t in part.split(",") if t.strip())
        )
    return tuple(prompts)
