"""Serving telemetry on the dependency-free TensorBoard event path.

Same substrate as the master's training gauges (common/tb_events.py —
the recovery gauges ride it too), so one TensorBoard logdir shows the
whole system. Gauges, stepped by decode-step index:

    serving/queue_depth        queued backlog at the step
    serving/active_slots       slots decoding at the step
    serving/step_ms            wall time of the decode step
    serving/tokens_per_sec     tokens committed / wall over the window
    serving/ttft_ms            per-request time-to-first-token (written
                               at each request's first token)
    serving/kv_bytes_in_use    KV bytes live requests pin at the step
    serving/kv_blocks_free     paged pool's free blocks at the step
    serving/kv_host_blocks     spilled chain blocks parked host-side
    serving/kv_host_bytes      host spill-tier bytes at the step
    serving/queue_wait_ms      EWMA of time-queued-before-seating (the
                               router's load signal; ServerStatus field)
    serving/ttft_p99_ms        histogram percentiles, one scalar per
    serving/e2e_p99_ms         flush window (see below)
    serving/admitted_total     monotone counters, one scalar per flush
    serving/rejected_total
    serving/expired_total
    serving/completed_total
    serving/reloads_total

Latency distributions live in fixed-bucket log-linear histograms
(observability/histogram.py) — TTFT, queue wait, step time and
end-to-end latency — NOT in point-gauges: the status RPCs report
p50/p90/p99 from them, the router merges the raw bucket counts across
replicas, and bench_serving.py computes its percentiles with the same
histogram code, so bench numbers and live numbers are definitionally
identical.

The snapshot derives the memory-efficiency headline
`kv_bytes_per_token` = sum-over-steps(kv_bytes_in_use) /
tokens_generated: the average KV bytes RESIDENT per generated token.
The dense pool pins every seated slot's full `seq_len` stripe, the
paged pool only the blocks written so far — this ratio is where the
difference shows up as one number.

Counters also back the ServerStatus RPC via snapshot() — the RPC must
work with telemetry disabled (no log_dir), so counters live here and
the event writer is optional. The counter NAME SET is closed
(`COUNTERS`): count() raises on anything undeclared, because a typo'd
name would silently fork a fresh counter and under-report the real
one forever (edl-lint EDL401 flags literal call sites statically; the
raise catches dynamic names).

Thread-safety: the scheduler thread writes step gauges; gRPC threads
bump admission counters and read snapshots — everything under one lock
(the writes are tiny appends; contention is negligible next to a decode
step)."""

import threading
import time

from elasticdl_tpu.common.tb_events import EventFileWriter
from elasticdl_tpu.observability.histogram import LogLinearHistogram


class ServingTelemetry(object):
    #: the closed counter set — count() REJECTS anything else.
    #: prefix_hit_tokens counts prompt tokens seated by shared-prefix
    #: incref (never re-prefilled), cow_copies the copy-on-write
    #: faults, draft_proposed/draft_accepted the speculative-decode
    #: proposal economy (accept rate = accepted / proposed).
    #: The tiered-KV trio: revive_uploads counts batched host->device
    #: revival scatters, prefill_tokens_revived the prompt tokens
    #: those uploads seated WITHOUT re-running prefill (the host
    #: tier's whole reason to exist), host_drops the spilled entries
    #: the bounded host LRU (or a reload flush) discarded.
    COUNTERS = ("admitted", "rejected", "expired", "completed",
                "tokens_generated", "reloads", "prefix_hit_tokens",
                "cow_copies", "draft_proposed", "draft_accepted",
                "revive_uploads", "prefill_tokens_revived",
                "host_drops")
    #: latency histograms (ms), all on the shared bucket scheme
    HISTOGRAMS = ("ttft_ms", "queue_wait_ms", "step_ms", "e2e_ms")

    def __init__(self, log_dir=None, flush_every=50, clock=time.monotonic):
        self._log_dir = log_dir
        self._flush_every = max(1, int(flush_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._writer = None
        self._started = clock()
        self.counters = {name: 0 for name in self.COUNTERS}
        self.hists = {name: LogLinearHistogram()
                      for name in self.HISTOGRAMS}
        self.max_active_slots = 0
        self.kv_bytes_in_use_peak = 0
        self._kv_byte_steps = 0  # sum of kv_bytes_in_use over steps
        self._queue_wait_ewma_ms = 0.0
        self._queue_waits_seen = 0
        self._step = 0
        self._window_tokens = 0
        self._window_t0 = clock()
        self._counters_flushed_at = 0  # step of the last counter flush
        self._dirty = False  # anything recorded since the last flush

    def _ensure_writer(self):
        if self._writer is None and self._log_dir:
            self._writer = EventFileWriter(
                self._log_dir, filename_suffix=".serving"
            )
        return self._writer

    def _scalar(self, tag, value, step):
        writer = self._ensure_writer()
        if writer is not None:
            writer.add_scalar(tag, float(value), step)

    # ------------------------------------------------------------ events

    def count(self, name, n=1):
        with self._lock:
            if name not in self.counters:
                raise ValueError(
                    "unknown serving counter %r (declared: %s) — a "
                    "typo here would silently fork a new counter"
                    % (name, ", ".join(self.COUNTERS))
                )
            self.counters[name] += n
            self._dirty = True

    def reset_latency(self):
        """Drop the latency DISTRIBUTIONS (histograms + the queue-wait
        EWMA) without touching the monotone counters. The pre-ready
        warmup path (serving/main.py --warmup_tokens) calls this so
        the jit-compile latency of a request no client ever sent can
        never surface in the percentiles a router/autoscaler SLOs on."""
        with self._lock:
            for name in self.hists:
                self.hists[name] = LogLinearHistogram()
            self._queue_wait_ewma_ms = 0.0
            self._queue_waits_seen = 0

    def record_ttft(self, request):
        """Time-to-first-token for one request, at its first token."""
        ttft_ms = (self._clock() - request.submitted_at) * 1000.0
        with self._lock:
            self._dirty = True
            self.hists["ttft_ms"].record(ttft_ms)
            self._scalar("serving/ttft_ms", ttft_ms, self._step)
        return ttft_ms

    def record_e2e(self, latency_ms):
        """End-to-end latency of one COMPLETED request (admission ->
        final token). Expired/rejected requests don't land here — the
        histogram answers "how long does a successful request take",
        the counters answer how many weren't."""
        with self._lock:
            self._dirty = True
            self.hists["e2e_ms"].record(latency_ms)

    # EWMA, not a running mean: the router reads this as a LOAD signal,
    # so it must track the current regime, not the lifetime average
    QUEUE_WAIT_ALPHA = 0.3

    def record_queue_wait(self, wait_secs):
        """Time one request spent queued before seating. Feeds the
        queue_wait_ms EWMA the router folds into least-loaded routing
        (ServerStatus.queue_wait_ms) and the queue-wait histogram
        behind the percentile fields."""
        wait_ms = wait_secs * 1000.0
        with self._lock:
            if self._queue_waits_seen == 0:
                self._queue_wait_ewma_ms = wait_ms
            else:
                a = self.QUEUE_WAIT_ALPHA
                self._queue_wait_ewma_ms = (
                    a * wait_ms + (1.0 - a) * self._queue_wait_ewma_ms
                )
            self._queue_waits_seen += 1
            self.hists["queue_wait_ms"].record(wait_ms)
            self._scalar("serving/queue_wait_ms",
                         self._queue_wait_ewma_ms, self._step)
        return wait_ms

    def record_step(self, queue_depth, active_slots, step_secs,
                    tokens_committed, kv_bytes_in_use=None,
                    kv_blocks_free=None, kv_host_blocks=None,
                    kv_host_bytes=None):
        """Per-decode-step gauges; counters flush every flush_every
        steps so the event file stays O(steps / flush_every)."""
        with self._lock:
            self._dirty = True
            self._step += 1
            self.max_active_slots = max(
                self.max_active_slots, active_slots
            )
            self.counters["tokens_generated"] += tokens_committed
            self._window_tokens += tokens_committed
            self.hists["step_ms"].record(step_secs * 1000.0)
            if kv_bytes_in_use is not None:
                self.kv_bytes_in_use_peak = max(
                    self.kv_bytes_in_use_peak, kv_bytes_in_use
                )
                self._kv_byte_steps += kv_bytes_in_use
                self._scalar("serving/kv_bytes_in_use",
                             kv_bytes_in_use, self._step)
            if kv_blocks_free is not None:
                self._scalar("serving/kv_blocks_free",
                             kv_blocks_free, self._step)
            if kv_host_blocks is not None:
                self._scalar("serving/kv_host_blocks",
                             kv_host_blocks, self._step)
            if kv_host_bytes is not None:
                self._scalar("serving/kv_host_bytes",
                             kv_host_bytes, self._step)
            self._scalar("serving/queue_depth", queue_depth, self._step)
            self._scalar("serving/active_slots", active_slots, self._step)
            self._scalar(
                "serving/step_ms", step_secs * 1000.0, self._step
            )
            if self._step % self._flush_every == 0:
                self._flush_window_locked()

    def _flush_window_locked(self):
        """Close the tokens/sec window and write the counter totals +
        headline percentiles. Caller holds the lock."""
        now = self._clock()
        window = max(now - self._window_t0, 1e-9)
        self._scalar(
            "serving/tokens_per_sec",
            self._window_tokens / window, self._step,
        )
        self._window_tokens = 0
        self._window_t0 = now
        for name, value in self.counters.items():
            self._scalar(
                "serving/%s_total" % name, value, self._step
            )
        for hist_name in ("ttft_ms", "e2e_ms"):
            hist = self.hists[hist_name]
            if hist.count:
                self._scalar(
                    "serving/%s_p99" % hist_name.replace("_ms", ""),
                    hist.percentile(99), self._step,
                )
        self._counters_flushed_at = self._step
        self._dirty = False

    # ---------------------------------------------------------- snapshot

    def snapshot(self):
        with self._lock:
            snap = dict(self.counters)
            snap["max_active_slots"] = self.max_active_slots
            snap["uptime_secs"] = self._clock() - self._started
            snap["steps"] = self._step
            snap["kv_bytes_in_use_peak"] = self.kv_bytes_in_use_peak
            snap["kv_bytes_per_token"] = (
                self._kv_byte_steps
                / max(1, self.counters["tokens_generated"])
            )
            snap["queue_wait_ms"] = self._queue_wait_ewma_ms
            for prefix in ("ttft", "queue_wait", "e2e", "step"):
                hist = self.hists[prefix + "_ms"]
                for q in (50, 90, 99):
                    snap["%s_p%d_ms" % (prefix, q)] = hist.percentile(q)
            snap["ttft_hist"] = self.hists["ttft_ms"].to_counts()
            snap["queue_wait_hist"] = (
                self.hists["queue_wait_ms"].to_counts()
            )
            return snap

    def close(self):
        """Flush the tail, then close the writer. Without this a
        server stopped mid-window under-reported in TensorBoard: the
        partial tokens/sec window and every counter bump since the
        last flush_every boundary never reached the event file."""
        with self._lock:
            if self._log_dir and self._dirty:
                # _flush_window_locked creates the writer on demand, so
                # even a server that never reached a flush boundary
                # leaves its final counters on disk
                self._flush_window_locked()
            if self._writer is not None:
                self._writer.close()
                self._writer = None


class RouterTelemetry(object):
    """The routing tier's gauges/counters on the same event path.

    Gauges, stepped by heartbeat-poll index (the router has no decode
    steps — its clock is the lease-renewal loop):

        router/healthy_replicas   replicas in rotation at the poll
        router/replicas           registered replicas
        router/routed_total       monotone counters, one scalar per
        router/completed_total    flush (routed = accepted dispatches,
        router/redispatched_total completed = returned OK, redispatched
        router/hedges_total       = re-sent after a replica failure,
        router/hedge_wins_total   shed = RESOURCE_EXHAUSTED with no
        router/shed_total         healthy replica, breaker_trips =
        router/breaker_trips_total  closed->open transitions)

    Counters back the router_status RPC via snapshot() — like the
    replica telemetry, the RPC must work with the writer disabled.
    The counter name set is closed (count() raises on unknowns;
    edl-lint EDL401 is the static twin). The router's end-to-end
    dispatch latency (accept -> terminal outcome, re-dispatches and
    hedges included) rides the shared log-linear histogram behind the
    e2e_p* router_status fields, and snapshot() carries the
    last-observed rotation gauges so operators aren't left scraping
    the event file for fleet size."""

    COUNTERS = ("routed", "completed", "redispatched", "hedges",
                "hedge_wins", "shed", "breaker_trips", "errors")

    def __init__(self, log_dir=None, flush_every=20, clock=time.monotonic):
        self._log_dir = log_dir
        self._flush_every = max(1, int(flush_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._writer = None
        self._started = clock()
        self._poll = 0
        self.counters = {name: 0 for name in self.COUNTERS}
        self.hists = {"e2e_ms": LogLinearHistogram()}
        # last-observed rotation gauges (record_poll), surfaced by
        # snapshot()/router_status
        self._healthy_replicas = 0
        self._replicas = 0

    def _ensure_writer(self):
        if self._writer is None and self._log_dir:
            self._writer = EventFileWriter(
                self._log_dir, filename_suffix=".router"
            )
        return self._writer

    def _scalar(self, tag, value, step):
        writer = self._ensure_writer()
        if writer is not None:
            writer.add_scalar(tag, float(value), step)

    def count(self, name, n=1):
        with self._lock:
            if name not in self.counters:
                raise ValueError(
                    "unknown router counter %r (declared: %s)"
                    % (name, ", ".join(self.COUNTERS))
                )
            self.counters[name] += n
            self._dirty = True

    def record_e2e(self, latency_ms):
        """Router-observed end-to-end latency of one dispatch that
        reached a terminal outcome."""
        with self._lock:
            self.hists["e2e_ms"].record(latency_ms)

    def record_poll(self, healthy, replicas):
        """One heartbeat sweep: rotation-size gauges now, counters
        every flush_every polls."""
        with self._lock:
            self._poll += 1
            self._healthy_replicas = healthy
            self._replicas = replicas
            self._scalar("router/healthy_replicas", healthy, self._poll)
            self._scalar("router/replicas", replicas, self._poll)
            if self._poll % self._flush_every == 0:
                for name, value in self.counters.items():
                    self._scalar(
                        "router/%s_total" % name, value, self._poll
                    )

    def snapshot(self):
        with self._lock:
            snap = dict(self.counters)
            snap["uptime_secs"] = self._clock() - self._started
            snap["polls"] = self._poll
            snap["healthy_replicas"] = self._healthy_replicas
            snap["replicas"] = self._replicas
            for q in (50, 90, 99):
                snap["e2e_p%d_ms" % q] = (
                    self.hists["e2e_ms"].percentile(q)
                )
            return snap

    def close(self):
        with self._lock:
            if self._writer is not None:
                for name, value in self.counters.items():
                    self._scalar(
                        "router/%s_total" % name, value, self._poll
                    )
                self._writer.close()
                self._writer = None
