"""Serving telemetry on the dependency-free TensorBoard event path.

Same substrate as the master's training gauges (common/tb_events.py —
the recovery gauges ride it too), so one TensorBoard logdir shows the
whole system. Gauges, stepped by decode-step index:

    serving/queue_depth        queued backlog at the step
    serving/active_slots       slots decoding at the step
    serving/step_ms            wall time of the decode step
    serving/tokens_per_sec     tokens committed / wall over the window
    serving/ttft_ms            per-request time-to-first-token (written
                               at each request's first token)
    serving/kv_bytes_in_use    KV bytes live requests pin at the step
    serving/kv_blocks_free     paged pool's free blocks at the step
    serving/kv_host_blocks     spilled chain blocks parked host-side
    serving/kv_host_bytes      host spill-tier bytes at the step
    serving/queue_wait_ms      EWMA of time-queued-before-seating (the
                               router's load signal; ServerStatus field)
    serving/ttft_p99           histogram percentiles, one scalar per
    serving/e2e_p99            flush window (see below)
    serving/prefix_hit_rate_window  windowed share of prompt tokens
                               seated by prefix incref/revival — the
                               warm-capacity signal (ring-derived)
    serving/admitted_total     monotone counters, one scalar per flush
    serving/rejected_total
    serving/expired_total
    serving/completed_total
    serving/reloads_total

Latency distributions live in fixed-bucket log-linear histograms
(observability/histogram.py) — TTFT, queue wait, step time and
end-to-end latency — NOT in point-gauges: the status RPCs report
p50/p90/p99 from them, the router merges the raw bucket counts across
replicas, and bench_serving.py computes its percentiles with the same
histogram code, so bench numbers and live numbers are definitionally
identical.

The LIVE signal plane (observability/metrics.py): every telemetry
object also feeds a windowed **TimeSeriesRing** — fixed-interval
snapshots of counter deltas, last gauges and histogram BUCKET deltas —
which is what the Prometheus `/metrics` exposition, the windowed
prefix-hit-rate and the router's SLO burn-rate engine read. The ring
and the tb_events path flush through the SAME lock at the SAME points,
and `close()` lands the final partial window in BOTH: a server stopped
mid-window reports identical totals to the event file and to the last
ring window (pinned by a regression test).

The snapshot derives the memory-efficiency headline
`kv_bytes_per_token` = sum-over-steps(kv_bytes_in_use) /
tokens_generated: the average KV bytes RESIDENT per generated token.

Counters also back the ServerStatus RPC via snapshot() — the RPC must
work with telemetry disabled (no log_dir), so counters live here and
the event writer is optional. The counter NAME SET is closed
(`COUNTERS`): count() raises on anything undeclared, because a typo'd
name would silently fork a fresh counter and under-report the real
one forever. The GAUGE set is closed the same way (`GAUGES` /
`gauge()`) — a typo'd gauge tag would fork a dead TensorBoard series
and a dead Prometheus series just as silently. edl-lint EDL401 flags
literal call sites of BOTH statically; the raises catch dynamic names.

Thread-safety: the scheduler thread writes step gauges; gRPC threads
bump admission counters and read snapshots; the metrics-exposition
thread reads `prometheus()` — everything under one lock (the writes
are tiny appends; contention is negligible next to a decode step)."""

import threading
import time

from elasticdl_tpu.common.tb_events import EventFileWriter
from elasticdl_tpu.observability.forensics import CAUSES
from elasticdl_tpu.observability.histogram import LogLinearHistogram
from elasticdl_tpu.observability.metrics import (
    TimeSeriesRing,
    counter_family,
    gauge_family,
    hist_family,
    labeled_counter_family,
)


class ServingTelemetry(object):
    #: the closed counter set — count() REJECTS anything else.
    #: prefix_hit_tokens counts prompt tokens seated by shared-prefix
    #: incref (never re-prefilled), prompt_tokens EVERY prompt token
    #: seated (the hit-rate denominator), cow_copies the copy-on-write
    #: faults, draft_proposed/draft_accepted the speculative-decode
    #: proposal economy (accept rate = accepted / proposed).
    #: The tiered-KV trio: revive_uploads counts batched host->device
    #: revival scatters, prefill_tokens_revived the prompt tokens
    #: those uploads seated WITHOUT re-running prefill, host_drops the
    #: spilled entries the bounded host LRU (or a reload flush)
    #: discarded.
    #: The runtime-health pair (observability/runtime_health.py):
    #: steady_recompiles counts post-warmup-boundary recompiles of an
    #: already-compiled executable (the zero-recompile anomaly class;
    #: the per-fn distribution is the sentry's own labeled
    #: edl_serving_recompiles_total{fn=} family), stalls the
    #: ok->stalled watchdog transitions (work seated, no progress for
    #: the budget — each one also dumps a diagnostic bundle).
    COUNTERS = ("admitted", "rejected", "expired", "completed",
                "tokens_generated", "reloads", "prefix_hit_tokens",
                "prompt_tokens", "cow_copies", "draft_proposed",
                "draft_accepted", "revive_uploads",
                "prefill_tokens_revived", "host_drops",
                "steady_recompiles", "stalls")
    #: the closed gauge set — gauge()/_gauge_locked REJECT anything
    #: else, exactly like the counters (EDL401 is the static twin for
    #: both). These are the serving/<name> TensorBoard tags and the
    #: edl_serving_<name> Prometheus series.
    #: last_progress_age_ms / memory_unaccounted_bytes are the
    #: runtime-health plane's scrape surface (watchdog age at the
    #: last reconcile; the memory accountant's monotone PEAK
    #: unaccounted-drift watermark)
    GAUGES = ("queue_depth", "active_slots", "step_ms",
              "tokens_per_sec", "ttft_ms", "queue_wait_ms",
              "kv_bytes_in_use", "kv_blocks_free", "kv_host_blocks",
              "kv_host_bytes", "ttft_p99", "e2e_p99",
              "prefix_hit_rate_window", "last_progress_age_ms",
              "memory_unaccounted_bytes")
    #: latency histograms (ms), all on the shared bucket scheme
    HISTOGRAMS = ("ttft_ms", "queue_wait_ms", "step_ms", "e2e_ms")
    #: the closed slow-cause label set (observability/forensics.py
    #: CAUSES — single source of truth): count_slow_cause() REJECTS
    #: anything else, exactly like count()/gauge(), and EDL401 is the
    #: static twin. One labeled Prometheus counter family
    #: (edl_serving_slow_cause_total{cause=...}) makes the
    #: DISTRIBUTION OF WHY terminally-slow requests were slow
    #: scrapeable, not just the that.
    SLOW_CAUSES = CAUSES
    #: the windowed prefix-hit-rate's trailing horizon (secs): long
    #: enough to smooth a single burst, short enough that a router
    #: reading it sees the CURRENT warm-capacity regime
    PREFIX_HIT_HORIZON_SECS = 30.0

    def __init__(self, log_dir=None, flush_every=50, clock=time.monotonic,
                 ring_secs=1.0, ring_windows=240, exemplars=True):
        self._log_dir = log_dir
        self._flush_every = max(1, int(flush_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._writer = None
        self._started = clock()
        # exemplars=False drops trace ids at the record sites (the
        # overhead A/B's OFF leg); the histograms themselves are
        # unchanged either way
        self._exemplars = bool(exemplars)
        self.counters = {name: 0 for name in self.COUNTERS}
        self.gauges = {name: 0.0 for name in self.GAUGES}
        self.slow_causes = {name: 0 for name in self.SLOW_CAUSES}
        self.hists = {name: LogLinearHistogram()
                      for name in self.HISTOGRAMS}
        # the live metrics plane: windowed counter/bucket deltas
        # (observability/metrics.py), fed under this lock at flush
        # cadence; /metrics, the SLO engine and the windowed
        # prefix-hit-rate all read it
        self.ring = TimeSeriesRing(interval_secs=ring_secs,
                                   capacity=ring_windows, clock=clock)
        self.max_active_slots = 0
        self.kv_bytes_in_use_peak = 0
        self._kv_byte_steps = 0  # sum of kv_bytes_in_use over steps
        self._queue_wait_ewma_ms = 0.0
        self._queue_waits_seen = 0
        self._step = 0
        self._window_tokens = 0
        self._window_t0 = clock()
        self._counters_flushed_at = 0  # step of the last counter flush
        self._dirty = False  # anything recorded since the last flush

    def _ensure_writer(self):
        if self._writer is None and self._log_dir:
            self._writer = EventFileWriter(
                self._log_dir, filename_suffix=".serving"
            )
        return self._writer

    def _scalar(self, tag, value, step):
        writer = self._ensure_writer()
        if writer is not None:
            writer.add_scalar(tag, float(value), step)

    def _gauge_locked(self, name, value, step=None):
        """One gauge write: last-value for the ring/exposition + a
        TensorBoard scalar. Closed set — see the class docstring.
        Caller holds the lock."""
        if name not in self.gauges:
            raise ValueError(
                "unknown serving gauge %r (declared: %s) — a typo "
                "here would fork a dead series"
                % (name, ", ".join(self.GAUGES))
            )
        self.gauges[name] = float(value)
        self._scalar("serving/%s" % name, value,
                     self._step if step is None else step)

    def gauge(self, name, value):
        """Public gauge entry for callers outside this class (the
        engine, the supervisor); internal call sites already hold the
        lock and use _gauge_locked."""
        with self._lock:
            self._gauge_locked(name, value)

    def _ring_observe_locked(self, roll=True):
        """Feed the ring one CUMULATIVE snapshot (it differences at
        window boundaries). Caller holds the lock. Copying the trimmed
        bucket lists is the whole cost, so hot paths gate this behind
        ring.due(). Slow-cause counts ride as `slow_cause.<cause>`
        counters so window deltas carry the why-distribution too."""
        counters = dict(self.counters)
        for cause, n in self.slow_causes.items():
            counters["slow_cause.%s" % cause] = n
        self.ring.observe(
            counters=counters,
            gauges=self.gauges,
            hists={name: h.to_counts()
                   for name, h in self.hists.items()},
            exemplars={name: h.exemplars
                       for name, h in self.hists.items()
                       if h.exemplars},
            roll=roll,
        )

    # ------------------------------------------------------------ events

    def count(self, name, n=1):
        with self._lock:
            if name not in self.counters:
                raise ValueError(
                    "unknown serving counter %r (declared: %s) — a "
                    "typo here would silently fork a new counter"
                    % (name, ", ".join(self.COUNTERS))
                )
            self.counters[name] += n
            self._dirty = True

    def count_slow_cause(self, cause, n=1):
        """One terminally-slow request attributed to `cause` — the
        dominant label forensics.attribute() produced. Closed set,
        same contract as count(): a typo'd cause would silently fork a
        dead series."""
        with self._lock:
            if cause not in self.slow_causes:
                raise ValueError(
                    "unknown slow cause %r (declared: %s) — a typo "
                    "here would silently fork a new series"
                    % (cause, ", ".join(self.SLOW_CAUSES))
                )
            self.slow_causes[cause] += n
            self._dirty = True

    def reset_latency(self):
        """Drop the latency DISTRIBUTIONS (histograms + the queue-wait
        EWMA) without touching the monotone counters. The pre-ready
        warmup path (serving/main.py --warmup_tokens) calls this so
        the jit-compile latency of a request no client ever sent can
        never surface in the percentiles a router/autoscaler SLOs on.
        The ring restarts with the histograms: a warmup window must
        not seed the burn-rate horizon either."""
        with self._lock:
            for name in self.hists:
                self.hists[name] = LogLinearHistogram()
            self._queue_wait_ewma_ms = 0.0
            self._queue_waits_seen = 0
            self.ring = TimeSeriesRing(
                interval_secs=self.ring.interval_secs,
                capacity=self.ring.capacity, clock=self._clock,
            )

    def record_ttft(self, request):
        """Time-to-first-token for one request, at its first token.
        The request's trace_id rides into the TTFT histogram as a
        bucket exemplar, so a scraped p99 bucket names a real trace."""
        ttft_ms = (self._clock() - request.submitted_at) * 1000.0
        trace_id = (getattr(request, "trace_id", "")
                    if self._exemplars else "")
        with self._lock:
            self._dirty = True
            self.hists["ttft_ms"].record(ttft_ms,
                                         trace_id=trace_id or None)
            self._gauge_locked("ttft_ms", ttft_ms)
            if self.ring.due():
                self._ring_observe_locked()
        return ttft_ms

    def record_e2e(self, latency_ms, trace_id=None):
        """End-to-end latency of one COMPLETED request (admission ->
        final token). Expired/rejected requests don't land here — the
        histogram answers "how long does a successful request take",
        the counters answer how many weren't."""
        with self._lock:
            self._dirty = True
            self.hists["e2e_ms"].record(
                latency_ms,
                trace_id=trace_id if self._exemplars else None,
            )

    # EWMA, not a running mean: the router reads this as a LOAD signal,
    # so it must track the current regime, not the lifetime average
    QUEUE_WAIT_ALPHA = 0.3

    def record_queue_wait(self, wait_secs, trace_id=None):
        """Time one request spent queued before seating. Feeds the
        queue_wait_ms EWMA the router folds into least-loaded routing
        (ServerStatus.queue_wait_ms) and the queue-wait histogram
        behind the percentile fields."""
        wait_ms = wait_secs * 1000.0
        with self._lock:
            if self._queue_waits_seen == 0:
                self._queue_wait_ewma_ms = wait_ms
            else:
                a = self.QUEUE_WAIT_ALPHA
                self._queue_wait_ewma_ms = (
                    a * wait_ms + (1.0 - a) * self._queue_wait_ewma_ms
                )
            self._queue_waits_seen += 1
            self.hists["queue_wait_ms"].record(
                wait_ms,
                trace_id=trace_id if self._exemplars else None,
            )
            self._gauge_locked("queue_wait_ms",
                               self._queue_wait_ewma_ms)
        return wait_ms

    def record_step(self, queue_depth, active_slots, step_secs,
                    tokens_committed, kv_bytes_in_use=None,
                    kv_blocks_free=None, kv_host_blocks=None,
                    kv_host_bytes=None):
        """Per-decode-step gauges; counters flush every flush_every
        steps so the event file stays O(steps / flush_every)."""
        with self._lock:
            self._dirty = True
            self._step += 1
            self.max_active_slots = max(
                self.max_active_slots, active_slots
            )
            self.counters["tokens_generated"] += tokens_committed
            self._window_tokens += tokens_committed
            self.hists["step_ms"].record(step_secs * 1000.0)
            if kv_bytes_in_use is not None:
                self.kv_bytes_in_use_peak = max(
                    self.kv_bytes_in_use_peak, kv_bytes_in_use
                )
                self._kv_byte_steps += kv_bytes_in_use
                self._gauge_locked("kv_bytes_in_use", kv_bytes_in_use)
            if kv_blocks_free is not None:
                self._gauge_locked("kv_blocks_free", kv_blocks_free)
            if kv_host_blocks is not None:
                self._gauge_locked("kv_host_blocks", kv_host_blocks)
            if kv_host_bytes is not None:
                self._gauge_locked("kv_host_bytes", kv_host_bytes)
            self._gauge_locked("queue_depth", queue_depth)
            self._gauge_locked("active_slots", active_slots)
            self._gauge_locked("step_ms", step_secs * 1000.0)
            if self._step % self._flush_every == 0:
                self._flush_window_locked()
            if self.ring.due():
                self._ring_observe_locked()

    def _prefix_hit_rate_locked(self):
        """Windowed warm-capacity signal: the share of prompt tokens
        seated WITHOUT paying prefill compute (prefix incref + spilled
        revival) over the trailing horizon — closed ring windows plus
        the open partial, so the first seconds of a burst already
        register. Caller holds the lock."""
        horizon = self.PREFIX_HIT_HORIZON_SECS
        # the live partial comes from the COUNTERS directly (the ring
        # only learns cumulative values at observe points, which the
        # hot path gates behind ring.due()) — live minus the open
        # window's baseline is the pending delta
        hit = (self.ring.sum_counter("prefix_hit_tokens", horizon)
               + self.counters["prefix_hit_tokens"]
               - self.ring.baseline_counter("prefix_hit_tokens"))
        total = (self.ring.sum_counter("prompt_tokens", horizon)
                 + self.counters["prompt_tokens"]
                 - self.ring.baseline_counter("prompt_tokens"))
        return hit / total if total > 0 else 0.0

    def _flush_window_locked(self):
        """Close the tokens/sec window and write the counter totals +
        headline percentiles. Caller holds the lock."""
        now = self._clock()
        window = max(now - self._window_t0, 1e-9)
        self._gauge_locked(
            "tokens_per_sec", self._window_tokens / window
        )
        self._window_tokens = 0
        self._window_t0 = now
        for name, value in self.counters.items():
            self._scalar(
                "serving/%s_total" % name, value, self._step
            )
        for hist_name in ("ttft_ms", "e2e_ms"):
            hist = self.hists[hist_name]
            if hist.count:
                self._gauge_locked(
                    "%s_p99" % hist_name.replace("_ms", ""),
                    hist.percentile(99),
                )
        self._gauge_locked("prefix_hit_rate_window",
                           self._prefix_hit_rate_locked())
        self._counters_flushed_at = self._step
        self._dirty = False

    # ---------------------------------------------------------- snapshot

    def snapshot(self):
        with self._lock:
            snap = dict(self.counters)
            snap["max_active_slots"] = self.max_active_slots
            snap["uptime_secs"] = self._clock() - self._started
            snap["steps"] = self._step
            snap["kv_bytes_in_use_peak"] = self.kv_bytes_in_use_peak
            snap["kv_bytes_per_token"] = (
                self._kv_byte_steps
                / max(1, self.counters["tokens_generated"])
            )
            snap["queue_wait_ms"] = self._queue_wait_ewma_ms
            snap["prefix_hit_rate_window"] = (
                self._prefix_hit_rate_locked()
            )
            for prefix in ("ttft", "queue_wait", "e2e", "step"):
                hist = self.hists[prefix + "_ms"]
                for q in (50, 90, 99):
                    snap["%s_p%d_ms" % (prefix, q)] = hist.percentile(q)
            snap["ttft_hist"] = self.hists["ttft_ms"].to_counts()
            snap["queue_wait_hist"] = (
                self.hists["queue_wait_ms"].to_counts()
            )
            # the slow-cause distribution, in declared order (the
            # ServerStatus slow_cause_counts repeated field's contract)
            snap["slow_cause_counts"] = [
                self.slow_causes[c] for c in self.SLOW_CAUSES
            ]
            snap["slow_requests"] = sum(self.slow_causes.values())
            return snap

    def prometheus(self):
        """The exposition families (observability/metrics.py shapes):
        every closed counter as edl_serving_<name>_total, every closed
        gauge as edl_serving_<name>, every histogram with
        _bucket/_sum/_count on the shared bucket scheme, plus the
        ring's drop accounting. Called from the metrics HTTP thread —
        snapshots under the telemetry lock."""
        with self._lock:
            fams = []
            for name in self.COUNTERS:
                fams.append(counter_family(
                    "edl_serving_%s_total" % name,
                    "serving counter %s" % name,
                    self.counters[name],
                ))
            gauges = dict(self.gauges)
            gauges["prefix_hit_rate_window"] = (
                self._prefix_hit_rate_locked()
            )
            for name in self.GAUGES:
                fams.append(gauge_family(
                    "edl_serving_%s" % name,
                    "serving gauge %s" % name,
                    [({}, gauges[name])],
                ))
            for name in self.HISTOGRAMS:
                h = self.hists[name]
                fams.append(hist_family(
                    "edl_serving_%s" % name,
                    "serving latency histogram %s (shared log-linear "
                    "scheme)" % name,
                    [({}, h.to_counts(), h.sum, h.exemplars)],
                ))
            fams.append(labeled_counter_family(
                "edl_serving_slow_cause_total",
                "terminally-slow requests by dominant attributed "
                "cause (observability/forensics.py taxonomy)",
                [({"cause": c}, self.slow_causes[c])
                 for c in self.SLOW_CAUSES],
            ))
            fams.append(gauge_family(
                "edl_serving_ring_windows_dropped",
                "time-series ring windows evicted by the bound",
                [({}, self.ring.dropped)],
            ))
            return fams

    def close(self):
        """Flush the tail, then close the writer. Without this a
        server stopped mid-window under-reported in TensorBoard: the
        partial tokens/sec window and every counter bump since the
        last flush_every boundary never reached the event file. The
        RING flushes at the same point with the same totals — the
        tb_events path and the last ring window must agree on the
        window boundary (regression-pinned), or the scrape plane and
        the event file would tell different stories about the same
        shutdown."""
        with self._lock:
            if self._log_dir and self._dirty:
                # _flush_window_locked creates the writer on demand, so
                # even a server that never reached a flush boundary
                # leaves its final counters on disk
                self._flush_window_locked()
            # final cumulative observation + force-close of the open
            # partial ring window: sum(ring deltas) == final counters
            # == the tb totals written above, by construction
            self._ring_observe_locked(roll=False)
            self.ring.flush()
            if self._writer is not None:
                self._writer.close()
                self._writer = None


class RouterTelemetry(object):
    """The routing tier's gauges/counters on the same event path.

    Gauges, stepped by heartbeat-poll index (the router has no decode
    steps — its clock is the lease-renewal loop):

        router/healthy_replicas   replicas in rotation at the poll
        router/replicas           registered replicas
        router/routed_total       monotone counters, one scalar per
        router/completed_total    flush (routed = accepted dispatches,
        router/redispatched_total completed = returned OK, redispatched
        router/hedges_total       = re-sent after a replica failure,
        router/hedge_wins_total   shed = RESOURCE_EXHAUSTED with no
        router/shed_total         healthy replica, breaker_trips =
        router/breaker_trips_total  closed->open transitions,
        router/affinity_hits_total  affinity_hits/misses = requests
        router/affinity_misses_total  with a prefix fingerprint that
                                  did / did not land on their learned
                                  replica — the decay-ladder telemetry)

    The cell gauges (`router/cell_id`, `router/cells`) identify this
    process inside a multi-cell router tier (serving/router_cell.py);
    a single-cell router reports cell_id=0, cells=1.

    Counters back the router_status RPC via snapshot() — like the
    replica telemetry, the RPC must work with the writer disabled.
    The counter AND gauge name sets are closed (count()/gauge() raise
    on unknowns; edl-lint EDL401 is the static twin for both). The
    router's end-to-end dispatch latency (accept -> terminal outcome,
    re-dispatches and hedges included) rides the shared log-linear
    histogram behind the e2e_p* router_status fields, and snapshot()
    carries the last-observed rotation gauges so operators aren't left
    scraping the event file for fleet size.

    The ring: every heartbeat poll feeds one cumulative observation —
    the router's own counters + e2e buckets PLUS the fleet-merged
    replica histograms the router hands in (`fleet_hists`: last-seen
    cumulative buckets per address, bucket-added — a killed replica's
    history stays in the sum). The SLO burn-rate engine
    (observability/slo.py) reads exactly this ring."""

    COUNTERS = ("routed", "completed", "redispatched", "hedges",
                "hedge_wins", "shed", "breaker_trips", "errors",
                "affinity_hits", "affinity_misses",
                # disaggregated prefill->decode handoffs (serving/
                # disagg.py): a fallback means the request dispatched
                # cold, not that it failed
                "disagg_handoffs", "disagg_fallbacks")
    GAUGES = ("healthy_replicas", "replicas", "cell_id", "cells")

    def __init__(self, log_dir=None, flush_every=20, clock=time.monotonic,
                 ring_secs=2.0, ring_windows=300):
        self._log_dir = log_dir
        self._flush_every = max(1, int(flush_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._writer = None
        self._started = clock()
        self._poll = 0
        self._dirty = False
        self.counters = {name: 0 for name in self.COUNTERS}
        self.gauges = {name: 0.0 for name in self.GAUGES}
        self.hists = {"e2e_ms": LogLinearHistogram()}
        self.ring = TimeSeriesRing(interval_secs=ring_secs,
                                   capacity=ring_windows, clock=clock)

    def _ensure_writer(self):
        if self._writer is None and self._log_dir:
            self._writer = EventFileWriter(
                self._log_dir, filename_suffix=".router"
            )
        return self._writer

    def _scalar(self, tag, value, step):
        writer = self._ensure_writer()
        if writer is not None:
            writer.add_scalar(tag, float(value), step)

    def _gauge_locked(self, name, value, step=None):
        if name not in self.gauges:
            raise ValueError(
                "unknown router gauge %r (declared: %s)"
                % (name, ", ".join(self.GAUGES))
            )
        self.gauges[name] = float(value)
        self._scalar("router/%s" % name, value,
                     self._poll if step is None else step)

    def gauge(self, name, value):
        with self._lock:
            self._gauge_locked(name, value)

    def count(self, name, n=1):
        with self._lock:
            if name not in self.counters:
                raise ValueError(
                    "unknown router counter %r (declared: %s)"
                    % (name, ", ".join(self.COUNTERS))
                )
            self.counters[name] += n
            self._dirty = True

    def record_e2e(self, latency_ms, trace_id=None):
        """Router-observed end-to-end latency of one dispatch that
        reached a terminal outcome. The request's trace_id becomes a
        bucket exemplar on the e2e histogram — the metrics->traces
        join the fleet collector walks."""
        with self._lock:
            self.hists["e2e_ms"].record(latency_ms,
                                        trace_id=trace_id)

    def record_poll(self, healthy, replicas, fleet_hists=None):
        """One heartbeat sweep: rotation-size gauges now, counters
        every flush_every polls, and one cumulative ring observation
        carrying the router's own counters/buckets plus the
        fleet-merged replica histograms (`fleet_hists`, e.g.
        {"fleet_ttft_ms": cumulative bucket counts}) the burn-rate
        engine windows over."""
        with self._lock:
            self._poll += 1
            self._gauge_locked("healthy_replicas", healthy)
            self._gauge_locked("replicas", replicas)
            if self._poll % self._flush_every == 0:
                for name, value in self.counters.items():
                    self._scalar(
                        "router/%s_total" % name, value, self._poll
                    )
            hists = {"e2e_ms": self.hists["e2e_ms"].to_counts()}
            if fleet_hists:
                hists.update(fleet_hists)
            self.ring.observe(
                counters=self.counters, gauges=self.gauges,
                hists=hists,
                exemplars={"e2e_ms": self.hists["e2e_ms"].exemplars},
            )

    def evaluate_slos(self, engine, now=None):
        """Run a BurnRateEngine over this telemetry's ring UNDER the
        telemetry lock (the ring itself is unlocked by design) — the
        router calls this each heartbeat and caches the reports."""
        with self._lock:
            return engine.evaluate(self.ring, now)

    def snapshot(self):
        with self._lock:
            snap = dict(self.counters)
            snap["uptime_secs"] = self._clock() - self._started
            snap["polls"] = self._poll
            snap["healthy_replicas"] = int(
                self.gauges["healthy_replicas"]
            )
            snap["replicas"] = int(self.gauges["replicas"])
            for q in (50, 90, 99):
                snap["e2e_p%d_ms" % q] = (
                    self.hists["e2e_ms"].percentile(q)
                )
            return snap

    def prometheus(self):
        """Exposition families for the routing tier: closed counters
        and gauges, the router's own e2e histogram, plus every
        fleet-merged histogram the ring carries (the cumulative
        last-seen sums record_poll fed) — so one scrape of the router
        answers fleet-wide TTFT without touching a replica."""
        with self._lock:
            fams = []
            for name in self.COUNTERS:
                fams.append(counter_family(
                    "edl_router_%s_total" % name,
                    "router counter %s" % name,
                    self.counters[name],
                ))
            for name in self.GAUGES:
                fams.append(gauge_family(
                    "edl_router_%s" % name,
                    "router gauge %s" % name,
                    [({}, self.gauges[name])],
                ))
            h = self.hists["e2e_ms"]
            fams.append(hist_family(
                "edl_router_e2e_ms",
                "router end-to-end dispatch latency (shared "
                "log-linear scheme)",
                [({}, h.to_counts(), h.sum, h.exemplars)],
            ))
            for name, counts in sorted(
                    self.ring.latest()["hists"].items()):
                if name == "e2e_ms":
                    continue  # rendered from the live hist above
                fams.append(hist_family(
                    "edl_router_%s" % name,
                    "fleet-merged replica histogram %s (bucket "
                    "addition across the roster)" % name,
                    [({}, counts, None)],
                ))
            fams.append(gauge_family(
                "edl_router_ring_windows_dropped",
                "time-series ring windows evicted by the bound",
                [({}, self.ring.dropped)],
            ))
            return fams

    def close(self):
        with self._lock:
            if self._writer is not None:
                for name, value in self.counters.items():
                    self._scalar(
                        "router/%s_total" % name, value, self._poll
                    )
            # same shutdown contract as the serving telemetry: the
            # final partial window lands in the ring too
            self.ring.observe(counters=self.counters,
                              gauges=self.gauges,
                              hists={"e2e_ms":
                                     self.hists["e2e_ms"].to_counts()},
                              roll=False)
            self.ring.flush()
            if self._writer is not None:
                self._writer.close()
                self._writer = None
