"""Serving process entrypoint.

Builds the sequence-family model from the zoo spec, restores the newest
checkpoint when one exists, and serves Generate/GenerateStream/
ServerStatus until SIGTERM/SIGINT — which trigger the graceful path:
admission closes (queued requests get RESOURCE_EXHAUSTED), in-flight
slots drain to completion, then the transport stops. With
--checkpoint_dir the server keeps following the directory and
hot-reloads newer versions between decode steps.

    python -m elasticdl_tpu.serving.main \\
        --model_zoo model_zoo \\
        --model_def transformer_lm.transformer_lm.custom_model \\
        --model_params "vocab_size=256; seq_len=128" \\
        --checkpoint_dir /ckpt --port 50051 --num_slots 8
"""

import argparse
import signal
import sys
import threading

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.model_utils import get_model_spec


def parse_serving_args(args=None):
    parser = argparse.ArgumentParser(
        description="elasticdl-tpu generation server"
    )
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--model_params", default="")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument("--num_slots", type=int, default=4)
    parser.add_argument("--queue_capacity", type=int, default=64)
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--top_p", type=float, default=1.0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--max_workers", type=int, default=64,
                        help="gRPC handler threads; size ABOVE the "
                             "expected concurrent in-flight RPCs — a "
                             "pool full of blocked generate handlers "
                             "starves server_status and the router "
                             "reads the silence as lease decay")
    parser.add_argument("--reload_poll_secs", type=float, default=2.0,
                        help="0 disables the watcher's self-upgrade "
                             "poll: checkpoints load only through the "
                             "explicit reload_checkpoint RPC (the "
                             "rollout-managed fleet mode)")
    parser.add_argument("--tensorboard_log_dir", default="")
    # KV pool layout: -1 resolves from EDL_KV_PAGED (the drill/CI
    # toggle); 1 = block-paged pool (serving/kv_pool.py), 0 = dense
    parser.add_argument("--kv_paged", type=int, default=-1,
                        choices=(-1, 0, 1))
    parser.add_argument("--kv_block_size", type=int, default=16)
    parser.add_argument("--kv_num_blocks", type=int, default=0,
                        help="block budget; 0 = dense-equivalent bytes")
    # prefix sharing (paged only): -1 resolves from EDL_KV_SHARED
    # (default on) — refcounted dedupe of matching prompt prefixes
    parser.add_argument("--kv_shared", type=int, default=-1,
                        choices=(-1, 0, 1))
    # tiered host spill (paged only): byte budget for evicted prefix
    # chains demoted to host RAM and revived by upload instead of
    # re-prefill; -1 resolves from EDL_KV_HOST_BYTES, 0 = off
    parser.add_argument("--kv_host_bytes", type=int, default=-1)
    # speculative decode: a small DRAFT model proposes draft_k tokens
    # per tick, verified in one target step (paged pool only; token-
    # exact with plain decode)
    parser.add_argument("--draft_k", type=int, default=0)
    parser.add_argument("--draft_model_def", default="",
                        help="zoo model_def for the draft; empty = "
                             "speculative decode off")
    parser.add_argument("--draft_model_params", default="")
    # pre-READY warmup: generate this many tokens in-process before
    # printing the readiness line, so the jit compile is paid BEFORE a
    # router/autoscaler routes live traffic here (a freshly adopted
    # replica must not serve its first request cold)
    parser.add_argument("--warmup_tokens", type=int, default=0)
    # live metrics plane: Prometheus-text /metrics exposition (stdlib
    # http.server thread, observability/metrics.py); -1 resolves from
    # EDL_METRICS_PORT (unset = off), 0 = ephemeral port — the bound
    # port prints as `METRICS_READY port=N` next to the serving line
    parser.add_argument("--metrics_port", type=int, default=-1)
    # per-step decode profiler (engine.StepProfiler): phase timers
    # around prefill / suffix tile / draft / verify / scatter / revive
    # upload / reload swap; -1 resolves from EDL_PROFILE, default off
    # (disabled = zero timing work)
    parser.add_argument("--profile", type=int, default=-1,
                        choices=(-1, 0, 1))
    # tail-forensics plane (histogram exemplars + tail-based trace
    # retention + slow-cause attribution): -1 resolves from
    # EDL_FORENSICS, default ON — priced by the bench overhead A/B
    parser.add_argument("--forensics", type=int, default=-1,
                        choices=(-1, 0, 1))
    # runtime health plane (observability/runtime_health.py):
    # recompile sentry + device-memory ledger reconciliation +
    # progress watchdog with flight recorder, self-reported through
    # ServerStatus health_state/last_progress_age_ms; -1 resolves
    # from EDL_RUNTIME_HEALTH, default ON — priced by the same bench
    # overhead A/B as the rest of the observability stack
    parser.add_argument("--runtime_health", type=int, default=-1,
                        choices=(-1, 0, 1))
    # watchdog budget: work seated but no progress (tokens OR jit
    # compiles) for this long = stalled; -1 resolves from
    # EDL_STALL_AFTER_SECS (default 10 s). Stall bundles dump to
    # $EDL_HEALTH_DIR when set.
    parser.add_argument("--stall_after_secs", type=float, default=-1.0)
    # disaggregated serving (serving/disagg.py): the phase this
    # replica advertises through ServerStatus.role — "prefill"
    # replicas are kept out of the router's normal rotation and serve
    # cache-warming handoffs only; "" resolves from EDL_SERVING_ROLE
    # (default "unified")
    parser.add_argument("--role", default="",
                        choices=("", "prefill", "decode", "unified"))
    # chunked prefill: tile size in tokens (paged pool only; long
    # prompts prefill in tiles interleaved with decode steps instead
    # of monopolizing a tick); -1 resolves from
    # EDL_PREFILL_CHUNK_TOKENS, 0 = monolithic prefill
    parser.add_argument("--prefill_chunk_tokens", type=int, default=-1)
    # SLO-aware per-tick prefill budget in milliseconds (at least one
    # tile always runs; the EWMA tile price decides whether the NEXT
    # one fits); -1 resolves from EDL_PREFILL_BUDGET_MS (default 8),
    # 0 = unbounded
    parser.add_argument("--prefill_budget_ms", type=float, default=-1.0)
    return parser.parse_args(args)


def build_server(args):
    # imports deferred so --help works without jax initialized
    import jax

    from elasticdl_tpu.checkpoint.saver import (
        get_latest_checkpoint_version,
        restore_state_from_checkpoint,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.serving.server import (
        GenerationServer,
        ServingConfig,
    )
    from elasticdl_tpu.training.trainer import Trainer

    spec = get_model_spec(args.model_zoo, args.model_def)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=args.model_params)
    seq_len = int(trainer.model.seq_len)
    dummy = np.zeros((1, seq_len), np.int32)
    state = trainer.init_state(({"tokens": dummy}, dummy))
    version = 0
    if args.checkpoint_dir:
        if get_latest_checkpoint_version(args.checkpoint_dir) >= 0:
            state, version = restore_state_from_checkpoint(
                state, args.checkpoint_dir, strict=False
            )
            logger.info("serving checkpoint version-%d", version)
        else:
            logger.warning(
                "no checkpoint under %r yet; serving fresh params "
                "until one lands", args.checkpoint_dir,
            )
    draft = None
    draft_k = int(args.draft_k)
    if args.draft_model_def and draft_k > 0:
        d_spec = get_model_spec(args.model_zoo, args.draft_model_def)
        d_trainer = Trainer(d_spec, mesh=mesh,
                            model_params=args.draft_model_params)
        d_len = int(d_trainer.model.seq_len)
        d_state = d_trainer.init_state(
            ({"tokens": np.zeros((1, d_len), np.int32)},
             np.zeros((1, d_len), np.int32))
        )
        draft = (d_trainer, d_state)
    server = GenerationServer(
        trainer, state,
        ServingConfig(
            num_slots=args.num_slots,
            queue_capacity=args.queue_capacity,
            top_k=args.top_k, top_p=args.top_p,
            checkpoint_dir=args.checkpoint_dir,
            reload_poll_secs=args.reload_poll_secs,
            telemetry_dir=args.tensorboard_log_dir,
            port=args.port,
            max_workers=args.max_workers,
            kv_paged=None if args.kv_paged < 0 else bool(args.kv_paged),
            kv_block_size=args.kv_block_size,
            kv_num_blocks=args.kv_num_blocks,
            kv_shared=(None if args.kv_shared < 0
                       else bool(args.kv_shared)),
            kv_host_bytes=(None if args.kv_host_bytes < 0
                           else args.kv_host_bytes),
            draft_k=draft_k if draft is not None else 0,
            metrics_port=(None if args.metrics_port < 0
                          else args.metrics_port),
            profile=None if args.profile < 0 else bool(args.profile),
            forensics=(None if args.forensics < 0
                       else bool(args.forensics)),
            runtime_health=(None if args.runtime_health < 0
                            else bool(args.runtime_health)),
            stall_after_secs=(None if args.stall_after_secs < 0
                              else args.stall_after_secs),
            role=args.role or None,
            prefill_chunk_tokens=(None if args.prefill_chunk_tokens < 0
                                  else args.prefill_chunk_tokens),
            prefill_budget_ms=(None if args.prefill_budget_ms < 0
                               else args.prefill_budget_ms),
        ),
        draft=draft,
    )
    server.engine.model_version = version
    if server.watcher is not None:
        server.watcher.version = version
    return server


def warmup(server, tokens):
    """One in-process generate through the UNWRAPPED servicer: pays
    the jit compile (and records nothing against armed fault rules)
    before the process advertises readiness."""
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    server.raw_servicer.generate(
        pb.GenerateRequest(prompt=[1, 2], max_new_tokens=tokens)
    )
    # the compile-heavy warmup latency must never surface in the
    # percentiles a router/autoscaler SLOs on
    server.telemetry.reset_latency()
    # the runtime-health steady boundary: from here on a recompile is
    # a counted anomaly and the memory baseline is anchored
    server.mark_steady()
    logger.info("warmup complete (%d tokens)", tokens)


def main(argv=None):
    args = parse_serving_args(argv)
    # SIGUSR2 -> all-thread stack dump: a live wedged replica can
    # always be interrogated without killing it
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    server = build_server(args).start()
    if args.warmup_tokens > 0:
        warmup(server, args.warmup_tokens)
    # name this process's span recorder after the bound port; spans
    # export to $EDL_TRACE_DIR on stop (plus an atexit backstop)
    from elasticdl_tpu.observability.tracing import configure

    configure(service="replica:%d" % server.port)
    done = threading.Event()

    def _graceful(_signum, _frame):
        logger.info("signal received: draining and stopping")
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    if server.metrics is not None:
        # same log-line discovery contract as SERVING_READY: a scraper
        # (or the supervisor's log re-read) learns the bound port here
        print("METRICS_READY port=%d" % server.metrics.port,
              flush=True)
    print("SERVING_READY port=%d" % server.port, flush=True)
    done.wait()
    server.stop(drain=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
