"""Continuous-batching decode engine over a fixed pool of KV slots.

The offline decoder (api/generation.py) compiles one program per
(batch, lengths, sampling) combination and runs each request cohort to
completion — fine for batch PREDICTION, fatal for serving, where
requests arrive continuously with mixed lengths and a static cohort
leaves the pool idle while the longest member finishes. This engine
instead runs ONE jit-compiled single-token decode step over a fixed
pool of `num_slots` batch slots, every step, forever:

* each slot owns a batch-1 KV-cache tree (the same per-layer caches the
  model's decode mode builds — including its scalar position counter),
  stacked leaf-wise into a pool with leading axis [S, ...];
* the step `jax.vmap`s the model's decode over the slot axis, so every
  slot advances at its OWN position — the per-slot cache counter drives
  each layer's cache write, RoPE rotation and position-embedding lookup
  exactly as in offline decode;
* prompt insertion = one batched prefill (the offline `_run_prefill`,
  bucketed to 64 like offline decode) + a `lax.dynamic_update_slice`
  of the slot's cache rows at a TRACED slot index — membership changes
  never recompile anything;
* finished/expired slots are simply marked free host-side; their stale
  cache rows are dead weight until the next insertion overwrites them
  (free slots still ride through the vmapped step as masked work — the
  static-shape price of zero recompiles).

Token parity: a request's output depends only on (params, prompt, seed,
temperature) — never on what else shares the pool. Greedy and sampled
tokens equal the offline `autoregressive_generate(use_cache=True)` on a
batch of one with the same knobs (serving_next_token's contract), which
the serving tests lock against the offline path.

Single-threaded by design: only the scheduler thread may call
insert/step/set_params (jax computations stay serialized; the gRPC
threads touch only the admission queue and event plumbing).
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.api.generation import (
    _kv_shapes_for,
    _maybe_dequantize,
    _prefill_bucket,
    _require_kv_convention,
    _run_prefill,
    serving_next_token,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


class _Slot(object):
    __slots__ = ("request", "max_total")

    def __init__(self, request, max_total):
        self.request = request
        self.max_total = max_total


class ContinuousBatchingEngine(object):
    """The decode pool. `top_k`/`top_p` are server-level static sampling
    filters (part of the compiled step); temperature and seed ride per
    request as traced values."""

    def __init__(self, trainer, state, num_slots, top_k=0, top_p=1.0):
        model = trainer.model
        _require_kv_convention(model)
        if not getattr(model, "causal", True):
            raise ValueError("serving needs a causal sequence model")
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1], got %r" % (top_p,))
        self.trainer = trainer
        self.model = model
        self.num_slots = int(num_slots)
        self.seq_len = int(model.seq_len)
        self.top_k = int(top_k)
        self.top_p = float(top_p)

        from elasticdl_tpu.api.quantization import is_quantized

        self._qz = is_quantized(state.params)
        self.set_params(state, version=getattr(state, "version", 0))

        # batch-1 cache template -> pooled leaves [S, ...]; shares the
        # trainer's compile cache so offline callers reuse the shapes
        from elasticdl_tpu.api.generation import _decode_cache

        self._kv_shapes = _kv_shapes_for(_decode_cache(trainer), model, 1)
        self._pool = jax.tree.map(
            lambda sh: jnp.zeros((self.num_slots,) + sh.shape, sh.dtype),
            self._kv_shapes,
        )
        self._slots = [None] * self.num_slots  # _Slot or None
        self._last_tokens = np.zeros(self.num_slots, np.int32)
        self._seeds = np.zeros(self.num_slots, np.int32)
        self._temps = np.zeros(self.num_slots, np.float32)
        self._prefill_fns = {}  # bucket -> compiled prefill
        self._step_fn = None
        self._write_fn = None

    # ------------------------------------------------------------ params

    def set_params(self, state, version):
        """Swap the serving params (hot reload). Runs BETWEEN decode
        steps (scheduler thread), so in-flight sequences simply continue
        on the new weights — their KV caches, positions and pending
        tokens are untouched. Shapes/dtypes must match the compiled
        executables; a changed architecture needs a new server."""
        self.variables = {"params": state.params, **state.model_state}
        from elasticdl_tpu.api.quantization import is_quantized

        if is_quantized(state.params) != self._qz and hasattr(
                self, "_pool"):
            raise ValueError(
                "hot reload cannot change quantization (compiled "
                "executables bake the dequantize path)"
            )
        self.model_version = int(version)

    # ------------------------------------------------------------- slots

    def free_slots(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def active_count(self):
        return sum(1 for s in self._slots if s is not None)

    def active_requests(self):
        return [s.request for s in self._slots if s is not None]

    def insert(self, request):
        """Seat `request` in a free slot: one prefill forward fills the
        slot's per-layer caches for the prompt and produces the FIRST
        generated token (pushed by the caller — this is the TTFT
        boundary). Returns (slot_idx, first_token, finished); raises
        RuntimeError when no slot is free (callers check free_slots)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        p = len(request.prompt)
        total = p + request.max_new_tokens
        if total > self.seq_len:
            raise ValueError(
                "request needs %d positions > seq_len %d"
                % (total, self.seq_len)
            )
        p_pad = _prefill_bucket(p, self.seq_len)
        fn = self._prefill_fns.get(p_pad)
        if fn is None:
            fn = self._build_prefill(p_pad)
            self._prefill_fns[p_pad] = fn
        buf = np.zeros((1, self.seq_len), np.int32)
        buf[0, :p] = request.prompt
        with self.trainer.mesh:
            kv, first = fn(
                self.variables, jnp.asarray(buf),
                jnp.asarray(p, jnp.int32),
                jnp.asarray(request.seed, jnp.int32),
                jnp.asarray(request.temperature, jnp.float32),
            )
            self._pool = self._write_slot(kv, slot)
        first = int(first)
        request.generated.append(first)
        request.model_version = self.model_version
        finished = request.max_new_tokens == 1
        if not finished:
            self._slots[slot] = _Slot(request, total)
            self._last_tokens[slot] = first
            self._seeds[slot] = request.seed
            self._temps[slot] = request.temperature
        return slot, first, finished

    def evict(self, slot):
        """Free a slot (completion or deadline eviction). The stale
        cache rows stay until the next insert overwrites them."""
        self._slots[slot] = None

    def evict_expired(self, now):
        """Evict every active request whose deadline has passed;
        returns the evicted requests (the scheduler fails them with
        DEADLINE_EXCEEDED — partial tokens already streamed stand)."""
        out = []
        for i, st in enumerate(self._slots):
            if st is not None and st.request.expired(now):
                self._slots[i] = None
                out.append(st.request)
        return out

    def step(self):
        """One vmapped decode step over the WHOLE pool. Every active
        slot advances one token at its own position; free slots run the
        same compute against stale caches and are ignored (static shape,
        zero recompiles). Returns [(slot, request, token, finished)] for
        slots that were active; finished slots are freed."""
        active = [
            (i, s) for i, s in enumerate(self._slots) if s is not None
        ]
        if not active:
            return []
        if self._step_fn is None:
            self._step_fn = self._build_step()
        with self.trainer.mesh:
            self._pool, nxt = self._step_fn(
                self.variables, self._pool,
                jnp.asarray(self._last_tokens),
                jnp.asarray(self._seeds),
                jnp.asarray(self._temps),
            )
            nxt = np.asarray(nxt)
        out = []
        for slot, st in active:
            token = int(nxt[slot])
            st.request.generated.append(token)
            st.request.model_version = self.model_version
            self._last_tokens[slot] = token
            finished = (
                len(st.request.prompt) + len(st.request.generated)
                >= st.max_total
            )
            if finished:
                self.evict(slot)
            out.append((slot, st.request, token, finished))
        return out

    # ------------------------------------------------------- compiled fns

    def _build_prefill(self, p_pad):
        model, kv_shapes = self.model, self._kv_shapes
        top_k, top_p, qz = self.top_k, self.top_p, self._qz

        def prefill(variables, buf, p_len, seed, temperature):
            variables = _maybe_dequantize(variables, qz)
            kv, last = _run_prefill(
                model, variables, kv_shapes, buf, p_len, p_pad
            )
            first = serving_next_token(
                last[0], seed, p_len, temperature, top_k, top_p
            )
            return kv, first

        logger.info("serving: compiling prefill for bucket %d", p_pad)
        return jax.jit(prefill)

    def _build_step(self):
        model = self.model
        top_k, top_p, qz = self.top_k, self.top_p, self._qz

        def step(variables, pool, last_tokens, seeds, temps):
            variables = _maybe_dequantize(variables, qz)

            def one(cache, tok, seed, temp):
                # pre-advance counter: the model writes this token's
                # k/v at `pos` and the sampled token lands at pos + 1
                # (the offline loop's `_next_token(..., i + 1)`)
                pos = cache["pos"]
                logits, upd = model.apply(
                    dict(variables, cache=cache),
                    {"tokens": tok[None, None]},
                    training=False, decode=True, mutable=["cache"],
                )
                nxt = serving_next_token(
                    logits[0, 0], seed, pos + 1, temp, top_k, top_p
                )
                return upd["cache"], nxt

            return jax.vmap(one)(pool, last_tokens, seeds, temps)

        logger.info(
            "serving: compiling decode step for %d slots", self.num_slots
        )
        return jax.jit(step)

    def _write_slot(self, kv, slot):
        """Insert a batch-1 cache tree into the pool at a TRACED slot
        index (one compiled write serves every slot)."""
        if self._write_fn is None:
            def write(pool, kv, idx):
                def upd(p, n):
                    start = (idx,) + (0,) * n.ndim
                    return jax.lax.dynamic_update_slice(
                        p, n[None], start
                    )

                return jax.tree.map(upd, pool, kv)

            self._write_fn = jax.jit(write)
        return self._write_fn(
            self._pool, kv, jnp.asarray(slot, jnp.int32)
        )
