"""Continuous-batching decode engine over a fixed pool of KV slots.

The offline decoder (api/generation.py) compiles one program per
(batch, lengths, sampling) combination and runs each request cohort to
completion — fine for batch PREDICTION, fatal for serving, where
requests arrive continuously with mixed lengths and a static cohort
leaves the pool idle while the longest member finishes. This engine
instead runs ONE jit-compiled single-token decode step over a fixed
pool of `num_slots` batch slots, every step, forever:

* each slot owns a batch-1 KV-cache tree (the same per-layer caches the
  model's decode mode builds — including its scalar position counter),
  stacked leaf-wise into a pool with leading axis [S, ...];
* the step `jax.vmap`s the model's decode over the slot axis, so every
  slot advances at its OWN position — the per-slot cache counter drives
  each layer's cache write, RoPE rotation and position-embedding lookup
  exactly as in offline decode;
* prompt insertion = one batched prefill (the offline `_run_prefill`,
  bucketed to 64 like offline decode) + a `lax.dynamic_update_slice`
  of the slot's cache rows at a TRACED slot index — membership changes
  never recompile anything;
* finished/expired slots are simply marked free host-side; their stale
  cache rows are dead weight until the next insertion overwrites them
  (free slots still ride through the vmapped step as masked work — the
  static-shape price of zero recompiles).

Token parity: a request's output depends only on (params, prompt, seed,
temperature) — never on what else shares the pool. Greedy and sampled
tokens equal the offline `autoregressive_generate(use_cache=True)` on a
batch of one with the same knobs (serving_next_token's contract), which
the serving tests lock against the offline path.

Single-threaded by design: only the scheduler thread may call
insert/step/set_params (jax computations stay serialized; the gRPC
threads touch only the admission queue and event plumbing).

Two pool layouts share this scheduler surface:

* ContinuousBatchingEngine — the DENSE pool: every slot owns a
  contiguous `seq_len` KV stripe per layer. Simple, but decode HBM
  scales as `num_slots x seq_len` no matter how short requests run.
* PagedContinuousBatchingEngine — the BLOCK-PAGED pool
  (serving/kv_pool.py): KV rows live in shared block arenas, slots
  hold block tables, and admission works against a token/block budget
  so short requests pack densely. Token streams are identical to the
  dense engine (the parity the e2e tests lock); only the memory
  geometry differs. Select with ServingConfig.kv_paged / EDL_KV_PAGED.

Weight-only int8 params (api/quantization): by default the engine
dequantizes ONCE per set_params (initial load and every hot reload)
and serves the cached float weights — a single-token decode step that
re-dequantized the full weight set every step dominated the step on
the latency-bound path (the decode_kv_int8 bench regression).
EDL_SERVING_FUSED_DEQUANT=1 restores in-jit dequantize (int8 weights
stream HBM->VMEM per step — the right trade when weights dwarf VMEM
and HBM bandwidth, not latency, bounds the step).
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.api.generation import (
    _kv_shapes_for,
    _maybe_dequantize,
    _prefill_bucket,
    _require_kv_convention,
    _run_prefill,
    serving_next_token,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.observability.histogram import LogLinearHistogram
from elasticdl_tpu.observability.metrics import hist_family
from elasticdl_tpu.observability.runtime_health import tracked_jit


def kv_paged_default():
    """EDL_KV_PAGED resolves the pool layout when the config leaves it
    unset — the env toggle the drills/CI use to prove both modes."""
    return os.environ.get("EDL_KV_PAGED", "") not in ("", "0")


def kv_shared_default():
    """EDL_KV_SHARED resolves prefix sharing when the config leaves it
    unset. Default ON (sharing is strictly a capacity win under the
    same token-parity contract); EDL_KV_SHARED=0 forces the private
    paged pool — the A/B leg the bench and drills exercise."""
    return os.environ.get("EDL_KV_SHARED", "1") not in ("", "0")


def kv_host_bytes_default():
    """EDL_KV_HOST_BYTES resolves the paged pool's host spill-tier
    budget when the config leaves it unset (0 = eviction forgets, the
    pre-tier behavior) — the env toggle the drills/CI use."""
    try:
        return int(os.environ.get("EDL_KV_HOST_BYTES", "") or 0)
    except ValueError:
        return 0


def _fused_dequant():
    return os.environ.get(
        "EDL_SERVING_FUSED_DEQUANT", "") not in ("", "0")


def prefill_chunk_default():
    """EDL_PREFILL_CHUNK_TOKENS resolves the chunked-prefill tile
    width when the config leaves it unset (0 = off: a prompt prefills
    monolithically, monopolizing its scheduler tick)."""
    try:
        return int(os.environ.get("EDL_PREFILL_CHUNK_TOKENS", "") or 0)
    except ValueError:
        return 0


def prefill_budget_default():
    """EDL_PREFILL_BUDGET_MS resolves the scheduler's per-tick chunked
    prefill budget when the config leaves it unset: the wall-clock ms
    of prefill tiles a tick may run while decode slots are active
    (<= 0 = unbounded). At least one tile always runs per tick, so
    prefill makes progress no matter how small the budget."""
    try:
        return float(
            os.environ.get("EDL_PREFILL_BUDGET_MS", "") or 8.0
        )
    except ValueError:
        return 8.0


def role_default():
    """EDL_SERVING_ROLE resolves the replica's disaggregation role
    when the config leaves it unset: "prefill" | "decode" | "unified"
    (serving/disagg.py). Unified replicas serve both phases — the
    pre-disagg behavior."""
    role = os.environ.get("EDL_SERVING_ROLE", "") or "unified"
    if role not in ("prefill", "decode", "unified"):
        raise ValueError(
            "EDL_SERVING_ROLE must be prefill|decode|unified, got %r"
            % role
        )
    return role


def profile_default():
    """EDL_PROFILE resolves the per-step decode profiler when the
    config leaves it unset (off by default: the disabled engine does
    no timing work at all)."""
    return os.environ.get("EDL_PROFILE", "") not in ("", "0")


class StepProfiler(object):
    """Per-step decode profiler: where inside a serving step does time
    go? Each PHASE is one host-visible region of the engine's work,
    timed wall-clock with the produced device values blocked on (so
    async dispatch can't smear a phase into its successor) and
    recorded into a per-phase log-linear histogram — the same bucket
    scheme as every latency surface, so phase p99s are comparable
    with TTFT/step percentiles and render as one more histogram
    family on /metrics (`edl_serving_phase_ms{phase=...}`).

    Phase taxonomy (closed set — observe() raises on anything else,
    the telemetry-counter contract):

        prefill        full-prompt prefill forward + cache/block write
        suffix_tile    shared-prefix suffix tile over resident blocks
        prefill_tile   one chunked-prefill tile: a fixed-token chunk
                       of a long prompt run between decode ticks (the
                       scheduler prices its per-tick chunk budget off
                       this phase's percentiles)
        decode         the plain vmapped single-token step (model
                       apply + sample; paged: minus the row scatter,
                       which times separately)
        draft          draft-model work: draft prefill at seat time +
                       the k-token draft scan each speculative tick
        verify_commit  the target's (k+1)-tile verify + accept/commit
                       math of the speculative tick
        scatter        row scatter into the paged arenas (plain and
                       speculative ticks)
        revive_upload  host->device batched revival scatter of spilled
                       prefix chains (tiered KV)
        reload_swap    hot checkpoint swap (set_params, dequantize
                       included)

    Enabled, the PAGED step runs as SPLIT compiled functions (decode |
    scatter; draft | verify | scatter) — mathematically identical to
    the fused step (the splits pass the same arrays through the host
    boundary; the e2e battery pins token parity with the profiler ON),
    trading only cross-phase fusion for attribution. Disabled
    (engine.profiler is None) the engine keeps the fused executables
    and does NO timing work — the serve-smoke overhead A/B bounds the
    enabled cost at 5%.

    Thread-safety: the scheduler thread records, the metrics HTTP
    thread snapshots — one lock, record is O(1)."""

    PHASES = ("prefill", "suffix_tile", "prefill_tile", "decode",
              "draft", "verify_commit", "scatter", "revive_upload",
              "reload_swap")

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self.hists = {p: LogLinearHistogram() for p in self.PHASES}

    def t(self):
        """The profiler's clock (engine call sites time around their
        own block_until_ready, so the clock is part of the API)."""
        return self._clock()

    def observe(self, phase, secs):
        with self._lock:
            if phase not in self.hists:
                raise ValueError(
                    "unknown profiler phase %r (declared: %s)"
                    % (phase, ", ".join(self.PHASES))
                )
            self.hists[phase].record(secs * 1000.0)

    def snapshot(self):
        """{phase: {count, p50_ms, p99_ms, total_ms}} for phases that
        recorded anything — the bench's BENCH_SERVING.json shape."""
        with self._lock:
            out = {}
            for phase in self.PHASES:
                h = self.hists[phase]
                if not h.count:
                    continue
                out[phase] = {
                    "count": h.count,
                    "p50_ms": round(h.percentile(50), 3),
                    "p99_ms": round(h.percentile(99), 3),
                    "total_ms": round(h.sum, 3),
                }
            return out

    def prometheus(self):
        """One labeled histogram family: edl_serving_phase_ms with a
        `phase` label per declared phase that recorded samples."""
        with self._lock:
            series = [
                ({"phase": phase}, self.hists[phase].to_counts(),
                 self.hists[phase].sum)
                for phase in self.PHASES if self.hists[phase].count
            ]
            return [hist_family(
                "edl_serving_phase_ms",
                "per-step decode profiler: wall ms per phase (shared "
                "log-linear scheme)",
                series,
            )]


class _Slot(object):
    __slots__ = ("request", "max_total")

    def __init__(self, request, max_total):
        self.request = request
        self.max_total = max_total


class _PrefillJob(object):
    """One chunked prefill in flight (paged engine): the slot is
    seated — its full block budget reserved — but the prompt's rows
    materialize tile by tile across scheduler ticks via
    advance_prefill(). `first` is the request's first generated token,
    set when the final tile lands; `finished` mirrors the insert()
    contract (a prefill-only or one-token request completes at its
    first token)."""

    __slots__ = ("slot", "request", "pos", "prompt_len", "first",
                 "finished", "tiles")

    def __init__(self, slot, request, pos):
        self.slot = slot
        self.request = request
        self.pos = int(pos)  # next un-prefilled prompt position
        self.prompt_len = len(request.prompt)
        self.first = None
        self.finished = False
        self.tiles = 0

    def done(self):
        return self.first is not None


class ContinuousBatchingEngine(object):
    """The decode pool. `top_k`/`top_p` are server-level static sampling
    filters (part of the compiled step); temperature and seed ride per
    request as traced values."""

    def __init__(self, trainer, state, num_slots, top_k=0, top_p=1.0):
        model = trainer.model
        _require_kv_convention(model)
        if not getattr(model, "causal", True):
            raise ValueError("serving needs a causal sequence model")
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1], got %r" % (top_p,))
        self.trainer = trainer
        self.model = model
        self.num_slots = int(num_slots)
        self.seq_len = int(model.seq_len)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # optional ServingTelemetry hook (GenerationServer wires it):
        # the engine reports prefix-share / CoW / draft-accept events
        # it alone can see; None costs nothing (tests, benches)
        self.telemetry = None
        # optional per-step decode profiler (StepProfiler; the server
        # wires it under ServingConfig.profile / EDL_PROFILE). None =
        # fused executables, no timing work at all
        self.profiler = None
        # optional recompile sentry (runtime_health.RecompileSentry;
        # the server attaches it under ServingConfig.runtime_health).
        # Every jit site below compiles through _tjit, which resolves
        # this LAZILY — executables built before the server attaches
        # the sentry still count their later compiles. None = plain
        # jax.jit, zero counting work.
        self.sentry = None
        self.draft_k = 0        # speculative decode off (paged engine
        self.draft_proposed = 0  # overrides when a draft is seated)
        self.draft_accepted = 0
        # chunked prefill tile width (0 = monolithic); the dense pool
        # never chunks — only the paged engine overrides this
        self.prefill_chunk_tokens = 0
        # cumulative wall ms this engine has spent inside insert()
        # (prefill / suffix tile / draft prefill) — the scheduler
        # advances it; the servicer stamps it at admission so seating
        # can report how long OTHER requests' prefills held the
        # single-threaded scheduler while this one waited
        # (forensics: prefill_blocked_by_other). Written only by the
        # scheduler thread, read racily by handler threads — a stale
        # read under-reports blocking by at most one prefill, which
        # the attribution tolerates by design.
        self.prefill_busy_ms = 0.0

        from elasticdl_tpu.api.quantization import is_quantized

        self._qz = is_quantized(state.params)
        # in-jit dequantize is opt-in (see the module docstring); the
        # default path serves float weights cached by set_params
        self._exec_qz = self._qz and _fused_dequant()
        self._dequant_fn = None
        self.set_params(state, version=getattr(state, "version", 0))

        # batch-1 cache template -> pooled leaves [S, ...]; shares the
        # trainer's compile cache so offline callers reuse the shapes
        from elasticdl_tpu.api.generation import _decode_cache

        self._kv_shapes = _kv_shapes_for(_decode_cache(trainer), model, 1)
        self._init_pool()
        self._slots = [None] * self.num_slots  # _Slot or None
        self._last_tokens = np.zeros(self.num_slots, np.int32)
        self._seeds = np.zeros(self.num_slots, np.int32)
        self._temps = np.zeros(self.num_slots, np.float32)
        self._prefill_fns = {}  # bucket -> compiled prefill
        self._step_fn = None
        self._write_fn = None

    def _init_pool(self):
        from elasticdl_tpu.api.generation import kv_row_leaf

        self._pool = jax.tree.map(
            lambda sh: jnp.zeros((self.num_slots,) + sh.shape, sh.dtype),
            self._kv_shapes,
        )
        # KV ROW bytes only (the position counters are noise and would
        # break the paged pool's equal-bytes comparison)
        self._kv_bytes_total = self.num_slots * int(sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(self._kv_shapes)
            if kv_row_leaf(leaf, self.seq_len)
        ))

    # ------------------------------------------------------------ params

    def set_params(self, state, version):
        """Swap the serving params (hot reload). Runs BETWEEN decode
        steps (scheduler thread), so in-flight sequences simply continue
        on the new weights — their KV caches, positions and pending
        tokens are untouched. Shapes/dtypes must match the compiled
        executables; a changed architecture needs a new server.

        With int8 params (and the default non-fused path) this is also
        the ONE place the weights dequantize: the cached float tree in
        `_exec_variables` serves every prefill/decode step until the
        next reload invalidates it here."""
        # reload_swap phase: the profiler attribute only exists after
        # __init__ assigns it, and the FIRST set_params (construction)
        # is not a reload — getattr keeps both true
        prof = getattr(self, "profiler", None)
        t0 = prof.t() if prof is not None else 0.0
        self.variables = {"params": state.params, **state.model_state}
        from elasticdl_tpu.api.quantization import is_quantized

        if is_quantized(state.params) != self._qz and hasattr(
                self, "_pool"):
            raise ValueError(
                "hot reload cannot change quantization (compiled "
                "executables bake the dequantize path)"
            )
        self.model_version = int(version)
        if self._qz and not self._exec_qz:
            if self._dequant_fn is None:
                from elasticdl_tpu.api.quantization import (
                    dequantize_params,
                )

                self._dequant_fn = self._tjit(
                    "dequant",
                    lambda v: dict(
                        v, params=dequantize_params(v["params"])
                    ),
                )
            with self.trainer.mesh:
                self._exec_variables = self._dequant_fn(self.variables)
        else:
            self._exec_variables = self.variables
        if prof is not None:
            jax.block_until_ready(self._exec_variables)
            prof.observe("reload_swap", prof.t() - t0)

    # ------------------------------------------------------------- slots

    def free_slots(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def active_count(self):
        return sum(1 for s in self._slots if s is not None)

    def active_requests(self):
        return [s.request for s in self._slots if s is not None]

    def can_seat(self, request):
        """Whether `request` can be seated RIGHT NOW beyond needing a
        free slot (the scheduler checks slots separately). The dense
        pool has no other resource; the paged pool answers from its
        block budget."""
        return True

    def max_cached_tokens(self):
        """Largest prompt+decode cache footprint a request may ever
        need — the admission queue's never-fits bound."""
        return self.seq_len

    def kv_stats(self):
        """KV memory accounting for telemetry / ServerStatus. The
        dense pool's total is resident whether slots are active or
        not — exactly the pressure the paged pool relieves; in_use
        reports the stripes live requests actually pin."""
        per_slot = self._kv_bytes_total // max(1, self.num_slots)
        return {
            "kv_paged": False,
            "kv_shared": False,
            "kv_cache_dtype": getattr(
                self.model, "kv_cache_dtype", "") or "",
            "kv_block_size": 0,
            "kv_blocks_total": 0,
            "kv_blocks_free": 0,
            "kv_blocks_cached": 0,
            "kv_blocks_shared": 0,
            "kv_bytes_total": self._kv_bytes_total,
            "kv_bytes_in_use": self.active_count() * per_slot,
            "prefix_hit_tokens": 0,
            "cow_copies": 0,
            "kv_host_blocks": 0,
            "kv_host_bytes": 0,
            "kv_host_bytes_budget": 0,
            "revive_uploads": 0,
            "prefill_tokens_revived": 0,
            "host_drops": 0,
        }

    def insert(self, request):
        """Seat `request` in a free slot: one prefill forward fills the
        slot's per-layer caches for the prompt and produces the FIRST
        generated token (pushed by the caller — this is the TTFT
        boundary). Returns (slot_idx, first_token, finished); raises
        RuntimeError when no slot is free (callers check free_slots)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        p = len(request.prompt)
        total = p + request.max_new_tokens
        if total > self.seq_len:
            raise ValueError(
                "request needs %d positions > seq_len %d"
                % (total, self.seq_len)
            )
        p_pad = _prefill_bucket(p, self.seq_len)
        fn = self._prefill_fns.get(p_pad)
        if fn is None:
            fn = self._build_prefill(p_pad)
            self._prefill_fns[p_pad] = fn
        buf = np.zeros((1, self.seq_len), np.int32)
        buf[0, :p] = request.prompt
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        with self.trainer.mesh:
            kv, first = fn(
                self._exec_variables, jnp.asarray(buf),
                jnp.asarray(p, jnp.int32),
                jnp.asarray(request.seed, jnp.int32),
                jnp.asarray(request.temperature, jnp.float32),
            )
            self._pool = self._write_slot(kv, slot)
        if prof is not None:
            jax.block_until_ready(self._pool)
            prof.observe("prefill", prof.t() - t0)
        first = int(first)
        # lifecycle annotation on the request's serve span (no-op for
        # untraced requests): which prefill bucket this paid for
        if hasattr(request, "trace_event"):
            request.trace_event("prefill", bucket=p_pad, slot=slot)
        request.generated.append(first)
        request.model_version = self.model_version
        finished = request.max_new_tokens == 1
        if not finished:
            self._slots[slot] = _Slot(request, total)
            self._last_tokens[slot] = first
            self._seeds[slot] = request.seed
            self._temps[slot] = request.temperature
        return slot, first, finished

    def evict(self, slot):
        """Free a slot (completion or deadline eviction). The stale
        cache rows stay until the next insert overwrites them."""
        self._slots[slot] = None

    def evict_expired(self, now):
        """Evict every active request whose deadline has passed;
        returns the evicted requests (the scheduler fails them with
        DEADLINE_EXCEEDED — partial tokens already streamed stand).
        Routed through evict() so the paged pool reclaims blocks."""
        out = []
        for i, st in enumerate(self._slots):
            if st is not None and st.request.expired(now):
                self.evict(i)
                out.append(st.request)
        return out

    def step(self):
        """One vmapped decode step over the WHOLE pool. Every active
        slot advances one token at its own position; free slots run the
        same compute against stale caches and are ignored (static shape,
        zero recompiles). Returns [(slot, request, tokens, finished)]
        for slots that were active — `tokens` is the LIST of tokens the
        step committed for that slot (one here; the speculative paged
        step can commit several). Finished slots are freed."""
        active = [
            (i, s) for i, s in enumerate(self._slots) if s is not None
        ]
        if not active:
            return []
        if self._step_fn is None:
            self._step_fn = self._build_step()
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        with self.trainer.mesh:
            self._pool, nxt = self._step_fn(
                self._exec_variables, self._pool,
                jnp.asarray(self._last_tokens),
                jnp.asarray(self._seeds),
                jnp.asarray(self._temps),
            )
            nxt = np.asarray(nxt)  # blocks on the step
        if prof is not None:
            prof.observe("decode", prof.t() - t0)
        out = []
        for slot, st in active:
            token = int(nxt[slot])
            st.request.generated.append(token)
            st.request.model_version = self.model_version
            self._last_tokens[slot] = token
            finished = (
                len(st.request.prompt) + len(st.request.generated)
                >= st.max_total
            )
            if finished:
                self.evict(slot)
            out.append((slot, st.request, [token], finished))
        return out

    # ------------------------------------------------------- compiled fns

    def _tjit(self, name, fn, **jit_kwargs):
        """jax.jit with recompile-sentry adoption: one fixed NAME per
        call site (buckets included), so a second compile of any name
        is, by construction, the churn-recompiles failure the sentry
        exists to catch."""
        return tracked_jit(
            fn, name, lambda: getattr(self, "sentry", None),
            **jit_kwargs,
        )

    def _build_prefill(self, p_pad):
        model, kv_shapes = self.model, self._kv_shapes
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz

        def prefill(variables, buf, p_len, seed, temperature):
            variables = _maybe_dequantize(variables, qz)
            kv, last = _run_prefill(
                model, variables, kv_shapes, buf, p_len, p_pad
            )
            first = serving_next_token(
                last[0], seed, p_len, temperature, top_k, top_p
            )
            return kv, first

        logger.info("serving: compiling prefill for bucket %d", p_pad)
        return self._tjit("prefill[%d]" % p_pad, prefill)

    def _build_step(self):
        model = self.model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz

        def step(variables, pool, last_tokens, seeds, temps):
            variables = _maybe_dequantize(variables, qz)

            def one(cache, tok, seed, temp):
                # pre-advance counter: the model writes this token's
                # k/v at `pos` and the sampled token lands at pos + 1
                # (the offline loop's `_next_token(..., i + 1)`)
                pos = cache["pos"]
                logits, upd = model.apply(
                    dict(variables, cache=cache),
                    {"tokens": tok[None, None]},
                    training=False, decode=True, mutable=["cache"],
                )
                nxt = serving_next_token(
                    logits[0, 0], seed, pos + 1, temp, top_k, top_p
                )
                return upd["cache"], nxt

            return jax.vmap(one)(pool, last_tokens, seeds, temps)

        logger.info(
            "serving: compiling decode step for %d slots", self.num_slots
        )
        return self._tjit("decode_step", step)

    def _write_slot(self, kv, slot):
        """Insert a batch-1 cache tree into the pool at a TRACED slot
        index (one compiled write serves every slot)."""
        if self._write_fn is None:
            def write(pool, kv, idx):
                def upd(p, n):
                    start = (idx,) + (0,) * n.ndim
                    return jax.lax.dynamic_update_slice(
                        p, n[None], start
                    )

                return jax.tree.map(upd, pool, kv)

            self._write_fn = self._tjit("slot_write", write)
        return self._write_fn(
            self._pool, kv, jnp.asarray(slot, jnp.int32)
        )


class PagedContinuousBatchingEngine(ContinuousBatchingEngine):
    """The decode pool over BLOCK-PAGED KV storage (serving/kv_pool.py).

    Same scheduler surface and token streams as the dense engine; the
    differences are all memory geometry:

    * per-layer KV rows live in shared `[num_blocks, block_size, hkv,
      d]` arenas — total KV HBM is the BLOCK BUDGET, decoupled from
      `num_slots x seq_len`, so more concurrent slots fit in the same
      bytes when requests run short of `seq_len`;
    * insert = the SAME batched prefill, then block-granular writes of
      the prompt's blocks into blocks allocated from the free list
      (never a whole-slot copy), with the request's full token budget
      RESERVED so decode growth cannot strand mid-flight;
    * the single jit-compiled vmapped step carries each slot's block
      table and position as DEVICE arrays: churn, growth and table
      contents never recompile. Attention streams the table
      (ops.paged_decode_attention); the new token's k/v rows come back
      sown through "kv_out" and scatter into the arenas — free lanes
      carry an out-of-bounds block id and drop;
    * evict returns the slot's blocks to the free list, O(1) per
      block — copy-free slot churn.

    PREFIX SHARING (share_prefix=True): the pool keeps a
    content-addressed index of resident full prompt blocks
    (serving/kv_pool.py). A request whose prompt prefix matches seats
    by INCREF — the shared blocks are never re-prefilled; only the
    unshared suffix runs, as ONE decode tile over the resident prefix
    (paged_decode_attention's verify-k shape). A full-prompt match
    re-runs just the last token for its logits; that row's re-write
    into the shared tail block is the planned COPY-ON-WRITE fault,
    drawing the CoW credit the seat reserved.

    SPECULATIVE DECODE (draft=(trainer, state), draft_k=k): a small
    draft model holds a dense per-slot cache pool beside the paged
    target pool. Each scheduler tick drafts k greedy tokens per slot
    (k vmapped single-token draft steps) and verifies them in ONE
    vmapped target step over a (k+1)-token tile; greedy-exact
    accept/rollback commits 1..k+1 tokens — rolled-back rows are
    simply never scattered into the block table, and the draft's
    rollback is counter-only. Sampled (temperature > 0) slots accept
    nothing and commit exactly the token the plain step would have
    sampled, so token parity holds for every request either way.

    can_seat() answers from the allocator (prefix matches shrink what
    a request needs), turning out-of-blocks into admission-queue
    backpressure instead of a crash. Requires the model's paged-decode
    convention (TransformerLM: `paged` kwarg + "kv_out" sowing).

    TIERED HOST SPILL (host_bytes > 0 / EDL_KV_HOST_BYTES): evicted
    refcount-0 prefix chains demote to bounded host-RAM buffers
    instead of being forgotten; a prompt matching a spilled chain
    seats by UPLOAD (serving/kv_pool.py revival) and then runs only
    the unshared suffix through the same `_insert_shared` tile — the
    engine cannot tell a revived prefix from one that never left the
    device, which is exactly why parity holds. Admission charges one
    fresh block per spilled chain entry, so upload latency replaces
    prefill compute without the planner and the allocator ever
    disagreeing.

    INT8 ARENAS (model kv_cache_dtype="int8"): the arenas store
    symmetric per-row int8 rows plus f32 per-row scale arenas
    `[num_blocks, block_size, hkv, 1]` — the scales are KV row leaves
    too, so the same tree-generic pool machinery (build, prompt write,
    scatter, CoW copy) carries them with zero special cases. Rows are
    quantized at the two insertion points only (the prefill cache
    write and the model's decode-tile sow); every read defers the
    dequantize into the paged attention scan. Halves-or-better
    bytes-per-block ON TOP of prefix sharing at the same block count,
    or buys proportionally more blocks at equal bytes — sharing, CoW
    and speculative decode compose unchanged (the trie is keyed on
    token ids, dtype-blind).
    """

    def __init__(self, trainer, state, num_slots, top_k=0, top_p=1.0,
                 block_size=16, num_blocks=0, share_prefix=True,
                 draft=None, draft_k=0, host_bytes=None,
                 prefill_chunk_tokens=None):
        import inspect

        model = trainer.model
        if "paged" not in inspect.signature(
                type(model).__call__).parameters:
            raise ValueError(
                "model %r lacks the paged-decode convention (`paged` "
                "kwarg); serve it with the dense engine"
                % type(model).__name__
            )
        if getattr(model, "kv_cache_dtype", "") not in ("", "int8"):
            raise ValueError(
                "paged KV supports the plain-dtype and int8 cache "
                "formats (kv_cache_dtype=%r)"
                % (getattr(model, "kv_cache_dtype", ""),)
            )
        self.block_size = int(block_size)
        # 0 = dense-equivalent budget: the same KV bytes the dense
        # pool would pin for this slot count
        self.num_blocks = int(num_blocks) or (
            int(num_slots) * -(-int(model.seq_len) // self.block_size)
        )
        self._share = bool(share_prefix)
        # host spill tier (None resolves from EDL_KV_HOST_BYTES): the
        # byte budget for chains demoted to host RAM on eviction,
        # revived by upload instead of re-prefill
        self.host_bytes = (
            kv_host_bytes_default() if host_bytes is None
            else int(host_bytes)
        )
        super().__init__(trainer, state, num_slots, top_k=top_k,
                         top_p=top_p)
        # chunked prefill (None resolves from EDL_PREFILL_CHUNK_TOKENS;
        # 0 = monolithic): long prompts run as fixed-token tiles via
        # begin_insert/advance_prefill so the scheduler can interleave
        # decode ticks between tiles
        self.prefill_chunk_tokens = (
            prefill_chunk_default() if prefill_chunk_tokens is None
            else int(prefill_chunk_tokens)
        )
        self._prefilling = {}  # slot -> _PrefillJob (chunked, pending)
        self._positions = np.zeros(self.num_slots, np.int32)
        self._suffix_fns = {}  # suffix bucket -> compiled tile prefill
        self._spec_fn = None
        self._step_fns_split = None  # (decode, scatter) when profiling
        self._spec_fns_split = None  # (draft, verify, scatter)
        # last-forwarded pool counters: the engine mirrors the pool's
        # monotone spill/revival counters into the closed telemetry
        # set by DELTA, so the event file stays in lockstep with the
        # allocator no matter which path (seat/extend/CoW) spilled
        self._host_counters_seen = {
            "revive_uploads": 0, "prefill_tokens_revived": 0,
            "host_drops": 0,
        }
        self._init_draft(draft, draft_k)

    def _init_pool(self):
        from elasticdl_tpu.serving.kv_pool import PagedKVPool

        self.kv = PagedKVPool(
            self._kv_shapes, self.seq_len, self.num_slots,
            self.num_blocks, self.block_size,
            share_prefix=self._share,
            host_bytes=getattr(self, "host_bytes", 0),
        )
        self._kv_bytes_total = self.kv.bytes_total

    def _init_draft(self, draft, draft_k):
        """Seat the draft model for speculative decode: its own dense
        per-slot cache pool (the draft is small — that is the point)
        beside the paged target pool the reclaimed blocks feed."""
        self._draft = None
        if draft is None or int(draft_k) < 1:
            return
        d_trainer, d_state = draft
        d_model = d_trainer.model
        _require_kv_convention(d_model)
        if not getattr(d_model, "causal", True):
            raise ValueError("speculative decode needs a causal draft")
        if getattr(d_model, "vocab_size", None) != getattr(
                self.model, "vocab_size", None):
            raise ValueError(
                "draft and target must share a vocabulary, got %r vs %r"
                % (getattr(d_model, "vocab_size", None),
                   getattr(self.model, "vocab_size", None))
            )
        if int(d_model.seq_len) < self.seq_len:
            raise ValueError(
                "draft seq_len %d must cover the target's %d"
                % (d_model.seq_len, self.seq_len)
            )
        from elasticdl_tpu.api.quantization import is_quantized

        if is_quantized(d_state.params):
            raise ValueError(
                "speculative decode needs float draft params (the "
                "draft is small; quantizing it buys nothing)"
            )
        from elasticdl_tpu.api.generation import _decode_cache

        self.draft_k = int(draft_k)
        self._draft = d_trainer
        self._d_model = d_model
        self._d_variables = {
            "params": d_state.params, **d_state.model_state
        }
        self._d_kv_shapes = _kv_shapes_for(
            _decode_cache(d_trainer), d_model, 1
        )
        self._d_pool = jax.tree.map(
            lambda sh: jnp.zeros((self.num_slots,) + sh.shape,
                                 sh.dtype),
            self._d_kv_shapes,
        )
        self._d_prefill_fns = {}
        self._d_write_fn = None

    # ------------------------------------------------------------ params

    @property
    def profiler(self):
        return self._profiler

    @profiler.setter
    def profiler(self, value):
        # the paged pool times its own revive uploads (the one phase
        # only it can see), so the profiler forwards to it
        self._profiler = value
        if hasattr(self, "kv"):
            self.kv.profiler = value

    @property
    def sentry(self):
        return self._sentry

    @sentry.setter
    def sentry(self, value):
        # the paged pool compiles its own spill gather / revival
        # upload / prompt write / CoW executables — the sentry
        # forwards so those sites count into the same family; the
        # offline decode caches adopt it too (one process, one sentry)
        self._sentry = value
        if hasattr(self, "kv"):
            self.kv.sentry = value
        from elasticdl_tpu.api import generation as _generation

        _generation.set_decode_sentry(value)

    def set_params(self, state, version):
        """Hot reload, plus the sharing-specific obligation: cached
        prefix rows were computed under the superseded params, so the
        prefix index flushes — a NEW request must never seat on stale
        rows (in-flight sequences keep their caches and continue on
        the new weights, the same contract as the dense engine)."""
        super().set_params(state, version)
        if hasattr(self, "kv"):
            self.kv.flush_prefix_cache()

    # ------------------------------------------------------------- slots

    def can_seat(self, request):
        if (request.max_new_tokens <= 1
                and not getattr(request, "prefill_only", False)):
            return True  # one-token answer; never touches the pool
        cached = len(request.prompt) + request.max_new_tokens - 1
        return self.kv.can_seat(request.prompt, len(request.prompt),
                                cached)

    def max_cached_tokens(self):
        # a request must fit BOTH one slot's table and the whole pool
        return min(self.seq_len, self.num_blocks * self.block_size)

    def kv_stats(self):
        return self.kv.stats()

    def _sync_host_telemetry(self):
        """Forward the pool's monotone spill-tier counters (revival
        uploads, tokens revived instead of re-prefilled, host LRU
        drops) into the closed telemetry counter set by delta — the
        pool is the single source of truth, the telemetry mirror can
        never drift from it."""
        if self.telemetry is None:
            return
        stats = self.kv.stats()
        for name in ("revive_uploads", "prefill_tokens_revived",
                     "host_drops"):
            delta = stats[name] - self._host_counters_seen[name]
            if delta:
                self.telemetry.count(name, delta)
                self._host_counters_seen[name] = stats[name]

    def insert(self, request):
        """Dense-engine contract (prefill + first token), with the KV
        landing in allocated blocks: the allocator reserves the FULL
        cache budget (prompt + max_new_tokens - 1 rows) up front —
        raising OutOfBlocks before any compute — so a seated request
        can always extend to completion. A prompt whose prefix matches
        the resident index seats the shared blocks by incref and runs
        ONLY the unshared suffix. A one-token request skips the pool
        entirely (nothing will ever read its rows)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        p = len(request.prompt)
        total = p + request.max_new_tokens
        if total > self.seq_len:
            raise ValueError(
                "request needs %d positions > seq_len %d"
                % (total, self.seq_len)
            )
        prefill_only = getattr(request, "prefill_only", False)
        decoding = request.max_new_tokens > 1 or prefill_only
        shared = 0
        if decoding:
            # reserve-or-raise BEFORE any compute; the scheduler
            # checks can_seat first, so raising here is a bug guard
            revived_before = self.kv.allocator.blocks_revived
            seat_t0 = time.perf_counter()
            shared = self.kv.seat(slot, request.prompt,
                                  p + request.max_new_tokens - 1)
            revived = (self.kv.allocator.blocks_revived
                       - revived_before)
            if revived and hasattr(request, "trace_event"):
                # the seat revived a spilled chain: the upload IS the
                # seat's cost here, and forensics.attribute() reads
                # this event to split revive_upload out of prefill_own
                request.trace_event(
                    "revive_upload",
                    ms=round((time.perf_counter() - seat_t0)
                             * 1000.0, 3),
                    tokens=revived * self.kv.block_size,
                )
        if decoding and shared:
            first = self._insert_shared(slot, request, shared)
        else:
            p_pad = _prefill_bucket(p, self.seq_len)
            fn = self._prefill_fns.get(p_pad)
            if fn is None:
                fn = self._build_prefill(p_pad)
                self._prefill_fns[p_pad] = fn
            buf = np.zeros((1, self.seq_len), np.int32)
            buf[0, :p] = request.prompt
            prof = self.profiler
            t0 = prof.t() if prof is not None else 0.0
            with self.trainer.mesh:
                kv, first = fn(
                    self._exec_variables, jnp.asarray(buf),
                    jnp.asarray(p, jnp.int32),
                    jnp.asarray(request.seed, jnp.int32),
                    jnp.asarray(request.temperature, jnp.float32),
                )
                if decoding:
                    self.kv.write_prompt(kv, slot, p)
            if prof is not None:
                jax.block_until_ready(self.kv.pools if decoding else first)
                prof.observe("prefill", prof.t() - t0)
            first = int(first)
            if hasattr(request, "trace_event"):
                request.trace_event("prefill", bucket=p_pad, slot=slot,
                                    paged=True)
        if decoding:
            # make this prompt's full blocks matchable (the shared
            # ones are already indexed; walking is idempotent)
            self.kv.register_prefix(slot, request.prompt)
            if self.draft_k and not prefill_only:
                self._prefill_draft(slot, request)
        request.generated.append(first)
        request.model_version = self.model_version
        self._sync_host_telemetry()
        if prefill_only:
            # cache-warming seat (disagg prefill replica): the chain
            # is registered; release the slot's references NOW so the
            # blocks park refcount-0 in the reclaimable cache —
            # matchable, exportable, and reclaimable under pressure
            self.kv.release(slot)
            return slot, first, True
        if not decoding:
            return slot, first, True
        self._slots[slot] = _Slot(request, total)
        self._positions[slot] = p
        self._last_tokens[slot] = first
        self._seeds[slot] = request.seed
        self._temps[slot] = request.temperature
        return slot, first, False

    def _insert_shared(self, slot, request, shared):
        """Seat on a prefix match: the shared blocks are resident, so
        only the suffix `prompt[start:]` runs — ONE decode tile over
        the prefix through the slot's table, its rows scattered into
        the slot's fresh blocks, its last logits sampling the first
        token. A full-prompt match re-runs just the last token; that
        row's write into the shared tail block is the planned CoW
        fault (the seat reserved the credit)."""
        p = len(request.prompt)
        if shared >= p:
            if (self.kv.cow_for_write(slot, p - 1) is not None
                    and self.telemetry is not None):
                self.telemetry.count("cow_copies")
            start = p - 1
        else:
            start = shared
        t = p - start
        t_pad = self._suffix_bucket(t)
        fn = self._suffix_fns.get(t_pad)
        if fn is None:
            fn = self._build_suffix_prefill(t_pad)
            self._suffix_fns[t_pad] = fn
        chunk = np.zeros((1, t_pad), np.int32)
        chunk[0, :t] = request.prompt[start:]
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        with self.trainer.mesh:
            self.kv.pools, first = fn(
                self._exec_variables, self.kv.pools,
                jnp.asarray(self.kv.tables[slot]),
                jnp.asarray(chunk),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(t, jnp.int32),
                jnp.asarray(request.seed, jnp.int32),
                jnp.asarray(request.temperature, jnp.float32),
            )
        if prof is not None:
            jax.block_until_ready(self.kv.pools)
            prof.observe("suffix_tile", prof.t() - t0)
        if self.telemetry is not None:
            # count the allocator-reported shared tokens so this stays
            # in lockstep with BlockAllocator.prefix_hit_tokens (start
            # is shared - 1 on a full-prompt match: the re-run row)
            self.telemetry.count("prefix_hit_tokens", shared)
        if hasattr(request, "trace_event"):
            request.trace_event("prefix_hit", slot=slot,
                                shared_tokens=start, suffix_tokens=t)
        return int(first)

    # --------------------------------------------------- chunked prefill

    def free_slots(self):
        # a seated-but-still-prefilling slot is occupied: its blocks
        # are reserved and its tiles are mid-flight
        return [i for i, s in enumerate(self._slots)
                if s is None and i not in self._prefilling]

    def active_requests(self):
        reqs = [s.request for s in self._slots if s is not None]
        reqs.extend(j.request for j in self._prefilling.values())
        return reqs

    def prefilling_count(self):
        return len(self._prefilling)

    def begin_insert(self, request):
        """Chunked admission: seat `request` — the same full-budget
        reservation as insert() — and return a _PrefillJob whose tiles
        advance_prefill() runs between decode ticks. Prompts that need
        no chunking (chunking off, one-token answers, full-prompt
        prefix matches) complete immediately: job.done() is True and
        job.first/job.finished carry the insert() result, so the
        caller has ONE completion path either way."""
        chunk = self.prefill_chunk_tokens
        prefill_only = getattr(request, "prefill_only", False)
        if not chunk or (request.max_new_tokens <= 1
                         and not prefill_only):
            slot, first, finished = self.insert(request)
            job = _PrefillJob(slot, request, len(request.prompt))
            job.first, job.finished = first, finished
            return job
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        p = len(request.prompt)
        total = p + request.max_new_tokens
        if total > self.seq_len:
            raise ValueError(
                "request needs %d positions > seq_len %d"
                % (total, self.seq_len)
            )
        revived_before = self.kv.allocator.blocks_revived
        seat_t0 = time.perf_counter()
        shared = self.kv.seat(slot, request.prompt,
                              p + request.max_new_tokens - 1)
        revived = self.kv.allocator.blocks_revived - revived_before
        if revived and hasattr(request, "trace_event"):
            request.trace_event(
                "revive_upload",
                ms=round((time.perf_counter() - seat_t0) * 1000.0, 3),
                tokens=revived * self.kv.block_size,
            )
        if shared >= p:
            # full-prompt match: the one-token re-run tile IS the
            # whole prefill — nothing left to chunk
            first = self._insert_shared(slot, request, shared)
            job = _PrefillJob(slot, request, p)
            self._finish_prefill(job, first)
            return job
        if shared:
            if self.telemetry is not None:
                self.telemetry.count("prefix_hit_tokens", shared)
            if hasattr(request, "trace_event"):
                request.trace_event(
                    "prefix_hit", slot=slot, shared_tokens=shared,
                    suffix_tokens=p - shared,
                )
        job = _PrefillJob(slot, request, shared)
        self._prefilling[slot] = job
        return job

    def advance_prefill(self, job):
        """Run ONE tile of `job`'s pending prompt: decode up to
        prefill_chunk_tokens prompt tokens at positions
        [pos, pos + t) over the slot's resident blocks and scatter
        their rows — the shared-prefix suffix executable pointed at a
        chunk window, so chunking adds no new compiled surface. The
        FINAL tile's sample (position = prompt length, the monolithic
        prefill's sampling position) is the request's first generated
        token; non-final samples are discarded. Returns True when the
        job completed this call."""
        if job.done():
            return True
        slot, request = job.slot, job.request
        p = job.prompt_len
        t = min(self.prefill_chunk_tokens, p - job.pos)
        final = job.pos + t >= p
        t_pad = self._suffix_bucket(t)
        fn = self._suffix_fns.get(t_pad)
        if fn is None:
            fn = self._build_suffix_prefill(t_pad)
            self._suffix_fns[t_pad] = fn
        chunk = np.zeros((1, t_pad), np.int32)
        chunk[0, :t] = request.prompt[job.pos:job.pos + t]
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        with self.trainer.mesh:
            self.kv.pools, first = fn(
                self._exec_variables, self.kv.pools,
                jnp.asarray(self.kv.tables[slot]),
                jnp.asarray(chunk),
                jnp.asarray(job.pos, jnp.int32),
                jnp.asarray(t, jnp.int32),
                jnp.asarray(request.seed, jnp.int32),
                jnp.asarray(request.temperature, jnp.float32),
            )
        if prof is not None:
            jax.block_until_ready(self.kv.pools)
            prof.observe("prefill_tile", prof.t() - t0)
        job.pos += t
        job.tiles += 1
        if not final:
            return False
        if hasattr(request, "trace_event"):
            request.trace_event(
                "prefill", slot=slot, paged=True, tiles=job.tiles,
                chunk_tokens=self.prefill_chunk_tokens,
            )
        self._finish_prefill(job, int(first))
        return True

    def _finish_prefill(self, job, first):
        """The chunked path's insert() epilogue: index the prompt,
        seat the draft, commit the first token, and either activate
        the slot for decode or (prefill-only) release it with the
        chain parked exportable."""
        slot, request = job.slot, job.request
        self._prefilling.pop(slot, None)
        prefill_only = getattr(request, "prefill_only", False)
        self.kv.register_prefix(slot, request.prompt)
        if self.draft_k and not prefill_only:
            self._prefill_draft(slot, request)
        request.generated.append(first)
        request.model_version = self.model_version
        self._sync_host_telemetry()
        job.first = first
        if prefill_only or request.max_new_tokens <= 1:
            self.kv.release(slot)
            job.finished = True
            return
        self._slots[slot] = _Slot(
            request, job.prompt_len + request.max_new_tokens
        )
        self._positions[slot] = job.prompt_len
        self._last_tokens[slot] = first
        self._seeds[slot] = request.seed
        self._temps[slot] = request.temperature

    def abort_prefill(self, job):
        """Abandon a pending chunked prefill (deadline expiry between
        tiles): release the seat — rows already scattered die with
        their blocks' refcounts; shared ancestors survive under their
        other owners."""
        if self._prefilling.pop(job.slot, None) is None:
            return
        job.finished = True
        self.kv.release(job.slot)

    def _prefill_draft(self, slot, request):
        """Fill the draft's dense cache for this prompt (the draft has
        no paged pool, so it always prefills the full prompt — it is
        small enough that this is noise next to the target)."""
        p = len(request.prompt)
        p_pad = _prefill_bucket(p, self.seq_len)
        fn = self._d_prefill_fns.get(p_pad)
        if fn is None:
            fn = self._build_draft_prefill(p_pad)
            self._d_prefill_fns[p_pad] = fn
        buf = np.zeros((1, self.seq_len), np.int32)
        buf[0, :p] = request.prompt
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        with self.trainer.mesh:
            d_kv = fn(self._d_variables, jnp.asarray(buf),
                      jnp.asarray(p, jnp.int32))
            self._write_draft_slot(d_kv, slot)
        if prof is not None:
            jax.block_until_ready(self._d_pool)
            prof.observe("draft", prof.t() - t0)

    def _suffix_bucket(self, t):
        """Static tile widths for the suffix prefill, in steps of 8 so
        nearby suffix lengths share one executable."""
        return min(self.seq_len, -(-int(t) // 8) * 8)

    def evict(self, slot):
        """Free the slot AND drop its block references; private rows
        are dead the moment the table forgets them, shared rows live
        on under their other owners (copy-free churn — nothing is
        zeroed or moved)."""
        self._slots[slot] = None
        self._positions[slot] = 0
        self.kv.release(slot)

    def step(self):
        """One vmapped decode step over the whole pool, paged: block
        tables and positions enter as device arrays, each active slot
        attends over its own table and its row scatters into its own
        block. Free lanes ride along masked (stale tokens, all-(-1)
        tables, out-of-bounds scatter ids) — the dense engine's
        static-shape contract, kept. With a draft seated the step is
        the speculative draft-verify tick instead, committing 1..k+1
        tokens per slot. Returns [(slot, request, tokens, finished)]."""
        active = [
            (i, s) for i, s in enumerate(self._slots) if s is not None
        ]
        if not active:
            return []
        if self.draft_k:
            return self._spec_step(active)
        for i, _st in active:
            # the block this step writes (position = the slot's pos);
            # drawn from the slot's reservation, so it cannot fail
            self.kv.ensure_blocks(i, int(self._positions[i]))
        # an extend's pop can spill under pressure: keep the telemetry
        # mirror current even on decode-only ticks
        self._sync_host_telemetry()
        if self.profiler is not None:
            nxt = self._profiled_step()
        else:
            if self._step_fn is None:
                self._step_fn = self._build_paged_step()
            with self.trainer.mesh:
                self.kv.pools, nxt = self._step_fn(
                    self._exec_variables, self.kv.pools,
                    self.kv.tables_device(),
                    jnp.asarray(self._positions),
                    jnp.asarray(self._last_tokens),
                    jnp.asarray(self._seeds),
                    jnp.asarray(self._temps),
                )
                nxt = np.asarray(nxt)
        out = []
        for slot, st in active:
            self._positions[slot] += 1
            token = int(nxt[slot])
            st.request.generated.append(token)
            st.request.model_version = self.model_version
            self._last_tokens[slot] = token
            finished = (
                len(st.request.prompt) + len(st.request.generated)
                >= st.max_total
            )
            if finished:
                self.evict(slot)
            out.append((slot, st.request, [token], finished))
        return out

    def _spec_step(self, active):
        """One speculative tick: k drafted tokens per slot, verified
        in ONE vmapped target step, greedy-exact accept/rollback.
        Rolled-back rows are never committed to the block table
        (their scatter ids are masked out-of-bounds inside the step);
        the draft's rollback is counter-only."""
        k = self.draft_k
        budgets = np.ones(self.num_slots, np.int32)
        for i, st in active:
            pos = int(self._positions[i])
            # materialize every block this tick MIGHT write (rows
            # pos..pos+k, capped at the slot's last needed row) —
            # reservation-backed, cannot fail for a seated request
            self.kv.ensure_blocks(i, min(pos + k, st.max_total - 2))
            budgets[i] = st.max_total - (
                len(st.request.prompt) + len(st.request.generated)
            )
        self._sync_host_telemetry()  # ensure_blocks pops can spill
        if self.profiler is not None:
            toks, counts = self._profiled_spec_step(budgets)
        else:
            if self._spec_fn is None:
                self._spec_fn = self._build_spec_step()
            with self.trainer.mesh:
                self.kv.pools, self._d_pool, toks, counts = (
                    self._spec_fn(
                        self._exec_variables, self._d_variables,
                        self.kv.pools, self._d_pool,
                        self.kv.tables_device(),
                        jnp.asarray(self._positions),
                        jnp.asarray(self._last_tokens),
                        jnp.asarray(self._seeds),
                        jnp.asarray(self._temps),
                        jnp.asarray(budgets),
                    )
                )
                toks = np.asarray(toks)
                counts = np.asarray(counts)
        out = []
        accepted = 0
        for slot, st in active:
            c = int(counts[slot])
            committed = [int(x) for x in toks[slot, :c]]
            st.request.generated.extend(committed)
            st.request.model_version = self.model_version
            self._positions[slot] += c
            self._last_tokens[slot] = committed[-1]
            accepted += c - 1
            finished = (
                len(st.request.prompt) + len(st.request.generated)
                >= st.max_total
            )
            if finished:
                self.evict(slot)
            out.append((slot, st.request, committed, finished))
        self.draft_proposed += k * len(active)
        self.draft_accepted += accepted
        if self.telemetry is not None:
            self.telemetry.count("draft_proposed", k * len(active))
            if accepted:
                self.telemetry.count("draft_accepted", accepted)
        return out

    # ------------------------------------------------------- compiled fns

    def _build_paged_step(self):
        from elasticdl_tpu.serving.kv_pool import scatter_rows

        model = self.model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz
        block_size, num_blocks = self.block_size, self.num_blocks

        def step(variables, pools, tables, positions, last_tokens,
                 seeds, temps):
            variables = _maybe_dequantize(variables, qz)

            def one(table, pos, tok, seed, temp):
                # pre-advance counter semantics match the dense step:
                # this token's k/v rows belong at `pos`, the sampled
                # token lands at pos + 1. The cache collection carries
                # ONLY the counter — the rows live in the shared
                # arenas, read through this slot's table and written
                # back via the sown "kv_out" rows.
                logits, aux = model.apply(
                    dict(variables, cache={"pos": pos}),
                    {"tokens": tok[None, None]},
                    training=False, decode=True,
                    mutable=["cache", "kv_out"],
                    paged={"pools": pools, "table": table[None]},
                )
                nxt = serving_next_token(
                    logits[0, 0], seed, pos + 1, temp, top_k, top_p
                )
                rows = jax.tree.map(
                    lambda t: t[0][0, :, 0, :], aux["kv_out"],
                    is_leaf=lambda x: isinstance(x, tuple),
                )  # sown [1, hkv, 1, d] -> [hkv, d]
                return nxt, rows

            nxt, rows = jax.vmap(one)(
                tables, positions, last_tokens, seeds, temps
            )
            bids = jnp.take_along_axis(
                tables, (positions // block_size)[:, None], axis=1
            )[:, 0]
            # free lanes (table row -1): point past the arena so the
            # scatter's mode="drop" discards them
            bids = jnp.where(bids < 0, num_blocks, bids)
            pools = scatter_rows(pools, rows, bids,
                                 positions % block_size)
            return pools, nxt

        logger.info(
            "serving: compiling paged decode step for %d slots over "
            "%d x %d-token blocks", self.num_slots, self.num_blocks,
            self.block_size,
        )
        return self._tjit("paged_step", step)

    # ------------------------------------------- profiled (split) steps

    def _profiled_step(self):
        """The plain paged tick with the profiler on: the SAME math as
        the fused step, split at the decode|scatter boundary so each
        phase times against blocked outputs. Returns the sampled
        tokens as a numpy array (the fused path's contract)."""
        prof = self.profiler
        if self._step_fns_split is None:
            self._step_fns_split = self._build_paged_step_split()
        decode_fn, scatter_fn = self._step_fns_split
        with self.trainer.mesh:
            tables = self.kv.tables_device()
            positions = jnp.asarray(self._positions)
            t0 = prof.t()
            nxt, rows = decode_fn(
                self._exec_variables, self.kv.pools, tables,
                positions, jnp.asarray(self._last_tokens),
                jnp.asarray(self._seeds), jnp.asarray(self._temps),
            )
            jax.block_until_ready(nxt)
            prof.observe("decode", prof.t() - t0)
            t0 = prof.t()
            self.kv.pools = scatter_fn(
                self.kv.pools, rows, tables, positions
            )
            jax.block_until_ready(self.kv.pools)
            prof.observe("scatter", prof.t() - t0)
            return np.asarray(nxt)

    def _profiled_spec_step(self, budgets):
        """The speculative tick with the profiler on, split at the
        draft|verify|scatter boundaries (same arrays cross the host
        boundary that the fused step keeps on device — token streams
        are identical, pinned by the e2e battery)."""
        prof = self.profiler
        if self._spec_fns_split is None:
            self._spec_fns_split = self._build_spec_step_split()
        draft_fn, verify_fn, scatter_fn = self._spec_fns_split
        with self.trainer.mesh:
            tables = self.kv.tables_device()
            positions = jnp.asarray(self._positions)
            t0 = prof.t()
            self._d_pool, d_toks, chunk = draft_fn(
                self._d_variables, self._d_pool, positions,
                jnp.asarray(self._last_tokens),
            )
            jax.block_until_ready(chunk)
            prof.observe("draft", prof.t() - t0)
            t0 = prof.t()
            toks, counts, rows, bids, offs = verify_fn(
                self._exec_variables, self.kv.pools, tables,
                positions, chunk, d_toks,
                jnp.asarray(self._seeds), jnp.asarray(self._temps),
                jnp.asarray(budgets),
            )
            jax.block_until_ready(toks)
            prof.observe("verify_commit", prof.t() - t0)
            t0 = prof.t()
            self.kv.pools = scatter_fn(self.kv.pools, rows, bids, offs)
            jax.block_until_ready(self.kv.pools)
            prof.observe("scatter", prof.t() - t0)
            return np.asarray(toks), np.asarray(counts)

    def _build_paged_step_split(self):
        """The fused `_build_paged_step` math as two executables:
        decode (model apply + sample, rows sown out) and scatter (row
        write into the arenas). Only cross-phase fusion is given up —
        every op and every mask is the fused step's."""
        from elasticdl_tpu.serving.kv_pool import scatter_rows

        model = self.model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz
        block_size, num_blocks = self.block_size, self.num_blocks

        def decode(variables, pools, tables, positions, last_tokens,
                   seeds, temps):
            variables = _maybe_dequantize(variables, qz)

            def one(table, pos, tok, seed, temp):
                logits, aux = model.apply(
                    dict(variables, cache={"pos": pos}),
                    {"tokens": tok[None, None]},
                    training=False, decode=True,
                    mutable=["cache", "kv_out"],
                    paged={"pools": pools, "table": table[None]},
                )
                nxt = serving_next_token(
                    logits[0, 0], seed, pos + 1, temp, top_k, top_p
                )
                rows = jax.tree.map(
                    lambda t: t[0][0, :, 0, :], aux["kv_out"],
                    is_leaf=lambda x: isinstance(x, tuple),
                )
                return nxt, rows

            return jax.vmap(one)(
                tables, positions, last_tokens, seeds, temps
            )

        def scatter(pools, rows, tables, positions):
            bids = jnp.take_along_axis(
                tables, (positions // block_size)[:, None], axis=1
            )[:, 0]
            bids = jnp.where(bids < 0, num_blocks, bids)
            return scatter_rows(pools, rows, bids,
                                positions % block_size)

        logger.info(
            "serving: compiling SPLIT (profiled) paged decode step "
            "for %d slots", self.num_slots,
        )
        return (self._tjit("paged_decode.split", decode),
                self._tjit("paged_scatter.split", scatter))

    def _build_spec_step_split(self):
        """The fused `_build_spec_step` math as three executables —
        draft scan | target verify + accept/commit | row scatter —
        for phase attribution under the profiler."""
        from elasticdl_tpu.serving.kv_pool import scatter_rows

        model, d_model = self.model, self._d_model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz
        block_size, num_blocks = self.block_size, self.num_blocks
        max_blocks = self.kv.max_blocks_per_slot
        k = self.draft_k

        def draft(d_variables, d_pool, positions, last_tokens):
            d_pool_f = dict(d_pool, pos=positions)

            def d_one(cache, tok):
                lg, upd = d_model.apply(
                    dict(d_variables, cache=cache),
                    {"tokens": tok[None, None]},
                    training=False, decode=True, mutable=["cache"],
                )
                nxt = jnp.argmax(lg[0, 0], axis=-1).astype(jnp.int32)
                return upd["cache"], nxt

            def d_scan(carry, _):
                cache, tok = carry
                cache, nxt = jax.vmap(d_one)(cache, tok)
                return (cache, nxt), nxt

            (d_pool_out, _), d_seq = jax.lax.scan(
                d_scan, (d_pool_f, last_tokens), None, length=k
            )
            d_toks = jnp.moveaxis(d_seq, 0, 1)
            chunk = jnp.concatenate(
                [last_tokens[:, None], d_toks], axis=1
            )
            return d_pool_out, d_toks, chunk

        def verify(variables, pools, tables, positions, chunk, d_toks,
                   seeds, temps, budgets):
            variables = _maybe_dequantize(variables, qz)

            def v_one(table, pos, toks):
                logits, aux = model.apply(
                    dict(variables, cache={"pos": pos}),
                    {"tokens": toks[None]},
                    training=False, decode=True,
                    mutable=["cache", "kv_out"],
                    paged={"pools": pools, "table": table[None]},
                )
                g = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
                rows = jax.tree.map(
                    lambda s: s[0][0].transpose(1, 0, 2),
                    aux["kv_out"],
                    is_leaf=lambda x: isinstance(x, tuple),
                )
                return logits[0], g, rows

            logits, g, rows = jax.vmap(v_one)(tables, positions, chunk)
            match = jnp.cumprod(
                (d_toks == g[:, :k]).astype(jnp.int32), axis=1
            )
            a = jnp.where(temps > 0.0, 0, match.sum(axis=1))
            c = jnp.minimum(a + 1, jnp.maximum(budgets, 1))

            def pick(lg, aa, seed, pos, temp):
                return serving_next_token(
                    lg[aa], seed, pos + 1 + aa, temp, top_k, top_p
                )

            bonus = jax.vmap(pick)(logits, a, seeds, positions, temps)
            out_toks = jnp.where(
                jnp.arange(k + 1)[None, :] == a[:, None],
                bonus[:, None], g,
            )
            wpos = positions[:, None] + jnp.arange(k + 1)[None, :]
            bids = jnp.take_along_axis(
                tables,
                jnp.minimum(wpos // block_size, max_blocks - 1),
                axis=1,
            )
            keep = (
                (jnp.arange(k + 1)[None, :] < c[:, None]) & (bids >= 0)
            )
            bids = jnp.where(keep, bids, num_blocks)
            return out_toks, c, rows, bids, wpos % block_size

        def scatter(pools, rows, bids, offs):
            return scatter_rows(pools, rows, bids, offs)

        logger.info(
            "serving: compiling SPLIT (profiled) speculative step "
            "(k=%d) for %d slots", k, self.num_slots,
        )
        return (self._tjit("spec_draft.split", draft),
                self._tjit("spec_verify.split", verify),
                self._tjit("spec_scatter.split", scatter))

    def _build_suffix_prefill(self, t_pad):
        """Compiled shared-prefix suffix prefill: decode a tile of up
        to `t_pad` prompt tokens at positions [start, start + t) over
        the resident prefix blocks, scatter the tile's rows into the
        slot's blocks (pad rows dropped via out-of-bounds ids), and
        sample the first generated token from the last REAL row's
        logits. One executable per tile bucket."""
        from elasticdl_tpu.serving.kv_pool import scatter_rows

        model = self.model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz
        block_size, num_blocks = self.block_size, self.num_blocks
        max_blocks = self.kv.max_blocks_per_slot

        def fn(variables, pools, table, chunk, start, t_real, seed,
               temp):
            variables = _maybe_dequantize(variables, qz)
            logits, aux = model.apply(
                dict(variables, cache={"pos": start}),
                {"tokens": chunk},
                training=False, decode=True,
                mutable=["cache", "kv_out"],
                paged={"pools": pools, "table": table[None]},
            )  # logits [1, t_pad, V]
            rows = jax.tree.map(
                lambda s: s[0][0].transpose(1, 0, 2), aux["kv_out"],
                is_leaf=lambda x: isinstance(x, tuple),
            )  # sown [1, hkv, t_pad, d] -> [t_pad, hkv, d]
            pos = start + jnp.arange(t_pad)
            bids = jnp.take(
                table, jnp.minimum(pos // block_size, max_blocks - 1)
            )
            keep = (jnp.arange(t_pad) < t_real) & (bids >= 0)
            bids = jnp.where(keep, bids, num_blocks)
            pools = scatter_rows(pools, rows, bids, pos % block_size)
            step_logits = jnp.take(logits[0], t_real - 1, axis=0)
            first = serving_next_token(
                step_logits, seed, start + t_real, temp, top_k, top_p
            )
            return pools, first

        logger.info(
            "serving: compiling shared-prefix suffix prefill for "
            "tile %d", t_pad,
        )
        return self._tjit("suffix_prefill[%d]" % t_pad, fn)

    def _build_draft_prefill(self, p_pad):
        d_model, d_kv_shapes = self._d_model, self._d_kv_shapes

        def prefill(d_variables, buf, p_len):
            kv, _last = _run_prefill(
                d_model, d_variables, d_kv_shapes, buf, p_len, p_pad
            )
            return kv

        logger.info(
            "serving: compiling draft prefill for bucket %d", p_pad
        )
        return self._tjit("draft_prefill[%d]" % p_pad, prefill)

    def _write_draft_slot(self, kv, slot):
        if self._d_write_fn is None:
            def write(pool, kv, idx):
                def upd(p, n):
                    start = (idx,) + (0,) * n.ndim
                    return jax.lax.dynamic_update_slice(
                        p, n[None], start
                    )

                return jax.tree.map(upd, pool, kv)

            self._d_write_fn = self._tjit("draft_slot_write", write)
        self._d_pool = self._d_write_fn(
            self._d_pool, kv, jnp.asarray(slot, jnp.int32)
        )

    def _build_spec_step(self):
        """The speculative tick as ONE compiled program: k vmapped
        draft steps (a lax.scan of single-token greedy proposals),
        then the target verifying the whole [last, d_1..d_k] tile in
        one vmapped (k+1)-wide paged decode. Acceptance is the longest
        greedy-matching proposal prefix (0 for sampled slots, whose
        committed token is exactly the plain step's sample); commit
        c = min(accepted + 1, remaining budget) tokens — row scatters
        for j >= c are masked to out-of-bounds ids, so rolled-back
        rows never reach the block table, and the draft rolls back by
        counter only (its pos is forced from `positions` each tick)."""
        from elasticdl_tpu.serving.kv_pool import scatter_rows

        model, d_model = self.model, self._d_model
        top_k, top_p, qz = self.top_k, self.top_p, self._exec_qz
        block_size, num_blocks = self.block_size, self.num_blocks
        max_blocks = self.kv.max_blocks_per_slot
        k = self.draft_k

        def step(variables, d_variables, pools, d_pool, tables,
                 positions, last_tokens, seeds, temps, budgets):
            variables = _maybe_dequantize(variables, qz)
            # force the draft counters to the committed truth — the
            # rollback contract: rows past the counter are masked junk
            d_pool_f = dict(d_pool, pos=positions)

            def d_one(cache, tok):
                lg, upd = d_model.apply(
                    dict(d_variables, cache=cache),
                    {"tokens": tok[None, None]},
                    training=False, decode=True, mutable=["cache"],
                )
                nxt = jnp.argmax(lg[0, 0], axis=-1).astype(jnp.int32)
                return upd["cache"], nxt

            def d_scan(carry, _):
                cache, tok = carry
                cache, nxt = jax.vmap(d_one)(cache, tok)
                return (cache, nxt), nxt

            (d_pool_out, _), d_seq = jax.lax.scan(
                d_scan, (d_pool_f, last_tokens), None, length=k
            )
            d_toks = jnp.moveaxis(d_seq, 0, 1)  # [S, k]
            chunk = jnp.concatenate(
                [last_tokens[:, None], d_toks], axis=1
            )  # [S, k+1]; row j = the token at stream position pos+j

            def v_one(table, pos, toks):
                logits, aux = model.apply(
                    dict(variables, cache={"pos": pos}),
                    {"tokens": toks[None]},
                    training=False, decode=True,
                    mutable=["cache", "kv_out"],
                    paged={"pools": pools, "table": table[None]},
                )  # logits [1, k+1, V]: row j predicts pos + j + 1
                g = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
                rows = jax.tree.map(
                    lambda s: s[0][0].transpose(1, 0, 2),
                    aux["kv_out"],
                    is_leaf=lambda x: isinstance(x, tuple),
                )  # [k+1, hkv, d]
                return logits[0], g, rows

            logits, g, rows = jax.vmap(v_one)(tables, positions, chunk)
            # longest greedy-matching proposal prefix, per slot;
            # sampled slots accept nothing (their committed token is
            # the sampled one below — exactly the plain step's)
            match = jnp.cumprod(
                (d_toks == g[:, :k]).astype(jnp.int32), axis=1
            )
            a = jnp.where(temps > 0.0, 0, match.sum(axis=1))  # [S]
            c = jnp.minimum(a + 1, jnp.maximum(budgets, 1))
            # committed token j < a: the greedy target (== proposal);
            # j == a: the correction/bonus, sampled exactly like the
            # plain step at position pos + 1 + a
            def pick(lg, aa, seed, pos, temp):
                return serving_next_token(
                    lg[aa], seed, pos + 1 + aa, temp, top_k, top_p
                )

            bonus = jax.vmap(pick)(logits, a, seeds, positions, temps)
            out_toks = jnp.where(
                jnp.arange(k + 1)[None, :] == a[:, None],
                bonus[:, None], g,
            )  # [S, k+1]; entries past c-1 are dead
            # scatter ONLY the committed rows j < c (free lanes carry
            # -1 tables; both mask to the out-of-bounds drop id)
            wpos = positions[:, None] + jnp.arange(k + 1)[None, :]
            bids = jnp.take_along_axis(
                tables, jnp.minimum(wpos // block_size, max_blocks - 1),
                axis=1,
            )
            keep = (jnp.arange(k + 1)[None, :] < c[:, None]) & (bids >= 0)
            bids = jnp.where(keep, bids, num_blocks)
            pools = scatter_rows(pools, rows, bids, wpos % block_size)
            return pools, d_pool_out, out_toks, c

        logger.info(
            "serving: compiling speculative draft-verify step "
            "(k=%d) for %d slots", k, self.num_slots,
        )
        return self._tjit("spec_step", step)
