"""Health-checked multi-replica serving router.

The routing tier in front of N GenerationServer replicas — the serving
twin of the master's fault-tolerance story: the master relaunches pods
and requeues tasks so a training job survives membership churn; the
router re-dispatches requests so the SERVING fleet does. The invariant
it sells is robustness, not speed: a request the router ACCEPTED is
never silently lost. It completes, or it fails with an explicit status
the client can act on — never a hang, never a dropped stream the client
has to time out.

    clients ──router_generate[_stream]──> Router ──generate──> replica 1
                                            │  ^                replica 2
                              heartbeat ────┘  └─ server_status replica 3

Mechanisms, each its own small state machine:

* **Leases** — a heartbeat loop polls every replica's `server_status`
  each `poll_secs`, concurrently (a wedged replica never stalls the
  others' renewals; the sweep is bounded regardless of replica
  count); a successful poll renews the replica's lease for
  `lease_secs` and refreshes its load signals (queue depth, active
  slots, kv_blocks_free, queue_wait_ms EWMA) and drain flag. A replica
  whose lease expires — crashed, wedged, partitioned — leaves the
  rotation passively: nothing needs to detect the death, the lease
  just stops being renewed.

* **Least-loaded routing** — among in-rotation replicas (lease valid,
  not draining, breaker not open) dispatch goes to the lowest load
  score: queue_depth + active_slots + queue_wait_ms/50 (the wait EWMA
  catches the case where two replicas have equal queue DEPTH but very
  different queue TIME), ties broken toward more free KV blocks.

* **Prefix-affine dispatch** — requests with at least one full KV
  block of prompt are fingerprinted over their leading blocks (the
  content-addressed trie's chain key: H(parent ‖ block tokens)) and
  steered to the replica that last served that chain, so shared
  system prompts prefill once instead of once per unlucky dispatch.
  Affinity is a HINT with a decay ladder, never an override: the
  learned target must still be in rotation (a draining or stalled
  replica is never affine-dispatched, perfect prefix match or not),
  must still report warm prefix capacity, and must sit within a load
  margin of the least-loaded candidate — any rung failing decays the
  request to pure least-loaded routing. `affinity_hits`/
  `affinity_misses` count the ladder's verdicts.

* **Circuit breakers** — per replica, CLOSED -> OPEN after
  `breaker_threshold` CONSECUTIVE transient dispatch failures; OPEN
  rejects dispatch for `breaker_cooldown_secs`, then HALF_OPEN admits
  exactly one probe request — success closes the breaker, a transient
  failure re-opens it and restarts the cooldown, and any OTHER
  outcome releases the probe slot (a leaked slot would evict the
  replica forever). RESOURCE_EXHAUSTED (backpressure from a live
  replica) re-routes but does NOT count against the breaker: the
  replica answered, so it is healthy — its capacity is not — and on a
  half-open probe that proof of life closes the breaker.

* **Bounded re-dispatch** — every dispatch failure is classified with
  common/retry.py: transient (UNAVAILABLE/CANCELLED/timeout) and
  backpressure (RESOURCE_EXHAUSTED) failures re-dispatch to another
  replica with full-jitter backoff inside `redispatch_window_secs`;
  anything else (INVALID_ARGUMENT, a client deadline genuinely spent)
  propagates immediately. Unary generates are idempotent — token
  streams depend only on (params, prompt, seed, temperature), never on
  which replica ran them — so re-dispatch at ANY point is safe.
  Streams re-dispatch only BEFORE the first chunk reaches the client;
  after that the router fails the stream explicitly rather than
  replaying tokens the client already has.

* **Hedged dispatch** — with `hedge_delay_secs > 0`, a unary generate
  that hasn't answered within the delay is duplicated to the next-best
  replica and the first success wins (the same idempotency that makes
  re-dispatch safe makes the duplicate free of semantic risk). Tail
  latency insurance, off by default.

* **Degradation ladder** — draining replicas leave the rotation for
  NEW requests while their in-flight streams finish; when NO replica
  is in rotation (all leases expired / breakers open / draining) the
  router sheds load with an immediate RESOURCE_EXHAUSTED instead of
  queueing into a black hole. Shed is the bottom rung, and it is loud:
  the `shed` counter and `router/healthy_replicas` gauge mark it.

Fault injection: the servicer wraps at the same choke point the master
and replica servicers use (common/fault_injection.py) under the
router-specific RPC names (`router_generate:drop:1`, ...), so chaos
specs can target the router boundary without touching replicas.

Observability (elasticdl_tpu/observability/): every routed request is
one SPAN TREE — a `router_generate[_stream]` root opened here (or
adopted from the client's trace context), one `dispatch` child per
leg, so hedges and re-dispatches land as sibling spans and the
replica's `serve` span parents under the leg that carried it. The
router also records its end-to-end dispatch latency into the shared
log-linear histogram (router_status e2e_p50/90/99_ms) and merges the
replicas' TTFT/queue-wait histogram BUCKETS from their heartbeat
status into fleet-wide percentiles — bucket addition, never
percentile averaging.
"""

import threading
import time
from concurrent import futures

try:
    import queue as _queue
except ImportError:  # pragma: no cover - py2 never happens here
    import Queue as _queue

from elasticdl_tpu.common.fault_injection import (
    SERVING_RPCS,
    FaultInjector,
    InjectedRpcError,
    maybe_wrap_servicer,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.retry import (
    RetryPolicy,
    is_backpressure_rpc_error,
    is_transient_rpc_error,
)
from elasticdl_tpu.observability.histogram import LogLinearHistogram
from elasticdl_tpu.observability.metrics import (
    MetricsServer,
    add_counts,
    counter_family,
    gauge_family,
    metrics_port_default,
)
from elasticdl_tpu.observability.slo import (
    BurnRateEngine,
    default_router_slos,
)
from elasticdl_tpu.observability.tracing import recorder
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.admission import AdmissionError
from elasticdl_tpu.serving.disagg import HandoffCoordinator
from elasticdl_tpu.serving.prefix_affinity import (
    AffinityIndex,
    prefix_fingerprint,
)
from elasticdl_tpu.serving.telemetry import RouterTelemetry


class RouterError(AdmissionError):
    """Terminal router-side failure; `code` is the gRPC status name the
    servicer maps to (same duality as the replica's AdmissionError:
    raised in-process, context.abort over real gRPC)."""


class RouterConfig(object):
    """Routing-tier knobs. lease_secs should cover a few poll periods
    (a single dropped poll must not evict a healthy replica); the
    heartbeat polls replicas concurrently and caps each sweep at
    min(poll_timeout_secs, lease_secs / 2), so lease safety never
    depends on replica count — keep lease_secs > poll_timeout_secs /
    2 + poll_secs so one wedged-replica sweep cannot outlast a healthy
    lease. redispatch_window_secs bounds the TOTAL time one request
    may spend being re-dispatched before its last error propagates.

    SLO knobs: the burn-rate engine (observability/slo.py) windows the
    router's time-series ring with `slo_fast/slow_window_secs` and
    evaluates three declared objectives — fleet TTFT p99 under
    `slo_ttft_p99_ms`, router e2e p99 under `slo_e2e_p99_ms` (both
    with error budget `slo_latency_goal`), and the goodput floor
    (shed+errors over routed under `slo_goodput_goal`). Burn rates
    surface in router_status (SloObjective blocks) and /metrics
    (`edl_router_slo_burn`); the autoscaler logs them read-only.
    metrics_port (None resolves from EDL_METRICS_PORT, unset = off)
    arms the /metrics exposition.

    Affinity knobs: with `affinity` on, requests whose prompt holds at
    least one full KV block are fingerprinted over their leading
    `affinity_block_tokens`-sized blocks (capped at
    `affinity_max_blocks` — system prompts dominate sharing) and
    routed to the replica that last served that chain, PROVIDED the
    learned entry is younger than `affinity_ttl_secs`, the target is
    still in rotation, still reports warm prefix capacity, and its
    load is within `affinity_load_margin` score points of the best
    candidate; any rung failing decays the request to pure
    least-loaded. cell_id/cells identify this process inside a
    multi-cell tier (serving/router_cell.py); the single-router
    defaults are cell_id=0, cells=1."""

    def __init__(self, poll_secs=0.5, poll_timeout_secs=2.0,
                 lease_secs=2.5, breaker_threshold=3,
                 breaker_cooldown_secs=2.0, hedge_delay_secs=0.0,
                 dispatch_timeout_secs=120.0,
                 redispatch_window_secs=30.0, base_delay_secs=0.05,
                 max_delay_secs=1.0, port=0, max_workers=64,
                 telemetry_dir="", telemetry_flush_every=20,
                 metrics_port=None, slo_ttft_p99_ms=30000.0,
                 slo_e2e_p99_ms=60000.0, slo_latency_goal=0.01,
                 slo_goodput_goal=0.02, slo_fast_window_secs=30.0,
                 slo_slow_window_secs=120.0, affinity=True,
                 affinity_block_tokens=16, affinity_max_blocks=4,
                 affinity_ttl_secs=60.0, affinity_load_margin=2.0,
                 affinity_capacity=4096, cell_id=0, cells=1,
                 disagg=True, disagg_timeout_secs=10.0):
        self.poll_secs = float(poll_secs)
        self.poll_timeout_secs = float(poll_timeout_secs)
        self.lease_secs = float(lease_secs)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_secs = float(breaker_cooldown_secs)
        self.hedge_delay_secs = float(hedge_delay_secs)
        self.dispatch_timeout_secs = float(dispatch_timeout_secs)
        self.redispatch_window_secs = float(redispatch_window_secs)
        self.base_delay_secs = float(base_delay_secs)
        self.max_delay_secs = float(max_delay_secs)
        self.port = int(port)
        self.max_workers = int(max_workers)
        self.telemetry_dir = telemetry_dir
        self.telemetry_flush_every = int(telemetry_flush_every)
        self.metrics_port = (
            metrics_port_default() if metrics_port is None
            else int(metrics_port)
        )
        self.slo_ttft_p99_ms = float(slo_ttft_p99_ms)
        self.slo_e2e_p99_ms = float(slo_e2e_p99_ms)
        self.slo_latency_goal = float(slo_latency_goal)
        self.slo_goodput_goal = float(slo_goodput_goal)
        self.slo_fast_window_secs = float(slo_fast_window_secs)
        self.slo_slow_window_secs = float(slo_slow_window_secs)
        self.affinity = bool(affinity)
        self.affinity_block_tokens = int(affinity_block_tokens)
        self.affinity_max_blocks = int(affinity_max_blocks)
        self.affinity_ttl_secs = float(affinity_ttl_secs)
        self.affinity_load_margin = float(affinity_load_margin)
        self.affinity_capacity = int(affinity_capacity)
        self.cell_id = int(cell_id)
        self.cells = int(cells)
        # disaggregated prefill/decode handoff (serving/disagg.py):
        # with `disagg` on and a replica advertising role=prefill in
        # rotation, a cold-prefix request is first warmed there and
        # its chain transferred to the least-loaded decode replica;
        # off, prefill replicas simply sit out of rotation
        self.disagg = bool(disagg)
        self.disagg_timeout_secs = float(disagg_timeout_secs)


class CircuitBreaker(object):
    """Per-replica breaker: CLOSED -> OPEN on `threshold` CONSECUTIVE
    transient failures; OPEN -> HALF_OPEN after `cooldown_secs`;
    HALF_OPEN admits ONE in-flight probe — success closes, transient
    failure re-opens and restarts the cooldown, and release_probe
    frees the slot for outcomes that judge neither way."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold=3, cooldown_secs=2.0):
        self.threshold = int(threshold)
        self.cooldown_secs = float(cooldown_secs)
        self.state = self.CLOSED
        self.failures = 0  # consecutive transient failures
        self._opened_at = None
        self._probe_inflight = False
        self._lock = threading.Lock()

    def eligible(self, now):
        """Whether a dispatch COULD go here now (non-mutating: safe to
        call while ranking candidates)."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                return (now - self._opened_at >= self.cooldown_secs
                        and not self._probe_inflight)
            return not self._probe_inflight  # HALF_OPEN

    def acquire(self, now):
        """Commit to dispatching here: transitions OPEN->HALF_OPEN when
        the cooldown has elapsed and claims the single probe slot.
        False if another thread raced the probe away."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if (self.state == self.OPEN
                    and now - self._opened_at >= self.cooldown_secs):
                self.state = self.HALF_OPEN
            if self.state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self):
        with self._lock:
            closed_now = self.state != self.CLOSED
            self.state = self.CLOSED
            self.failures = 0
            self._probe_inflight = False
            return closed_now

    def release_probe(self):
        """Release a held probe slot WITHOUT judging the replica. Every
        dispatch outcome must land in exactly one of record_success /
        record_failure / release_probe: a HALF_OPEN probe that fails
        for a reason that says nothing about transport health (e.g.
        INVALID_ARGUMENT) would otherwise pin _probe_inflight forever
        and evict the replica from rotation permanently."""
        with self._lock:
            self._probe_inflight = False

    def record_failure(self, now):
        """One transient dispatch failure; True when this TRIPS the
        breaker (closed/half-open -> open)."""
        with self._lock:
            self.failures += 1
            self._probe_inflight = False
            if (self.state == self.HALF_OPEN
                    or self.failures >= self.threshold):
                tripped = self.state != self.OPEN
                self.state = self.OPEN
                self._opened_at = now
                return tripped
            return False


class Replica(object):
    """Registry entry: address, stub, lease, breaker, load signals.

    The heartbeat signals live in DECLARED TABLES, not ad-hoc copies:
    `OBSERVED_SCALARS` (name -> reset default; the default's type is
    the coercion `observe` applies) and `OBSERVED_LISTS` name every
    field one ServerStatus heartbeat refreshes, and `STATUS_FORWARD`
    names every entry attribute `Router.status_response` forwards
    verbatim into `pb.ReplicaStatus` (`STATUS_COMPUTED` covers the
    router-derived rest). A field added to the heartbeat or to the
    proto therefore fails LOUDLY — the completeness pin test diffs
    these tables against both message descriptors — instead of being
    silently dropped between servicer and router_status, which is how
    `kv_host_blocks`/`prefix_hit_rate_window` nearly went dark."""

    #: every scalar one heartbeat refreshes, with its reset default.
    #: Notable members: kv_blocks_cached (refcount-0 blocks parked
    #: reclaimable by the prefix cache — evictable-on-demand headroom
    #: for the autoscaler's scale-down gate), kv_blocks_shared
    #: (blocks referenced by >1 sequence: live prefix dedup),
    #: kv_host_blocks/kv_host_bytes (tiered host spill: warm prefix
    #: capacity that survives device eviction), prefix_hit_rate_window
    #: (share of prompt tokens seated without prefill compute over the
    #: replica's trailing window) — together the warm-capacity ladder
    #: prefix-affinity routing ranks by; health_state ("" = the
    #: replica predates the health plane; "stalled" leaves rotation
    #: and arms the autoscaler's fast replacement path).
    OBSERVED_SCALARS = {
        "draining": False,
        "queue_depth": 0,
        "active_slots": 0,
        "kv_blocks_free": 0,
        "kv_blocks_cached": 0,
        "kv_blocks_shared": 0,
        "kv_cache_dtype": "",
        "kv_host_blocks": 0,
        "kv_host_bytes": 0,
        "revive_uploads": 0,
        "prefill_tokens_revived": 0,
        "host_drops": 0,
        "prefix_hit_rate_window": 0.0,
        "queue_wait_ms": 0.0,
        "health_state": "",
        "last_progress_age_ms": 0.0,
        # disaggregated serving phase ("" = predates roles, treated
        # as unified): "prefill" replicas leave normal rotation and
        # serve only cache-warming handoffs
        "role": "",
        # checkpoint identity: the version this replica is serving
        # plus the hot-reload failure latch — the rollout controller's
        # per-replica ground truth (a wave commits only when every
        # member advertises the target version)
        "model_version": 0,
        "reload_failed": False,
    }

    #: repeated heartbeat fields (histogram BUCKETS, mergeable by
    #: addition; slow_cause_counts = terminally-slow requests by
    #: dominant attributed cause, forensics taxonomy order)
    OBSERVED_LISTS = ("ttft_hist", "queue_wait_hist",
                      "slow_cause_counts")

    #: ReplicaStatus fields forwarded verbatim from the entry by
    #: status_response (attribute name == proto field name)
    STATUS_FORWARD = (
        "address", "draining", "queue_depth", "active_slots",
        "kv_blocks_free", "kv_blocks_cached", "kv_blocks_shared",
        "kv_cache_dtype", "kv_host_blocks", "kv_host_bytes",
        "revive_uploads", "prefill_tokens_revived", "host_drops",
        "prefix_hit_rate_window", "queue_wait_ms", "dispatched",
        "failures", "inflight", "slow_cause_counts", "health_state",
        "last_progress_age_ms", "role", "model_version",
        "reload_failed",
    )

    #: the router-derived remainder of pb.ReplicaStatus —
    #: STATUS_FORWARD + STATUS_COMPUTED must cover the message exactly
    STATUS_COMPUTED = ("healthy", "breaker", "lease_remaining_secs")

    def __init__(self, address, stub, breaker, lease_until):
        self.address = address
        self.stub = stub
        self.breaker = breaker
        # retire/close state: remove_replica marks the entry retired;
        # the channel closes once every in-flight poll AND dispatch
        # has settled (closing under a live call would turn a healthy
        # heartbeat into a transport error)
        self.retired = False
        self._closed = False
        # registration grants one lease period of grace so routing
        # works before the first poll lands; a dead replica burns the
        # grace on its breaker instead
        self.lease_expires_at = lease_until
        for name, default in self.OBSERVED_SCALARS.items():
            setattr(self, name, default)
        for name in self.OBSERVED_LISTS:
            setattr(self, name, [])
        self.dispatched = 0
        self.failures = 0
        self.poll_failures = 0
        # router-side in-flight dispatches: the polled signals freeze
        # between heartbeats, so without this every tie inside a poll
        # window breaks to the same replica and requests herd
        self.inflight = 0
        self._inflight_lock = threading.Lock()
        # one status poll in flight at a time: a wedged replica must
        # not accumulate a poll thread per sweep
        self._poll_inflight = False

    def begin_poll(self):
        with self._inflight_lock:
            if self._poll_inflight:
                return False
            self._poll_inflight = True
            return True

    def end_poll(self):
        with self._inflight_lock:
            self._poll_inflight = False
        self._maybe_close()

    def begin_dispatch(self):
        with self._inflight_lock:
            self.dispatched += 1
            self.inflight += 1

    def end_dispatch(self):
        with self._inflight_lock:
            self.inflight -= 1
        self._maybe_close()

    def retire(self):
        """Take this entry out of service for good: close the gRPC
        channel now if nothing is in flight, otherwise defer the close
        to the last in-flight poll/dispatch settling — safe against a
        concurrent heartbeat poll by construction. Idempotent."""
        with self._inflight_lock:
            self.retired = True
        return self._maybe_close()

    def _maybe_close(self):
        close_now = False
        with self._inflight_lock:
            if (self.retired and not self._closed
                    and not self._poll_inflight and not self.inflight):
                self._closed = True
                close_now = True
        if not close_now:
            return False
        # outside the lock: a real grpc channel close can block
        close = getattr(self.stub, "close", None)
        if callable(close):
            try:
                close()
            except Exception as e:  # noqa: BLE001 - best-effort close
                logger.debug("closing channel to %s failed: %r",
                             self.address, e)
        return True

    def lease_ok(self, now):
        return now < self.lease_expires_at

    def in_rotation(self, now):
        # a self-reported stalled replica serves nothing even though
        # its (gRPC-thread) lease renews fine — dispatching to it
        # only buys redispatch latency later
        return (self.lease_ok(now) and not self.draining
                and self.health_state != "stalled"
                and self.breaker.eligible(now))

    def load_score(self):
        """Lower = dispatch here. Queue wait (ms) is scaled so ~50 ms
        of measured waiting weighs like one queued request; inflight is
        the router's own live correction to the heartbeat-stale rest —
        and the one live-updated term, so it is read under its lock
        (edl-lint EDL002: dispatch threads bump it concurrently; the
        polled signals freeze between heartbeats and may be stale by
        design)."""
        with self._inflight_lock:
            inflight = self.inflight
        return (self.queue_depth + self.active_slots + inflight
                + self.queue_wait_ms / 50.0)

    def warm_capacity(self):
        """Whether this replica plausibly still HOLDS warm prefix
        state worth routing toward: shared or cached device blocks, a
        host tier with spilled chains, or a recent window of prompt
        tokens seated without prefill. All four zero means a prefix
        match here would prefill cold anyway — affinity decays to
        least-loaded rather than herding onto a cold target."""
        return (self.kv_blocks_shared > 0 or self.kv_blocks_cached > 0
                or self.kv_host_blocks > 0
                or self.prefix_hit_rate_window > 0.0)

    def observe(self, status, lease_until):
        """One heartbeat's signal copy, driven by the declared tables:
        every OBSERVED_SCALARS member is coerced through its default's
        type (bool for draining, str for kv_cache_dtype, ...), every
        OBSERVED_LISTS member is snapshotted as a plain list (raw
        histogram buckets merge by addition fleet-wide)."""
        self.lease_expires_at = lease_until
        for name, default in self.OBSERVED_SCALARS.items():
            setattr(self, name,
                    type(default)(getattr(status, name)))
        for name in self.OBSERVED_LISTS:
            setattr(self, name, list(getattr(status, name)))


def _default_stub_factory(address):
    from elasticdl_tpu.proto.service import ServingStub, build_channel

    channel = build_channel(address)
    stub = ServingStub(channel)
    # the retire path (Router.remove_replica) closes the channel
    # through this handle once in-flight polls/dispatches settle
    stub.close = channel.close
    return stub


def _code_name(exc, default="UNAVAILABLE"):
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            return code().name
        except Exception:
            return default
    return default


class Router(object):
    """The registry + heartbeat + dispatch engine. Transport-agnostic:
    `stub_factory(address)` must return an object with the ServingStub
    surface (generate / generate_stream / server_status, each taking
    `timeout=`) — real gRPC stubs in production, in-process fakes in
    the unit tests."""

    def __init__(self, replica_addrs, config=None, stub_factory=None,
                 clock=time.monotonic, sleep=time.sleep, telemetry=None):
        self.config = config or RouterConfig()
        self._stub_factory = stub_factory or _default_stub_factory
        self._clock = clock
        self._sleep = sleep
        self.telemetry = telemetry or RouterTelemetry(
            log_dir=self.config.telemetry_dir or None,
            flush_every=self.config.telemetry_flush_every,
        )
        self._policy = RetryPolicy(
            base_delay_secs=self.config.base_delay_secs,
            max_delay_secs=self.config.max_delay_secs,
            reconnect_window_secs=self.config.redispatch_window_secs,
        )
        # prefix-affinity memory: fingerprint -> last replica that
        # served it, learned on successful dispatch, TTL'd + LRU
        # bounded (stale affinity decays to least-loaded, it never
        # overrides rotation state)
        self._affinity = AffinityIndex(
            ttl_secs=self.config.affinity_ttl_secs,
            capacity=self.config.affinity_capacity,
        )
        # disaggregated handoff orchestration (serving/disagg.py);
        # the fault injector (start()) arms the disagg_handoff hook
        self._disagg = HandoffCoordinator(
            timeout_secs=self.config.disagg_timeout_secs
        )
        self._injector = None
        self._lock = threading.Lock()
        self._replicas = {}
        for addr in replica_addrs:
            self.add_replica(addr)
        self._stop = threading.Event()
        self._heartbeat = None
        self._server = None
        self.servicer = None
        self.port = None
        self.metrics = None  # MetricsServer when config.metrics_port
        # SLO burn-rate engine over the telemetry ring: last-seen
        # CUMULATIVE replica histogram buckets per address (an entry
        # outlives its replica, so a killed replica's history stays in
        # the fleet sum — the TtftWindows convention), bucket-added
        # into the ring each heartbeat
        self._fleet_hists = {}
        self._slo_engine = BurnRateEngine(
            default_router_slos(
                self.config.slo_ttft_p99_ms,
                self.config.slo_e2e_p99_ms,
                self.config.slo_goodput_goal,
                latency_goal=self.config.slo_latency_goal,
            ),
            fast_window_secs=self.config.slo_fast_window_secs,
            slow_window_secs=self.config.slo_slow_window_secs,
        )
        self._slo_lock = threading.Lock()
        self._slo_reports = []
        # optional replica supervisor (serving/autoscaler.py): owns
        # the fleet processes and contributes the router_status
        # autoscaler block; the router never calls INTO it while
        # holding _lock (lock order: supervisor -> router, one way)
        self.autoscaler = None
        # optional fleet rollout controller (serving/rollout.py):
        # contributes the router_status rollout block; same one-way
        # lock order as the autoscaler (controller -> router). The
        # hold set steers NEW dispatches away from a replica about to
        # swap checkpoints before its own draining advertisement lands
        self.rollout = None
        self._rollout_hold = set()
        # tail-based trace retention: the router's request roots are
        # classified against the SAME declared SLO thresholds the burn
        # engine evaluates — a breaching, shed, re-dispatched, hedged
        # or failed root's whole trace survives ring pressure that
        # evicts healthy siblings (observability/tracing.py)
        recorder().add_classifier(self._root_span_classifier)

    #: root-span events that mark a trace worth retaining even when
    #: the request eventually succeeded — the re-dispatch/hedge/shed
    #: machinery fired, which is exactly what an incident replay wants
    RETAIN_EVENTS = frozenset(
        ("redispatched", "hedged", "breaker_trip", "shed")
    )

    def _root_span_classifier(self, span):
        """Verdict hook for router_generate[_stream] roots: errors,
        shed, re-dispatched/hedged legs and e2e beyond the declared
        SLO threshold RETAIN the trace; clean fast roots sample."""
        if span.name not in ("router_generate",
                             "router_generate_stream"):
            return None
        if span.status != "ok":
            return True
        if any(name in self.RETAIN_EVENTS
               for _ts, name, _attrs in span.events):
            return True
        if span.end is not None:
            e2e_ms = (span.end - span.start) * 1000.0
            if e2e_ms > self.config.slo_e2e_p99_ms:
                return True
        return False

    def set_autoscaler(self, supervisor):
        """Attach the replica supervisor whose status_block() fills
        router_status.autoscaler. The supervisor's lifecycle is owned
        by the caller (router_main), not by Router.stop()."""
        self.autoscaler = supervisor

    def set_rollout(self, controller):
        """Attach the fleet rollout controller whose status_block()
        fills router_status.rollout. Same ownership contract as the
        autoscaler: lifecycle belongs to router_main, and the
        controller calls INTO the router (hold/release, slo_reports)
        — never the reverse while a router lock is held."""
        self.rollout = controller

    # ------------------------------------------------- rollout steering

    def hold_replica(self, address):
        """Steer NEW dispatches away from a replica about to swap
        checkpoints, ahead of its own `draining` advertisement landing
        on a heartbeat (the advertisement lags by up to poll_secs; the
        hold closes that window). In-flight work is untouched. The
        rollout controller pairs every hold with release_replica."""
        with self._lock:
            self._rollout_hold.add(address)

    def release_replica(self, address):
        with self._lock:
            self._rollout_hold.discard(address)

    def held_replicas(self):
        with self._lock:
            return set(self._rollout_hold)

    # ------------------------------------------------------- membership

    def add_replica(self, address):
        with self._lock:
            if address in self._replicas:
                return self._replicas[address]
            rep = Replica(
                address, self._stub_factory(address),
                CircuitBreaker(self.config.breaker_threshold,
                               self.config.breaker_cooldown_secs),
                lease_until=self._clock() + self.config.lease_secs,
            )
            self._replicas[address] = rep
            return rep

    def remove_replica(self, address):
        """Unregister AND retire: the entry leaves the registry (no
        new dispatch can pick it) and its gRPC channel closes once any
        concurrent heartbeat poll or in-flight dispatch settles — a
        removed replica must not leak a channel or leave begin_* /
        end_* counters unsettled. Returns the retired entry (None if
        the address was unknown)."""
        with self._lock:
            rep = self._replicas.pop(address, None)
        if rep is not None:
            rep.retire()
            # affinity must never resurrect a removed address: drop
            # every fingerprint that learned it
            self._affinity.forget_address(address)
        return rep

    def replicas(self):
        with self._lock:
            return list(self._replicas.values())

    # -------------------------------------------------------- heartbeat

    def _poll_replica(self, rep):
        try:
            status = rep.stub.server_status(
                pb.ServerStatusRequest(),
                timeout=self.config.poll_timeout_secs,
            )
            rep.observe(
                status, self._clock() + self.config.lease_secs
            )
        except Exception as e:  # noqa: BLE001 - silence = lease decay
            rep.poll_failures += 1
            logger.debug("router poll %s failed: %r", rep.address, e)
        finally:
            rep.end_poll()

    def poll_once(self):
        """One heartbeat sweep: renew leases + load signals from every
        replica that answers server_status; silence lets the lease
        decay. Replicas are polled CONCURRENTLY (one thread each) — a
        wedged replica must never stall the others' lease renewals;
        polled sequentially, the sweep period would grow with
        replica_count * poll_timeout and healthy replicas would be
        spuriously evicted whenever two or more replicas hung. The
        sweep itself waits at most min(poll_timeout, lease/2)
        regardless of replica count; a straggler's renewal still lands
        when its thread finally returns, and a replica whose previous
        poll is STILL in flight is skipped rather than re-polled.
        Returns the number of in-rotation replicas."""
        spawned = []
        for rep in self.replicas():
            if not rep.begin_poll():
                continue  # previous poll still stuck on this replica
            t = threading.Thread(
                target=self._poll_replica, args=(rep,), daemon=True,
                name="router-poll-%s" % rep.address,
            )
            t.start()
            spawned.append(t)
        deadline = time.monotonic() + min(
            self.config.poll_timeout_secs, self.config.lease_secs / 2.0
        )
        for t in spawned:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        now = self._clock()
        healthy = sum(1 for r in self.replicas() if r.in_rotation(now))
        # fleet-merged CUMULATIVE histogram buckets into the ring: the
        # last-seen counts per ADDRESS (never deleted — a killed
        # replica's history must stay in the sum or its window deltas
        # would go negative), bucket-added across the roster. The SLO
        # engine windows exactly this series.
        for rep in self.replicas():
            if rep.ttft_hist:
                self._fleet_hists[rep.address] = (
                    list(rep.ttft_hist), list(rep.queue_wait_hist)
                )
        ttft_cum, wait_cum = [], []
        for ttft, wait in self._fleet_hists.values():
            ttft_cum = add_counts(ttft_cum, ttft)
            wait_cum = add_counts(wait_cum, wait)
        self.telemetry.record_poll(
            healthy, len(self.replicas()),
            fleet_hists={"fleet_ttft_ms": ttft_cum,
                         "fleet_queue_wait_ms": wait_cum},
        )
        reports = self.telemetry.evaluate_slos(self._slo_engine)
        with self._slo_lock:
            self._slo_reports = reports
        return healthy

    def slo_reports(self):
        """The last heartbeat's burn-rate evaluations (plain dicts —
        the shape observability/slo.py documents). Read-only consumers:
        router_status, /metrics, the autoscaler's logged advisory."""
        with self._slo_lock:
            return list(self._slo_reports)

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.config.poll_secs)

    # -------------------------------------------------------- selection

    def _fingerprint(self, request):
        """The request's prefix fingerprint under the configured block
        geometry, or None when affinity is off or the prompt holds no
        complete block (nothing shareable -> pure least-loaded)."""
        if not self.config.affinity:
            return None
        return prefix_fingerprint(
            request.prompt,
            block_tokens=self.config.affinity_block_tokens,
            max_blocks=self.config.affinity_max_blocks,
        )

    def _acquire_replica(self, now, exclude=(), fingerprint=None):
        """Best in-rotation replica with its breaker probe slot
        acquired, as `(replica, affine)`; (None, False) = shed.

        With a fingerprint, the affinity decay ladder runs first:
        learned entry fresh -> target among the in-rotation candidates
        (so a draining/stalled replica or an open breaker is NEVER
        affine-dispatched, however perfect the prefix match — the
        candidate filter IS the guard) -> target still reports warm
        prefix capacity -> target's load within affinity_load_margin
        of the least-loaded candidate -> breaker slot acquired. Any
        rung failing falls through to the least-loaded order below."""
        with self._lock:
            candidates = [
                r for r in self._replicas.values()
                if r.address not in exclude and r.in_rotation(now)
                # dedicated prefill replicas serve cache-warming
                # handoffs only — never normal decode traffic
                and r.role != "prefill"
                # rollout steering: a replica held for a checkpoint
                # swap takes no new work
                and r.address not in self._rollout_hold
            ]
        candidates.sort(
            key=lambda r: (r.load_score(), -r.kv_blocks_free, r.address)
        )
        if fingerprint is not None and candidates:
            target = self._affinity.lookup(fingerprint, now)
            if target is not None:
                affine = next((r for r in candidates
                               if r.address == target), None)
                if (affine is not None
                        and affine.warm_capacity()
                        and affine.load_score()
                        <= (candidates[0].load_score()
                            + self.config.affinity_load_margin)):
                    if affine.breaker.acquire(now):
                        return affine, True
        for rep in candidates:
            if rep.breaker.acquire(now):
                return rep, False
        return None, False

    def _acquire_prefill(self, now):
        """Least-loaded in-rotation PREFILL replica with its breaker
        probe slot acquired; None = no dedicated prefill pool in
        rotation right now (the caller just dispatches cold)."""
        with self._lock:
            pool = [r for r in self._replicas.values()
                    if r.in_rotation(now) and r.role == "prefill"
                    and r.address not in self._rollout_hold]
        pool.sort(key=lambda r: (r.load_score(), r.address))
        for rep in pool:
            if rep.breaker.acquire(now):
                return rep
        return None

    def _decode_target(self, now):
        """Least-loaded in-rotation decode-capable replica — the same
        ordering _acquire_replica dispatches by, so the warmed chain
        lands where the follow-up dispatch will go. No breaker slot is
        held: a failed import falls back to a cold dispatch without
        judging the target's transport."""
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.in_rotation(now) and r.role != "prefill"
                          and r.address not in self._rollout_hold]
        if not candidates:
            return None
        candidates.sort(
            key=lambda r: (r.load_score(), -r.kv_blocks_free, r.address)
        )
        return candidates[0]

    def _maybe_handoff(self, request, fp, root):
        """Phase-split cache warming for a COLD prefix: run the prompt
        on a dedicated prefill replica, move the finished chain to the
        least-loaded decode replica (export -> transfer, a dense byte
        copy), and teach affinity so the dispatch that follows seats
        there by prefix hit. Every failure path falls back to a plain
        cold dispatch — a handoff can cost the warm-start, never the
        request. No-op without a fingerprint, with disagg off, with no
        prefill pool in rotation, or when affinity already knows a
        warm target."""
        if fp is None or not self.config.disagg:
            return
        now = self._clock()
        if self._affinity.lookup(fp, now) is not None:
            return
        prefill_rep = self._acquire_prefill(now)
        if prefill_rep is None:
            return
        decode_rep = self._decode_target(now)
        if decode_rep is None:
            prefill_rep.breaker.release_probe()
            return
        if self._injector is not None:
            # the disagg drill's injection point: a drop/error rule
            # here forces the fallback path with both replicas healthy
            try:
                self._injector.intercept("disagg_handoff")
            except InjectedRpcError as e:
                prefill_rep.breaker.release_probe()
                self.telemetry.count("disagg_fallbacks")
                root.event("disagg_fallback", error=str(e))
                return
        disagg = self._disagg
        tid = disagg.new_transfer_id()
        prefill_rep.begin_dispatch()
        decode_rep.begin_dispatch()
        try:
            payload = disagg.export_chain(prefill_rep, request, tid)
            disagg.import_chain(decode_rep, payload)
        except Exception as e:  # noqa: BLE001 - fallback is the policy
            # settle the export obligation (the failure's ledger
            # entry) and the probe slot; the request dispatches cold
            disagg.abort_transfer(prefill_rep, tid)
            prefill_rep.breaker.release_probe()
            self.telemetry.count("disagg_fallbacks")
            root.event("disagg_fallback",
                       prefill=prefill_rep.address,
                       decode=decode_rep.address,
                       error=_code_name(e))
            return
        finally:
            prefill_rep.end_dispatch()
            decode_rep.end_dispatch()
        self._on_success(prefill_rep)
        self.telemetry.count("disagg_handoffs")
        root.event("disagg_handoff", prefill=prefill_rep.address,
                   decode=decode_rep.address, transfer_id=tid)
        self._affinity.learn(fp, decode_rep.address, self._clock())

    # --------------------------------------------------------- dispatch

    def _sub_request(self, request, remaining_ms, trace_id="",
                     parent_span_id=""):
        return pb.GenerateRequest(
            prompt=list(request.prompt),
            max_new_tokens=request.max_new_tokens,
            temperature=request.temperature,
            seed=request.seed,
            deadline_ms=remaining_ms,
            # context propagation: the replica parents its serve span
            # under THIS dispatch leg's span, so hedge legs and
            # re-dispatches land as siblings in one request tree
            trace_id=trace_id,
            parent_span_id=parent_span_id,
        )

    def _root_span(self, name, request):
        """The request's root span on the router: adopts the client's
        trace when the inbound RPC carried one, mints otherwise (the
        router IS admission for routed requests)."""
        return recorder().start_span(
            name,
            trace_id=getattr(request, "trace_id", "") or None,
            parent_span_id=getattr(request, "parent_span_id", ""),
            prompt_len=len(request.prompt),
            max_new_tokens=request.max_new_tokens,
        )

    def _budget(self, request, t0):
        """(remaining_ms, call_timeout) for a dispatch starting now.
        remaining_ms is the client's unspent deadline budget (0 = no
        deadline); raises when the budget is already gone — the ONE
        DEADLINE_EXCEEDED the router never retries, because it is the
        client's own clock that ran out."""
        timeout = self.config.dispatch_timeout_secs
        if request.deadline_ms <= 0:
            return 0, timeout
        remaining = (
            request.deadline_ms / 1000.0 - (self._clock() - t0)
        )
        if remaining <= 0:
            raise RouterError(
                "DEADLINE_EXCEEDED",
                "deadline spent after %.0f ms of routing"
                % (request.deadline_ms,),
            )
        return int(remaining * 1000.0), min(timeout, remaining)

    def _on_success(self, rep):
        rep.breaker.record_success()

    def _on_failure(self, rep, exc, span=None):
        """Breaker accounting for one failed dispatch. Every outcome
        must settle the breaker — in particular a HALF_OPEN probe slot
        is released on EVERY path, or the replica is silently evicted
        from rotation forever."""
        rep.failures += 1
        now = self._clock()
        if is_transient_rpc_error(exc):
            if rep.breaker.record_failure(now):
                self.telemetry.count("breaker_trips")
                if span is not None:
                    span.event("breaker_trip", replica=rep.address)
                logger.warning(
                    "router breaker OPEN for %s after %d consecutive "
                    "transient failures (%r)",
                    rep.address, rep.breaker.failures, exc,
                )
        elif is_backpressure_rpc_error(exc):
            # backpressure: the replica answered — it is alive and
            # explicitly shedding. A live answer is success as far as
            # the TRANSPORT breaker is concerned: it closes a half-open
            # probe and breaks the consecutive-transient streak; the
            # dispatch loop re-routes toward capacity elsewhere.
            rep.breaker.record_success()
        else:
            # non-transient application error (INVALID_ARGUMENT, a
            # spent client deadline): says nothing about transport
            # health, so leave the breaker state alone — but release a
            # held probe slot so HALF_OPEN can probe again
            rep.breaker.release_probe()

    def _call_unary(self, rep, request, remaining_ms, timeout, root,
                    hedge=False, attempt=0):
        """One dispatch leg, traced: its own `dispatch` span under the
        request's root — a hedge or a re-dispatch adds a SIBLING span,
        which is exactly the causal shape the trace must keep."""
        span = recorder().start_span(
            "dispatch", trace_id=root.trace_id,
            parent_span_id=root.span_id, replica=rep.address,
            hedge=hedge, attempt=attempt,
        )
        sub = self._sub_request(request, remaining_ms,
                                trace_id=root.trace_id,
                                parent_span_id=span.span_id)
        rep.begin_dispatch()
        try:
            resp = rep.stub.generate(sub, timeout=timeout)
        except Exception as e:
            self._on_failure(rep, e, span=span)
            span.set(error=_code_name(e))
            span.finish("error")
            raise
        finally:
            rep.end_dispatch()
        self._on_success(rep)
        span.finish("ok")
        return resp

    def _raise_terminal(self, exc, root=None):
        self.telemetry.count("errors")
        if isinstance(exc, RouterError):
            if root is not None:
                root.finish(exc.code)
            raise exc  # already carries its status name
        if root is not None:
            root.finish(_code_name(exc))
        raise RouterError(_code_name(exc), str(exc))

    def _finish_e2e(self, root, t0, status="ok"):
        # the trace_id rides into the e2e histogram as a bucket
        # exemplar: a scraped p99 bucket names this very request
        self.telemetry.record_e2e((self._clock() - t0) * 1000.0,
                                  trace_id=root.trace_id)
        root.finish(status)

    def dispatch_generate(self, request):
        """Unary generate with re-dispatch + optional hedging. The
        response is atomic (nothing reaches the client until a replica
        finishes), so re-dispatch is safe at ANY point of a failed
        attempt — token parity guarantees replica-independence."""
        self.telemetry.count("routed")
        root = self._root_span("router_generate", request)
        fp = self._fingerprint(request)
        t0 = self._clock()
        self._maybe_handoff(request, fp, root)
        window_ends = t0 + self.config.redispatch_window_secs
        attempt = 0
        failed = set()  # addresses that failed THIS request
        while True:
            try:
                remaining_ms, timeout = self._budget(request, t0)
            except RouterError as e:
                self._raise_terminal(e, root=root)
            now = self._clock()
            rep, affine = self._acquire_replica(
                now, exclude=failed, fingerprint=fp
            )
            if rep is None and failed:
                # every live replica failed this request once already;
                # forgive and re-pick — the breaker/lease state decides
                failed = set()
                rep, affine = self._acquire_replica(
                    now, fingerprint=fp
                )
            if rep is None:
                self.telemetry.count("shed")
                root.event("shed")
                root.finish("RESOURCE_EXHAUSTED")
                raise RouterError(
                    "RESOURCE_EXHAUSTED",
                    "no healthy replicas in rotation (shed)",
                )
            if fp is not None and attempt == 0:
                # the ladder's verdict, counted once per request (the
                # first pick; re-dispatches would double-count)
                self.telemetry.count(
                    "affinity_hits" if affine else "affinity_misses"
                )
                if affine:
                    root.event("affinity", replica=rep.address)
            try:
                resp = self._dispatch_maybe_hedged(
                    rep, request, remaining_ms, timeout, failed,
                    root, attempt,
                )
                self.telemetry.count("completed")
                # a success TEACHES affinity: the chain's blocks are
                # resident on this replica now
                if fp is not None:
                    self._affinity.learn(fp, rep.address,
                                         self._clock())
                self._finish_e2e(root, t0)
                return resp
            except Exception as e:  # noqa: BLE001 - classified below
                failed.add(rep.address)
                retryable = (is_transient_rpc_error(e)
                             or is_backpressure_rpc_error(e))
                spent_deadline = (
                    request.deadline_ms > 0
                    and _code_name(e, "") == "DEADLINE_EXCEEDED"
                )
                if not retryable or spent_deadline:
                    self._raise_terminal(e, root=root)
                if self._clock() >= window_ends:
                    logger.error(
                        "router giving up on request after %d "
                        "re-dispatches over %.0fs window",
                        attempt, self.config.redispatch_window_secs,
                    )
                    self._raise_terminal(e, root=root)
                self.telemetry.count("redispatched")
                root.event("redispatched", attempt=attempt,
                           failed_replica=rep.address,
                           error=_code_name(e))
                delay = min(self._policy.backoff(attempt),
                            max(0.0, window_ends - self._clock()))
                self._sleep(delay)
                attempt += 1

    def _dispatch_maybe_hedged(self, primary, request, remaining_ms,
                               timeout, failed, root, attempt):
        """One attempt. With hedging enabled and a second replica in
        rotation, a primary that hasn't answered inside hedge_delay is
        duplicated; first success wins (duplicates are harmless — both
        would return the same tokens). Raises the primary's error when
        every leg failed. Each leg runs as its own `dispatch` span
        under `root` — hedge legs are SIBLINGS, distinguishable by the
        `hedge` attr."""
        if self.config.hedge_delay_secs <= 0:
            return self._call_unary(primary, request, remaining_ms,
                                    timeout, root, attempt=attempt)
        results = _queue.Queue()

        def leg(rep, hedge):
            try:
                results.put(("ok", rep, self._call_unary(
                    rep, request, remaining_ms, timeout, root,
                    hedge=hedge, attempt=attempt,
                )))
            except Exception as e:  # noqa: BLE001 - the datum
                results.put(("err", rep, e))

        threading.Thread(
            target=leg, args=(primary, False), daemon=True
        ).start()
        outstanding, hedged = 1, False
        primary_err = None
        while outstanding:
            try:
                wait = (self.config.hedge_delay_secs if not hedged
                        else timeout + 5.0)
                kind, rep, payload = results.get(timeout=wait)
            except _queue.Empty:
                if hedged:
                    raise RouterError(
                        "DEADLINE_EXCEEDED",
                        "hedged dispatch timed out on every leg",
                    )
                hedged = True
                # no fingerprint: the hedge exists to land SOMEWHERE
                # ELSE than the (possibly affine) slow primary
                hedge_rep, _ = self._acquire_replica(
                    self._clock(),
                    exclude=set(failed) | {primary.address},
                )
                if hedge_rep is not None:
                    self.telemetry.count("hedges")
                    root.event("hedged", replica=hedge_rep.address)
                    threading.Thread(
                        target=leg, args=(hedge_rep, True), daemon=True
                    ).start()
                    outstanding += 1
                continue
            outstanding -= 1
            if kind == "ok":
                if rep is not primary:
                    self.telemetry.count("hedge_wins")
                    root.event("hedge_win", replica=rep.address)
                return payload
            # either leg failing marks its replica failed for THIS
            # request, so a later re-dispatch skips a hedge replica
            # already known bad instead of burning an attempt on it
            failed.add(rep.address)
            if rep is primary:
                primary_err = payload
        raise primary_err if primary_err is not None else payload

    def dispatch_stream(self, request):
        """Streaming generate. Re-dispatch is allowed only BEFORE the
        first chunk reaches the client: after that, a replay would
        duplicate delivered tokens, so a mid-stream replica loss fails
        the stream EXPLICITLY (UNAVAILABLE + token count) instead —
        never silently truncated, never hung."""
        self.telemetry.count("routed")
        root = self._root_span("router_generate_stream", request)
        fp = self._fingerprint(request)
        t0 = self._clock()
        self._maybe_handoff(request, fp, root)
        window_ends = t0 + self.config.redispatch_window_secs
        attempt = 0
        failed = set()

        def gen():
            nonlocal attempt, failed
            delivered = 0
            while True:
                try:
                    remaining_ms, timeout = self._budget(request, t0)
                except RouterError as e:
                    self._raise_terminal(e, root=root)
                now = self._clock()
                rep, affine = self._acquire_replica(
                    now, exclude=failed, fingerprint=fp
                )
                if rep is None and failed:
                    failed = set()
                    rep, affine = self._acquire_replica(
                        now, fingerprint=fp
                    )
                if rep is None:
                    self.telemetry.count("shed")
                    root.event("shed")
                    root.finish("RESOURCE_EXHAUSTED")
                    raise RouterError(
                        "RESOURCE_EXHAUSTED",
                        "no healthy replicas in rotation (shed)",
                    )
                if fp is not None and attempt == 0:
                    self.telemetry.count(
                        "affinity_hits" if affine
                        else "affinity_misses"
                    )
                    if affine:
                        root.event("affinity", replica=rep.address)
                span = recorder().start_span(
                    "dispatch", trace_id=root.trace_id,
                    parent_span_id=root.span_id, replica=rep.address,
                    attempt=attempt, stream=True,
                )
                rep.begin_dispatch()
                try:
                    stream = rep.stub.generate_stream(
                        self._sub_request(
                            request, remaining_ms,
                            trace_id=root.trace_id,
                            parent_span_id=span.span_id,
                        ),
                        timeout=timeout,
                    )
                    for chunk in stream:
                        delivered += len(chunk.tokens)
                        yield chunk
                    self._on_success(rep)
                    span.finish("ok")
                    self.telemetry.count("completed")
                    if fp is not None:
                        self._affinity.learn(fp, rep.address,
                                             self._clock())
                    self._finish_e2e(root, t0)
                    return
                except Exception as e:  # noqa: BLE001 - classified
                    self._on_failure(rep, e, span=span)
                    span.set(error=_code_name(e),
                             delivered=delivered)
                    span.finish("error")
                    failed.add(rep.address)
                    if delivered:
                        self.telemetry.count("errors")
                        root.finish("UNAVAILABLE")
                        raise RouterError(
                            "UNAVAILABLE",
                            "replica %s lost mid-stream after %d "
                            "delivered tokens (%s)"
                            % (rep.address, delivered, _code_name(e)),
                        )
                    retryable = (is_transient_rpc_error(e)
                                 or is_backpressure_rpc_error(e))
                    spent_deadline = (
                        request.deadline_ms > 0
                        and _code_name(e, "") == "DEADLINE_EXCEEDED"
                    )
                    if not retryable or spent_deadline:
                        self._raise_terminal(e, root=root)
                    if self._clock() >= window_ends:
                        self._raise_terminal(e, root=root)
                    self.telemetry.count("redispatched")
                    root.event("redispatched", attempt=attempt,
                               failed_replica=rep.address,
                               error=_code_name(e))
                    delay = min(self._policy.backoff(attempt),
                                max(0.0, window_ends - self._clock()))
                    self._sleep(delay)
                    attempt += 1
                finally:
                    # also covers a client abandoning the generator
                    # (GeneratorExit is not an Exception)
                    rep.end_dispatch()

        return gen()

    # ----------------------------------------------------------- status

    def status_response(self):
        now = self._clock()
        snap = self.telemetry.snapshot()
        # fleet-wide latency: the replicas' histogram BUCKETS merge by
        # addition (percentiles of the merged counts — never averages
        # of per-replica percentiles, which would be meaningless)
        fleet_ttft = LogLinearHistogram()
        fleet_wait = LogLinearHistogram()
        for rep in self.replicas():
            if rep.ttft_hist:
                fleet_ttft.merge(
                    LogLinearHistogram.from_counts(rep.ttft_hist)
                )
            if rep.queue_wait_hist:
                fleet_wait.merge(
                    LogLinearHistogram.from_counts(rep.queue_wait_hist)
                )
        reps = []
        for rep in sorted(self.replicas(), key=lambda r: r.address):
            # table-driven: STATUS_FORWARD attrs pass through verbatim
            # (attribute name == proto field name by declaration), the
            # STATUS_COMPUTED remainder is derived here — the pin test
            # holds the union congruent with the message descriptor
            kwargs = {name: getattr(rep, name)
                      for name in Replica.STATUS_FORWARD}
            kwargs.update(
                healthy=rep.in_rotation(now),
                breaker=rep.breaker.state,
                lease_remaining_secs=max(
                    0.0, rep.lease_expires_at - now
                ),
            )
            reps.append(pb.ReplicaStatus(**kwargs))
        autoscaler = None
        if self.autoscaler is not None:
            autoscaler = self.autoscaler.status_block()
        rollout = None
        if self.rollout is not None:
            rollout = self.rollout.status_block()
        # fleet-wide host-tier view: occupancy gauges and the monotone
        # revival economy sum across replicas (counters are monotone
        # per replica, so the fleet sums are monotone too while the
        # roster is stable; a replaced replica resets its share — the
        # same contract every other fleet counter here has)
        fleet_host_blocks = sum(r.kv_host_blocks
                                for r in self.replicas())
        fleet_host_bytes = sum(r.kv_host_bytes
                               for r in self.replicas())
        fleet_revive_uploads = sum(r.revive_uploads
                                   for r in self.replicas())
        fleet_revived_tokens = sum(r.prefill_tokens_revived
                                   for r in self.replicas())
        fleet_host_drops = sum(r.host_drops for r in self.replicas())
        slo_blocks = [
            pb.SloObjective(
                name=r["name"],
                kind=r["kind"],
                threshold_ms=r["threshold_ms"],
                goal=r["goal"],
                fast_burn=r["fast_burn"],
                slow_burn=r["slow_burn"],
                fast_window_secs=r["fast_window_secs"],
                slow_window_secs=r["slow_window_secs"],
                fast_samples=r["fast_samples"],
                slow_samples=r["slow_samples"],
                alerting=r["alerting"],
            )
            for r in self.slo_reports()
        ]
        return pb.RouterStatusResponse(
            autoscaler=autoscaler,
            rollout=rollout,
            slo=slo_blocks,
            replicas=len(reps),
            healthy=sum(1 for r in reps if r.healthy),
            kv_host_blocks=fleet_host_blocks,
            kv_host_bytes=fleet_host_bytes,
            revive_uploads=fleet_revive_uploads,
            prefill_tokens_revived=fleet_revived_tokens,
            host_drops=fleet_host_drops,
            replica=reps,
            routed=snap["routed"],
            completed=snap["completed"],
            redispatched=snap["redispatched"],
            hedges=snap["hedges"],
            hedge_wins=snap["hedge_wins"],
            shed=snap["shed"],
            breaker_trips=snap["breaker_trips"],
            affinity_hits=snap["affinity_hits"],
            affinity_misses=snap["affinity_misses"],
            disagg_handoffs=snap["disagg_handoffs"],
            disagg_fallbacks=snap["disagg_fallbacks"],
            cell_id=self.config.cell_id,
            cells=self.config.cells,
            uptime_secs=snap["uptime_secs"],
            e2e_p50_ms=snap["e2e_p50_ms"],
            e2e_p90_ms=snap["e2e_p90_ms"],
            e2e_p99_ms=snap["e2e_p99_ms"],
            ttft_p50_ms=fleet_ttft.percentile(50),
            ttft_p90_ms=fleet_ttft.percentile(90),
            ttft_p99_ms=fleet_ttft.percentile(99),
            queue_wait_p50_ms=fleet_wait.percentile(50),
            queue_wait_p90_ms=fleet_wait.percentile(90),
            queue_wait_p99_ms=fleet_wait.percentile(99),
        )

    # ----------------------------------------------------- /metrics

    def _metrics_families(self):
        """One router scrape: the closed telemetry sets + the
        fleet-merged histograms (RouterTelemetry.prometheus), the SLO
        burn-rate gauges, and — when a supervisor is attached — the
        autoscaler roster/decision series. Runs on the exposition
        HTTP thread; every collector locks itself."""
        fams = self.telemetry.prometheus()
        burn, alerting = [], []
        for r in self.slo_reports():
            burn.append(({"slo": r["name"], "window": "fast"},
                         r["fast_burn"]))
            burn.append(({"slo": r["name"], "window": "slow"},
                         r["slow_burn"]))
            alerting.append(({"slo": r["name"]},
                             1.0 if r["alerting"] else 0.0))
        fams.append(gauge_family(
            "edl_router_slo_burn",
            "SLO error-budget burn rate per objective and window "
            "(1.0 = spending the budget exactly on schedule)",
            burn,
        ))
        fams.append(gauge_family(
            "edl_router_slo_alerting",
            "1 when BOTH burn windows exceed 1.0 (multi-window rule)",
            alerting,
        ))
        sup = self.autoscaler
        if sup is not None:
            block = sup.status_block()
            for name in ("target", "live", "starting", "draining"):
                fams.append(gauge_family(
                    "edl_autoscaler_%s" % name,
                    "autoscaler roster gauge %s" % name,
                    [({}, getattr(block, name))],
                ))
            for name in ("scale_ups", "scale_downs", "replacements",
                         "spawn_failures"):
                fams.append(counter_family(
                    "edl_autoscaler_%s_total" % name,
                    "autoscaler decision counter %s" % name,
                    getattr(block, name),
                ))
            fams.append(gauge_family(
                "edl_autoscaler_circuit_open",
                "1 when the restart circuit is open",
                [({}, 1.0 if block.circuit_open else 0.0)],
            ))
        ctl = self.rollout
        if ctl is not None:
            block = ctl.status_block()
            for name in ("target_version", "old_version", "wave",
                         "waves_total", "swapped", "fleet"):
                fams.append(gauge_family(
                    "edl_rollout_%s" % name,
                    "rollout controller gauge %s" % name,
                    [({}, getattr(block, name))],
                ))
            fams.append(gauge_family(
                "edl_rollout_active",
                "1 while a rollout is in flight (any non-terminal "
                "phase)",
                [({"phase": block.phase},
                  0.0 if block.phase in ("idle", "committed",
                                         "rolled_back", "aborted")
                  else 1.0)],
            ))
            fams.append(counter_family(
                "edl_rollout_rollbacks_total",
                "replica checkpoint swaps reversed by judgment or "
                "burn (rollback swap count)",
                block.rollbacks,
            ))
        return fams

    # -------------------------------------------------------- lifecycle

    def start(self, grpc_server=True, injector=None):
        # identify this process inside the (possibly multi-cell) tier:
        # a per-cell scrape disambiguates which cell's counters these
        # are without parsing ports out of labels
        self.telemetry.gauge("cell_id", self.config.cell_id)
        self.telemetry.gauge("cells", self.config.cells)
        self._heartbeat = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="router-heartbeat",
        )
        self._heartbeat.start()
        servicer = RouterServicer(self)
        # the handoff path consults the injector directly (the
        # disagg_handoff hook) — a transfer is router-initiated, so
        # there is no inbound RPC for the wrapper to intercept. Same
        # EDL_FAULT_SPEC env fallback as the servicer wrapper below.
        injector = injector or FaultInjector.from_env()
        self._injector = injector
        # EDL_FAULT_SPEC arms drop/error/delay/kill at the router
        # boundary under the router_* RPC names; replica-name rules
        # never fire here (and vice versa)
        self.servicer = maybe_wrap_servicer(
            servicer, injector, rpcs=SERVING_RPCS
        )
        if self.config.metrics_port is not None:
            self.metrics = MetricsServer(
                self._metrics_families, port=self.config.metrics_port
            )
            logger.info(
                "Router /metrics exposition on port %d",
                self.metrics.port,
            )
        if grpc_server:
            from elasticdl_tpu.proto.service import (
                add_router_servicer_to_server,
                build_server,
            )

            server = build_server(
                futures.ThreadPoolExecutor(
                    max_workers=self.config.max_workers
                )
            )
            add_router_servicer_to_server(self.servicer, server)
            self.port = server.add_insecure_port(
                "[::]:%d" % self.config.port
            )
            server.start()
            self._server = server
            logger.info(
                "Serving router started on port %d (%d replicas, "
                "poll=%.2fs lease=%.2fs)", self.port,
                len(self.replicas()), self.config.poll_secs,
                self.config.lease_secs,
            )
        return self

    def stop(self, grace=5.0):
        self._stop.set()
        recorder().remove_classifier(self._root_span_classifier)
        if self._heartbeat is not None:
            self._heartbeat.join(timeout=10.0)
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        if self.metrics is not None:
            self.metrics.close()
            self.metrics = None
        self.telemetry.close()
        # export the span ring when EDL_TRACE_DIR is set (no-op
        # otherwise); the dump tool merges per-process files
        recorder().flush()


class RouterServicer(object):
    """gRPC handlers for the Router service (proto/service.py Router
    table). Same in-process/real-transport duality as the replica
    servicer: context=None raises RouterError to the caller, a real
    context gets an abort with the mapped status code."""

    def __init__(self, router):
        self._router = router

    def router_generate(self, request, context=None):
        try:
            return self._router.dispatch_generate(request)
        except RouterError as e:
            self._fail(context, e.code, str(e))

    def router_generate_stream(self, request, context=None):
        inner = self._router.dispatch_stream(request)

        def stream():
            try:
                for chunk in inner:
                    yield chunk
            except RouterError as e:
                self._fail(context, e.code, str(e))

        return stream()

    def router_status(self, request, context=None):
        return self._router.status_response()

    def _fail(self, context, code_name, message):
        if context is not None:
            import grpc

            context.abort(
                getattr(grpc.StatusCode, code_name,
                        grpc.StatusCode.UNKNOWN),
                message,
            )
        raise RouterError(code_name, message)
