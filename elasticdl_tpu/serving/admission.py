"""Request admission: the bounded queue in front of the decode pool.

The serving twin of the master's task queue, with the elastic-training
DNA inverted: training workers PULL tasks and the queue is unbounded
(the job is finite); serving requests are PUSHED by clients and the
queue must be bounded, because decode capacity is fixed (the slot pool)
and an unbounded queue converts overload into unbounded latency for
everyone. Admission policy:

* full queue        -> reject NOW with RESOURCE_EXHAUSTED (backpressure:
                       the client retries against another replica; the
                       retry semantics mirror common/retry.py — the
                       rejection is transient and retryable)
* invalid request   -> INVALID_ARGUMENT (prompt/output budget cannot fit
                       the model's cache; never enters the queue)
* expired deadline  -> DEADLINE_EXCEEDED, whether it expires while
                       queued or while decoding (the scheduler evicts
                       mid-flight expirations between steps)

Thread-safe: gRPC handler threads submit; the single scheduler thread
pops. Completion plumbing rides on each request's event queue so a
handler can stream tokens as the scheduler produces them.
"""

import collections
import threading
import time


class AdmissionError(Exception):
    """Rejected at (or after) admission. `code` is the gRPC status name
    the servicer maps to: RESOURCE_EXHAUSTED (queue full / shutdown),
    INVALID_ARGUMENT (malformed), DEADLINE_EXCEEDED (expired)."""

    def __init__(self, code, message):
        super().__init__(message)
        self.code = code


class ServingRequest(object):
    """One in-flight generation request.

    Client-facing fields mirror proto GenerateRequest; the rest is
    scheduler state. Events flow through `events` as tuples:
        ("tokens", [ids], model_version)  new tokens (first event also
                                          marks TTFT)
        ("done", model_version)           completed; all tokens emitted
        ("error", code, message)          terminal failure

    `span` (observability/tracing.py) is the request's serve span: the
    servicer opens it at admission (parenting under the router's
    dispatch span when the RPC carried trace context) and the
    scheduler/engine annotate the lifecycle through `trace_event` —
    both guard on span being None so direct/off-path construction
    (tests, benches) costs nothing."""

    _ids = iter(range(1, 2 ** 62))
    _ids_lock = threading.Lock()

    def __init__(self, prompt, max_new_tokens, temperature=0.0, seed=0,
                 deadline_ms=0, clock=time.monotonic, trace_id="",
                 parent_span_id="", prefill_only=False):
        with ServingRequest._ids_lock:
            self.request_id = next(ServingRequest._ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        # disaggregated cache warming (serving/disagg.py): seat, run
        # the prompt's prefill, register the chain, release — the
        # blocks park refcount-0 cached, matchable and exportable
        self.prefill_only = bool(prefill_only)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.submitted_at = clock()
        self.deadline = (
            self.submitted_at + deadline_ms / 1000.0
            if deadline_ms and deadline_ms > 0 else None
        )
        self.events = collections.deque()
        self._event_cv = threading.Condition()
        # tracing context (empty = untraced caller; the servicer mints)
        self.trace_id = trace_id or ""
        self.parent_span_id = parent_span_id or ""
        self.span = None
        # scheduler-side state
        self.generated = []
        self.first_token_at = None
        self.seated_at = None  # set when the scheduler seats a slot
        self.model_version = -1

    # ---- tracing (no-ops until the servicer attaches a span)

    def trace_event(self, name, **attrs):
        if self.span is not None:
            self.span.event(name, **attrs)

    def finish_span(self, status="ok"):
        if self.span is not None:
            self.span.finish(status)

    def expired(self, now):
        return self.deadline is not None and now > self.deadline

    def queue_wait_secs(self, now=None):
        """Time spent queued before seating (None until seated). The
        router folds this — via the telemetry EWMA and the
        ServerStatus queue_wait_ms field — into its load signal: two
        replicas with equal queue DEPTH can hide very different queue
        TIME when their requests differ in length."""
        if self.seated_at is None:
            return None
        return self.seated_at - self.submitted_at

    # ---- event plumbing (scheduler -> handler thread)

    def push(self, event):
        with self._event_cv:
            self.events.append(event)
            self._event_cv.notify_all()

    def next_event(self, timeout=None):
        """Block for the next event; None on timeout (the caller re-checks
        its own deadline and keeps waiting — used as a liveness bound so
        a lost scheduler can never hang a handler forever)."""
        with self._event_cv:
            if not self.events:
                self._event_cv.wait(timeout)
            if not self.events:
                return None
            return self.events.popleft()


class RequestQueue(object):
    """Bounded FIFO with deadline-aware pop; the admission controller.

    `capacity` bounds only the QUEUED backlog — requests move out of the
    queue when the scheduler seats them in a slot. total_budget(seq_len)
    validation happens at submit so a request that can never fit fails
    fast instead of poisoning a slot.

    `max_cached_tokens` is the paged pool's never-fits bound (engine.
    max_cached_tokens()): a request whose prompt + decode cache rows
    exceed the WHOLE block budget is invalid at submit — it could queue
    forever. Requests that fit the pool but not the blocks free right
    now are a different thing entirely: they stay queued and seat when
    completions release blocks (the `fit` predicate on pop_ready).
    """

    def __init__(self, capacity, seq_len, clock=time.monotonic,
                 max_cached_tokens=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1, got %d" % capacity)
        self.capacity = int(capacity)
        self.seq_len = int(seq_len)
        self.max_cached_tokens = (
            int(max_cached_tokens) if max_cached_tokens else None
        )
        self._clock = clock
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self):
        with self._cv:
            return len(self._q)

    def submit(self, request):
        """Admit or raise AdmissionError. Never blocks: backpressure is
        an immediate REJECT, not a wait (a waiting client holds a gRPC
        thread; a rejected one retries with backoff against capacity
        that may have moved elsewhere)."""
        self.validate(request)
        with self._cv:
            if self._closed:
                raise AdmissionError(
                    "RESOURCE_EXHAUSTED", "server is shutting down"
                )
            if len(self._q) >= self.capacity:
                raise AdmissionError(
                    "RESOURCE_EXHAUSTED",
                    "request queue full (%d queued)" % len(self._q),
                )
            self._q.append(request)
            self._cv.notify_all()

    def validate(self, request):
        p = len(request.prompt)
        if p < 1:
            raise AdmissionError("INVALID_ARGUMENT", "empty prompt")
        if request.max_new_tokens < 1:
            raise AdmissionError(
                "INVALID_ARGUMENT",
                "max_new_tokens must be >= 1, got %d"
                % request.max_new_tokens,
            )
        if p + request.max_new_tokens > self.seq_len:
            raise AdmissionError(
                "INVALID_ARGUMENT",
                "prompt %d + max_new_tokens %d exceeds the model's "
                "seq_len %d" % (p, request.max_new_tokens, self.seq_len),
            )
        cached = p + request.max_new_tokens - 1
        caches = (request.max_new_tokens > 1
                  or getattr(request, "prefill_only", False))
        if (self.max_cached_tokens is not None
                and caches
                and cached > self.max_cached_tokens):
            raise AdmissionError(
                "INVALID_ARGUMENT",
                "request needs %d KV rows > the pool's total budget of "
                "%d tokens" % (cached, self.max_cached_tokens),
            )
        if request.expired(self._clock()):
            raise AdmissionError(
                "DEADLINE_EXCEEDED", "deadline expired before admission"
            )

    def pop_ready(self, fit=None):
        """Next admissible request, expiring stale ones on the way out.
        Returns (request, expired_list); request is None when empty.

        `fit` (optional predicate): the engine's can_seat — when the
        head-of-line request cannot seat RIGHT NOW (paged pool out of
        blocks), it STAYS at the head and pop returns None. FIFO order
        is preserved deliberately: skipping ahead to smaller requests
        would starve long ones under sustained short-request load."""
        expired = []
        now = self._clock()
        with self._cv:
            while self._q:
                req = self._q[0]
                if req.expired(now):
                    self._q.popleft()
                    expired.append(req)
                    continue
                if fit is not None and not fit(req):
                    return None, expired
                self._q.popleft()
                return req, expired
        return None, expired

    def wait_for_work(self, timeout):
        """Scheduler idle wait: returns once a request is queued or the
        timeout lapses (the scheduler then runs its periodic duties —
        hot-reload poll, telemetry flush)."""
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            return bool(self._q)

    def wake(self):
        """Wake any wait_for_work sleeper (shutdown path)."""
        with self._cv:
            self._cv.notify_all()

    def close(self):
        """Stop admitting; drain-and-reject the backlog. Returns the
        requests that were still queued so the caller can fail them
        cleanly (RESOURCE_EXHAUSTED, never a hang)."""
        with self._cv:
            self._closed = True
            backlog = list(self._q)
            self._q.clear()
            self._cv.notify_all()
        return backlog
