"""Block-paged KV storage for the serving engine.

The dense decode pool (engine.py) gives every slot a contiguous
`seq_len` stripe of cache per layer, so decode HBM scales as
`num_slots x seq_len` even though most requests finish far short of
`seq_len` — the padding is resident, bandwidth-neutral, and
unsellable. This module converts that padding into admissible work:

* KV rows live in per-layer block ARENAS shaped
  `[num_blocks, block_size, kv_heads, head_dim]`, shared by every
  sequence on the server;
* a sequence's logical cache is its BLOCK TABLE — the ordered block
  ids covering positions `[j*block_size, (j+1)*block_size)`;
* `BlockAllocator` is the host-side accounting: alloc/extend/free are
  O(1) per block, and a RESERVATION ledger guarantees that a seated
  request can always extend to its full token budget — out-of-blocks
  is an admission-time condition (backpressure), never a mid-decode
  crash;
* `PagedKVPool` owns the device arenas and the write paths: the
  block-granular prompt insertion (one `dynamic_update_slice` per
  block, never a whole-slot copy) and the per-step decode-row scatter
  (`.at[bids, offs].set`, free lanes dropped via an out-of-bounds
  sentinel).

PREFIX SHARING (share_prefix=True): blocks are REFCOUNTED and full
prompt blocks are indexed in a content-addressed prefix trie keyed
`(parent block id, block token tuple)` — collision-free by
construction. A request whose prompt prefix matches a resident chain
seats by INCREMENTING refcounts instead of allocating + re-prefilling;
the engine then prefills only the unshared suffix. Invariants:

* only FULL blocks enter the index — every row of an indexed block is
  real prompt content, and its owner never writes it again (decode
  writes land at positions >= the prompt length, i.e. in later
  blocks);
* a block is freed (returned to the free list) only at refcount 0.
  Refcount-0 blocks that are still indexed become RECLAIMABLE: they
  sit in an LRU cache, revivable by a future prefix match at zero
  cost, and are evicted (leaf-first — a live block's ancestors are
  always live, so every reclaimable subtree has reclaimable leaves)
  when the free list runs dry. `available()` therefore counts
  free + reclaimable - reserved;
* COPY-ON-WRITE: a slot's write into a block with refcount > 1 first
  copies the block into a fresh one and repoints the slot's table
  (`cow`). The only planned CoW is the full-prompt-match seat (the
  last token must re-run for logits, re-writing its row into the
  shared tail block), and `alloc` RESERVES one block of CoW credit
  for it up front — the CoW fault draws from the slot's existing
  reservation, never from thin air, keeping out-of-blocks an
  admission-time condition.

INT8 ARENAS (model kv_cache_dtype="int8"): per-row scales are KV row
leaves too — the batch-1 cache template then carries
`[1, hkv, cache_len, 1]` f32 scale buffers beside the int8 rows, so
`build_pools` maps them to `[num_blocks, block_size, hkv, 1]` scale
arenas through the SAME `kv_row_leaf` convention, and every write path
here (block-granular prompt insertion, decode-row scatter, CoW block
copy) is tree-generic and carries scale leaves with no special case.
The quantize-at-insertion invariant: rows are quantized exactly where
they are produced (the model's prefill cache write / decode-tile sow)
and the arenas only ever RECEIVE quantized data; every read defers the
dequantize into the paged attention scan (k-scales fold into score
tiles, v-scales into weights — ops.attention.paged_decode_attention),
so no float copy of cached rows exists anywhere. The prefix trie is
keyed on TOKEN IDS, not bytes, so sharing/CoW/reclaim are dtype-blind.

Block ids enter the compiled decode step as DEVICE arrays (the tables),
so slot churn and sequence growth never recompile anything — the same
zero-recompile contract the dense pool holds, at block granularity.
The device table upload is CACHED and refreshed only when some table
actually changed (one device put per mutating step, not per slot —
mid-decode steps where no block boundary is crossed reuse the resident
array). The attention that consumes this layout is
`ops.attention.paged_decode_attention`.
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.api.generation import kv_row_leaf


class OutOfBlocks(Exception):
    """The pool cannot cover a request's block budget right now. The
    scheduler treats this as backpressure: the request stays queued
    until completions free blocks (admission rejects outright only
    requests that could NEVER fit)."""


def blocks_for(tokens, block_size):
    """Blocks covering `tokens` cache rows (0 tokens -> 0 blocks)."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator(object):
    """Host-side block accounting: free list, refcounts, per-slot block
    tables, the reservation ledger, and (share_prefix=True) the
    content-addressed prefix index with its reclaimable LRU.

    `alloc(slot, tokens, commit_tokens)` materializes the blocks for
    `tokens` rows and RESERVES (without materializing) enough blocks
    for `commit_tokens` total; `extend` then draws the growth blocks
    from that reservation, so a request admitted under its full budget
    can never strand mid-decode waiting for a block another request
    holds. With `prompt=` token ids, the prompt's full blocks are first
    matched against the prefix index and seated by incref — only the
    unmatched remainder draws fresh blocks. `available()` is what
    admission may promise to NEW work. Every operation is O(blocks
    touched); steady-state slot churn is O(1) per block."""

    def __init__(self, num_blocks, block_size, share_prefix=False):
        if num_blocks < 1:
            raise ValueError(
                "num_blocks must be >= 1, got %d" % num_blocks)
        if block_size < 1:
            raise ValueError(
                "block_size must be >= 1, got %d" % block_size)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.share_prefix = bool(share_prefix)
        # LIFO: the most recently freed block is reused first (warm
        # reuse; also what the reuse-order tests lock)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}     # slot -> [block ids]
        self._committed = {}  # slot -> total blocks promised
        self._cow_credit = {}  # slot -> reserved CoW copies (0 or 1)
        self._reserved = 0    # promised-but-unmaterialized, all slots
        self._refcount = {}   # bid -> live references (allocated only)
        # prefix index: (parent bid, block token tuple) -> bid; -1 is
        # the root parent. Collision-free: the key IS the content path.
        self._index = {}
        self._index_key = {}  # bid -> its index key (reverse map)
        self._children = {}   # bid -> set of indexed child bids
        # refcount-0 blocks still indexed, oldest-first (LRU eviction)
        self._cached = collections.OrderedDict()
        self.cow_copies = 0        # monotone: CoW faults served
        self.prefix_hits = 0       # monotone: seats that matched
        self.prefix_hit_tokens = 0  # monotone: tokens seated by incref

    # ------------------------------------------------------------ queries

    def num_free(self):
        return len(self._free)

    def num_cached(self):
        """Reclaimable blocks: refcount 0 but still in the prefix
        index — revivable by a match, evictable under pressure."""
        return len(self._cached)

    def blocks_in_use(self):
        """Blocks pinned by LIVE references (refcount > 0)."""
        return self.num_blocks - len(self._free) - len(self._cached)

    def shared_blocks(self):
        """Blocks currently referenced by more than one table."""
        return sum(1 for c in self._refcount.values() if c > 1)

    def available(self):
        """Blocks admission may promise to NEW work: free plus
        reclaimable, minus the reservations already promised to
        seated slots."""
        return len(self._free) + len(self._cached) - self._reserved

    def can_fit(self, tokens):
        return blocks_for(tokens, self.block_size) <= self.available()

    def table(self, slot):
        return list(self._tables.get(slot, ()))

    # ----------------------------------------------------- prefix index

    def _full_block_tuples(self, prompt):
        bs = self.block_size
        n = len(prompt) // bs
        return [tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
                for j in range(n)]

    def match_prefix(self, prompt):
        """Longest resident chain of full blocks covering a prefix of
        `prompt`: the block ids, root-first. Read-only (no refcount
        change) — `alloc(prompt=...)` seats on the result."""
        if not self.share_prefix:
            return []
        chain = []
        parent = -1
        for toks in self._full_block_tuples(prompt):
            bid = self._index.get((parent, toks))
            if bid is None:
                break
            chain.append(bid)
            parent = bid
        return chain

    def plan(self, prompt, tokens, commit_tokens=None):
        """(chain, needed) for seating `prompt` with `tokens` rows now
        and `commit_tokens` promised: the matched shared chain and how
        many blocks the seat would draw from `available()` (fresh
        blocks, the CoW credit for a full-prompt match, and the
        RECLAIMABLE chain blocks the seat would revive — reviving pops
        a block out of the cache `available()` counts, so it costs
        capacity exactly like a fresh draw). The admission-time answer
        `can_seat` and the seat itself (`alloc`) both run through
        this, so they cannot disagree."""
        chain, needed, _cow = self._plan(prompt, tokens, commit_tokens)
        return chain, needed

    def _plan(self, prompt, tokens, commit_tokens=None):
        now = blocks_for(tokens, self.block_size)
        commit = max(
            now, blocks_for(commit_tokens or tokens, self.block_size)
        )
        chain = self.match_prefix(prompt) if prompt is not None else []
        chain = chain[:now]
        # full-prompt match: the engine must re-run the last prompt
        # token for its logits, which re-writes that token's row into
        # the shared tail block -> one planned CoW copy, reserved here.
        # EXCEPT when the tail is reclaimable (refcount 0): the seat
        # revives it as sole owner and the re-write lands in place, so
        # no copy can fault — its cost is the revival charge below,
        # and charging both would refuse a full-budget reseat forever
        # on an idle pool
        cow = 1 if (chain and len(chain) * self.block_size
                    >= int(tokens)
                    and chain[-1] not in self._cached) else 0
        # chain blocks at refcount 0 are counted by available(); the
        # seat revives them (incref pops the cache), so they must be
        # charged or reservations can exceed free + reclaimable and
        # a reservation-backed extend could strand mid-decode
        revived = sum(1 for b in chain if b in self._cached)
        return chain, commit - len(chain) + cow + revived, cow

    def can_seat(self, prompt, tokens, commit_tokens=None):
        _chain, needed = self.plan(prompt, tokens, commit_tokens)
        return needed <= self.available()

    def register_prefix(self, slot, prompt):
        """Index `slot`'s FULL prompt blocks so later prompts can seat
        on them. Walks the index: levels already present (this seat's
        own shared chain, or a concurrent duplicate) keep the existing
        block — chains may interleave blocks owned by different slots,
        which is sound because the key path pins the exact content."""
        if not self.share_prefix:
            return
        table = self._tables.get(slot)
        if table is None:
            return
        parent = -1
        for j, toks in enumerate(self._full_block_tuples(prompt)):
            if j >= len(table):
                break
            key = (parent, toks)
            bid = self._index.get(key)
            if bid is None:
                bid = table[j]
                if bid in self._index_key:
                    # already indexed under another path (shouldn't
                    # happen for fresh private blocks) — don't re-key
                    break
                self._index[key] = bid
                self._index_key[bid] = key
                self._children.setdefault(parent, set()).add(bid)
            parent = bid

    def flush_index(self):
        """Drop the whole prefix index (hot reload: cached rows were
        computed under superseded params — new requests must never
        seat on them). Reclaimable blocks return to the free list;
        live blocks just lose their index entry and free normally at
        refcount 0."""
        for bid in list(self._cached):
            self._free.append(bid)
            self._refcount.pop(bid, None)
        self._cached.clear()
        self._index.clear()
        self._index_key.clear()
        self._children.clear()

    # -------------------------------------------------------- refcounts

    def incref(self, bid):
        """Add a live reference to `bid`, reviving it from the
        reclaimable cache when its refcount was 0. Every incref must
        be settled by a decref/free (edl-lint EDL501 tracks the
        pair)."""
        self._refcount[bid] = self._refcount.get(bid, 0) + 1
        self._cached.pop(bid, None)

    def decref(self, bid):
        """Drop a live reference; at refcount 0 the block becomes
        reclaimable (still indexed) or free (not indexed). A block is
        never on the free list while any table references it."""
        rc = self._refcount.get(bid, 0) - 1
        if rc > 0:
            self._refcount[bid] = rc
            return
        self._refcount.pop(bid, None)
        if bid in self._index_key:
            self._cached[bid] = None  # newest at the LRU tail
        else:
            self._free.append(bid)

    def _evict_cached(self):
        """Reclaim the oldest LEAF in the reclaimable LRU (a live
        block's ancestors are live, so every reclaimable subtree has a
        reclaimable leaf — progress is guaranteed)."""
        for bid in self._cached:
            if not self._children.get(bid):
                key = self._index_key.pop(bid)
                del self._index[key]
                kids = self._children.get(key[0])
                if kids is not None:
                    kids.discard(bid)
                    if not kids:
                        del self._children[key[0]]
                self._children.pop(bid, None)
                del self._cached[bid]
                return bid
        raise OutOfBlocks(
            "no evictable cached block (allocator invariant broken)"
        )

    def _pop_block(self):
        if self._free:
            return self._free.pop()
        return self._evict_cached()

    # ------------------------------------------------------------- churn

    def alloc(self, slot, tokens, commit_tokens=None, prompt=None):
        """Materialize blocks for `tokens` rows under `slot` and
        reserve up to `commit_tokens` total; raises OutOfBlocks when
        the full commitment is not coverable (nothing is taken then).
        With `prompt` (token ids) and share_prefix, the prompt's full
        blocks seat on the prefix index by incref where resident.
        Returns the number of SHARED tokens (0 without a match)."""
        if slot in self._tables:
            raise ValueError("slot %r already holds blocks" % (slot,))
        now = blocks_for(tokens, self.block_size)
        commit = max(
            now, blocks_for(commit_tokens or tokens, self.block_size)
        )
        chain, needed, cow = self._plan(prompt, tokens, commit_tokens)
        if needed > self.available():
            raise OutOfBlocks(
                "need %d new blocks (%d now, %d shared), %d available"
                % (needed, now, len(chain), self.available())
            )
        for bid in chain:
            self.incref(bid)
        fresh = []
        for _ in range(now - len(chain)):
            bid = self._pop_block()
            self.incref(bid)
            fresh.append(bid)
        self._tables[slot] = list(chain) + fresh
        self._committed[slot] = commit
        self._cow_credit[slot] = cow
        self._reserved += (commit - now) + cow
        if chain:
            self.prefix_hits += 1
            self.prefix_hit_tokens += len(chain) * self.block_size
        return len(chain) * self.block_size

    def extend(self, slot, total_tokens):
        """Grow `slot`'s table to cover `total_tokens` rows; growth
        inside the slot's commitment draws reserved blocks (never
        fails), growth beyond it competes with admission and can raise
        OutOfBlocks. Returns the appended block ids."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError("slot %r holds no blocks" % (slot,))
        need = blocks_for(total_tokens, self.block_size) - len(table)
        added = []
        for _ in range(max(0, need)):
            if len(table) < self._committed[slot]:
                self._reserved -= 1  # drawing our own reservation
            elif self.available() < 1:
                raise OutOfBlocks(
                    "slot %r grew past its commitment and no block is "
                    "available" % (slot,)
                )
            else:
                self._committed[slot] += 1
            bid = self._pop_block()
            self.incref(bid)
            table.append(bid)
            added.append(bid)
        return added

    def cow(self, slot, block_index):
        """Copy-on-write fault: `slot` is about to write into its
        table[block_index]. If that block is shared (refcount > 1), a
        fresh block replaces it in the table — drawing the slot's CoW
        credit reserved at seat time (falling back to free capacity
        for an UNPLANNED divergence) — and the shared original is
        decref'd, never freed out from under its other owners.
        Returns (old bid, new bid) when a copy happened, None when the
        block was private (write is safe in place)."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError("slot %r holds no blocks" % (slot,))
        old = table[block_index]
        if self._refcount.get(old, 0) <= 1:
            return None
        if self._cow_credit.get(slot, 0) > 0:
            self._cow_credit[slot] -= 1
            self._reserved -= 1  # the credit was reserved at seat
        elif self.available() < 1:
            raise OutOfBlocks(
                "CoW fault on slot %r with no block available (no "
                "credit reserved and the pool is dry)" % (slot,)
            )
        new = self._pop_block()
        self.incref(new)
        table[block_index] = new
        self.decref(old)
        self.cow_copies += 1
        return old, new

    def free(self, slot):
        """Release `slot`'s references and its remaining reservation;
        returns how many table entries were dropped. Shared blocks
        survive (decref only) — a block returns to the free list or
        the reclaimable cache strictly at refcount 0. Safe to call for
        a slot that holds nothing (0)."""
        table = self._tables.pop(slot, None)
        if table is None:
            return 0
        self._reserved -= (
            self._committed.pop(slot) - len(table)
            + self._cow_credit.pop(slot, 0)
        )
        # decref'd in table order so a fully-private table lands on the
        # free list with the block allocated LAST on top of the stack
        # (LIFO through the whole alloc -> free -> alloc cycle)
        for bid in table:
            self.decref(bid)
        return len(table)


def build_pools(kv_shapes, cache_len, num_blocks, block_size):
    """Device arenas from the model's batch-1 decode-cache template
    (api/generation._kv_shapes_for): every KV row leaf
    `[1, hkv, cache_len, d]` becomes `[num_blocks, block_size, hkv, d]`
    zeros; non-row leaves (the position counter) stay as zero-d
    placeholders so the pool tree keeps the cache tree's structure —
    the model slices its own layer's arenas out of it by name."""
    def arena(leaf):
        if kv_row_leaf(leaf, cache_len):
            _, hkv, _, d = leaf.shape
            return jnp.zeros((num_blocks, block_size, hkv, d),
                             leaf.dtype)
        return jnp.zeros(leaf.shape, leaf.dtype)

    return jax.tree.map(arena, kv_shapes)


def write_prompt_block(pools, kv, j, bid, block_size):
    """Insert block `j` of a freshly prefilled batch-1 cache tree into
    the arenas at block id `bid` — ONE `dynamic_update_slice` per row
    leaf at a TRACED (j, bid), so one compiled write serves every
    (prompt bucket, block, slot) combination. Rows past the true
    prompt length inside the last block are prefill junk; the paged
    attention masks `k_pos < length` so they are never read before the
    decode scatter overwrites them."""
    def upd(pool, leaf):
        if leaf.ndim != 4:  # the position counter placeholder
            return pool
        rows = jax.lax.dynamic_slice_in_dim(
            leaf[0], j * block_size, block_size, axis=1
        )  # [hkv, block_size, d]
        rows = rows.transpose(1, 0, 2)  # [block_size, hkv, d]
        return jax.lax.dynamic_update_slice(
            pool, rows[None], (bid, 0, 0, 0)
        )

    return jax.tree.map(upd, pools, kv)


def copy_block(pools, src, dst):
    """Device-side CoW: duplicate arena block `src` into `dst` on
    every row leaf (one gather + dynamic_update_slice per leaf, traced
    indices — one compiled copy serves every fault)."""
    def upd(pool):
        if pool.ndim != 4:
            return pool
        return jax.lax.dynamic_update_slice(
            pool,
            jax.lax.dynamic_slice_in_dim(pool, src, 1, axis=0),
            (dst, 0, 0, 0),
        )

    return jax.tree.map(upd, pools)


def scatter_rows(pools, rows, bids, offs):
    """Write decode rows into the arenas: `rows` is a tree whose
    structure is a SUBSET of `pools` (the model's "kv_out" sown
    collection) with leaves `[..., hkv, d]` — one row per leading
    index; `bids`/`offs` carry matching leading shape (`[S]` for the
    per-slot step, `[S, t]` for the speculative verify tile, `[t]` for
    a suffix prefill). Rows to drop (free lanes, rolled-back draft
    rows, pad rows) carry an out-of-bounds bid and are discarded by the
    scatter — they never touch a block a live sequence owns. Distinct
    live rows target distinct (block, offset) pairs, so the scatter
    indices never collide."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(pools)
    rmap = {
        jax.tree_util.keystr(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(rows)[0]
    }
    out = []
    for path, pool in flat:
        row = rmap.get(jax.tree_util.keystr(path))
        if row is None:
            out.append(pool)
        else:
            out.append(pool.at[bids, offs].set(row, mode="drop"))
    return jax.tree_util.tree_unflatten(treedef, out)


class PagedKVPool(object):
    """The device arenas + host tables for one serving engine.

    Owns the BlockAllocator, the `[num_slots, seq_len/block_size]`
    int32 table mirror the compiled step consumes (-1 = unallocated),
    and the jitted block write/copy. `cache_len % block_size == 0` is
    required so prompt blocks slice cleanly out of the prefill cache.

    The device copy of the table mirror is cached: `tables_device()`
    re-uploads only after a mutation (alloc/extend/CoW/release), so a
    decode step that crosses no block boundary costs zero host->device
    table traffic — the per-step assembly is one cached handle, not
    per-slot work."""

    def __init__(self, kv_shapes, cache_len, num_slots, num_blocks,
                 block_size, share_prefix=False):
        cache_len = int(cache_len)
        block_size = int(block_size)
        if cache_len % block_size:
            raise ValueError(
                "seq_len %d must be a multiple of kv_block_size %d"
                % (cache_len, block_size)
            )
        self.cache_len = cache_len
        self.block_size = block_size
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_slot = cache_len // block_size
        self.allocator = BlockAllocator(num_blocks, block_size,
                                        share_prefix=share_prefix)
        self.pools = build_pools(kv_shapes, cache_len, num_blocks,
                                 block_size)
        self.tables = np.full(
            (int(num_slots), self.max_blocks_per_slot), -1, np.int32
        )
        self._tables_dev = None  # cached device upload of `tables`
        # TRUE arena bytes: summed per leaf at its OWN dtype, so int8
        # arenas count their int8 rows AND f32 scale leaves exactly —
        # never a homogeneous row-dtype assumption. This is what
        # kv_bytes_in_use / bytes-per-generated-token report.
        row_leaves = [
            leaf for leaf in jax.tree.leaves(self.pools)
            if leaf.ndim == 4
        ]
        self.bytes_total = int(sum(leaf.nbytes for leaf in row_leaves))
        self.block_bytes = self.bytes_total // max(1, self.num_blocks)
        # the arenas' storage format, advertised on stats/ServerStatus:
        # any int8 row leaf means the quantized format (its f32 scale
        # leaves ride along)
        self.kv_cache_dtype = (
            "int8" if any(leaf.dtype == jnp.int8 for leaf in row_leaves)
            else ""
        )
        self._write_fn = None
        self._copy_fn = None

    # ----------------------------------------------------------- lifecycle

    def can_seat(self, prompt, prompt_tokens, commit_tokens):
        return self.allocator.can_seat(prompt, prompt_tokens,
                                       commit_tokens)

    def seat(self, slot, prompt, commit_tokens):
        """Reserve the request's full block budget and materialize the
        prompt's blocks — shared-prefix blocks by incref, the rest
        fresh; raises OutOfBlocks with nothing taken. Returns the
        shared token count (0 without a match)."""
        shared = self.allocator.alloc(
            slot, len(prompt), commit_tokens=commit_tokens,
            prompt=prompt,
        )
        self._sync_row(slot)
        return shared

    def register_prefix(self, slot, prompt):
        """Index the slot's full prompt blocks for future sharing
        (call after their rows are actually resident)."""
        self.allocator.register_prefix(slot, prompt)

    def write_prompt(self, kv, slot, prompt_tokens, start_block=0):
        """Scatter the prefilled cache's blocks [start_block, ...)
        into the slot's allocated blocks — block-granular, no
        whole-slot copy (shared blocks below start_block are already
        resident and must not be re-written)."""
        if self._write_fn is None:
            self._write_fn = jax.jit(
                write_prompt_block, static_argnames=("block_size",)
            )
        table = self.allocator.table(slot)
        for j in range(start_block,
                       blocks_for(prompt_tokens, self.block_size)):
            self.pools = self._write_fn(
                self.pools, kv, jnp.asarray(j, jnp.int32),
                jnp.asarray(table[j], jnp.int32),
                block_size=self.block_size,
            )

    def ensure_blocks(self, slot, pos):
        """Make sure the block covering cache position `pos` exists
        (the decode step writes up to there this iteration); draws the
        slot's reservation, so it cannot fail for a seated request."""
        if self.allocator.extend(slot, pos + 1):
            self._sync_row(slot)

    # back-compat spelling (single position)
    ensure_block = ensure_blocks

    def cow_for_write(self, slot, pos):
        """Copy-on-write guard before `slot` writes cache position
        `pos`: if the covering block is shared, copy it (device) and
        repoint the table. Returns the (old, new) ids or None."""
        moved = self.allocator.cow(slot, pos // self.block_size)
        if moved is None:
            return None
        old, new = moved
        if self._copy_fn is None:
            self._copy_fn = jax.jit(copy_block)
        self.pools = self._copy_fn(
            self.pools, jnp.asarray(old, jnp.int32),
            jnp.asarray(new, jnp.int32),
        )
        self._sync_row(slot)
        return moved

    def release(self, slot):
        """Reclaim a finished/evicted slot's references (O(1) per
        block); private rows are dead the moment the table forgets
        them, shared rows live on under their other owners."""
        freed = self.allocator.free(slot)
        if freed:
            self.tables[slot, :] = -1
            self._tables_dev = None
        return freed

    def flush_prefix_cache(self):
        """Hot reload hook: stale-params rows must never seat a new
        request (see BlockAllocator.flush_index)."""
        self.allocator.flush_index()

    def _sync_row(self, slot):
        table = self.allocator.table(slot)
        row = np.full(self.max_blocks_per_slot, -1, np.int32)
        row[: len(table)] = table
        self.tables[slot] = row
        self._tables_dev = None  # mutation: next step re-uploads once

    def tables_device(self):
        """The block tables as ONE cached device array — re-uploaded
        only after a mutation, so steady-state decode steps pay no
        host->device table transfer."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self.tables)
        return self._tables_dev

    # ------------------------------------------------------------- stats

    def bytes_in_use(self):
        return self.allocator.blocks_in_use() * self.block_bytes

    def stats(self):
        return {
            "kv_paged": True,
            "kv_shared": self.allocator.share_prefix,
            "kv_cache_dtype": self.kv_cache_dtype,
            "kv_block_size": self.block_size,
            "kv_blocks_total": self.num_blocks,
            # capacity available to new work: free + reclaimable —
            # cached prefixes are not "in use", they are a warm cache
            "kv_blocks_free": (self.allocator.num_free()
                               + self.allocator.num_cached()),
            "kv_blocks_cached": self.allocator.num_cached(),
            "kv_blocks_shared": self.allocator.shared_blocks(),
            "kv_bytes_total": self.bytes_total,
            "kv_bytes_in_use": self.bytes_in_use(),
            "prefix_hit_tokens": self.allocator.prefix_hit_tokens,
            "cow_copies": self.allocator.cow_copies,
        }
