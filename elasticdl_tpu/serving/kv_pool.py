"""Block-paged KV storage for the serving engine.

The dense decode pool (engine.py) gives every slot a contiguous
`seq_len` stripe of cache per layer, so decode HBM scales as
`num_slots x seq_len` even though most requests finish far short of
`seq_len` — the padding is resident, bandwidth-neutral, and
unsellable. This module converts that padding into admissible work:

* KV rows live in per-layer block ARENAS shaped
  `[num_blocks, block_size, kv_heads, head_dim]`, shared by every
  sequence on the server;
* a sequence's logical cache is its BLOCK TABLE — the ordered block
  ids covering positions `[j*block_size, (j+1)*block_size)`;
* `BlockAllocator` is the host-side accounting: alloc/extend/free are
  O(1) per block, and a RESERVATION ledger guarantees that a seated
  request can always extend to its full token budget — out-of-blocks
  is an admission-time condition (backpressure), never a mid-decode
  crash;
* `PagedKVPool` owns the device arenas and the write paths: the
  block-granular prompt insertion (one `dynamic_update_slice` per
  block, never a whole-slot copy) and the per-step decode-row scatter
  (`.at[bids, offs].set`, free lanes dropped via an out-of-bounds
  sentinel).

PREFIX SHARING (share_prefix=True): blocks are REFCOUNTED and full
prompt blocks are indexed in a content-addressed prefix trie keyed
`(parent block id, block token tuple)` — collision-free by
construction. A request whose prompt prefix matches a resident chain
seats by INCREMENTING refcounts instead of allocating + re-prefilling;
the engine then prefills only the unshared suffix. Invariants:

* only FULL blocks enter the index — every row of an indexed block is
  real prompt content, and its owner never writes it again (decode
  writes land at positions >= the prompt length, i.e. in later
  blocks);
* a block is freed (returned to the free list) only at refcount 0.
  Refcount-0 blocks that are still indexed become RECLAIMABLE: they
  sit in an LRU cache, revivable by a future prefix match at zero
  cost, and are evicted (leaf-first — a live block's ancestors are
  always live, so every reclaimable subtree has reclaimable leaves)
  when the free list runs dry. `available()` therefore counts
  free + reclaimable - reserved;
* COPY-ON-WRITE: a slot's write into a block with refcount > 1 first
  copies the block into a fresh one and repoints the slot's table
  (`cow`). The only planned CoW is the full-prompt-match seat (the
  last token must re-run for logits, re-writing its row into the
  shared tail block), and `alloc` RESERVES one block of CoW credit
  for it up front — the CoW fault draws from the slot's existing
  reservation, never from thin air, keeping out-of-blocks an
  admission-time condition.

INT8 ARENAS (model kv_cache_dtype="int8"): per-row scales are KV row
leaves too — the batch-1 cache template then carries
`[1, hkv, cache_len, 1]` f32 scale buffers beside the int8 rows, so
`build_pools` maps them to `[num_blocks, block_size, hkv, 1]` scale
arenas through the SAME `kv_row_leaf` convention, and every write path
here (block-granular prompt insertion, decode-row scatter, CoW block
copy) is tree-generic and carries scale leaves with no special case.
The quantize-at-insertion invariant: rows are quantized exactly where
they are produced (the model's prefill cache write / decode-tile sow)
and the arenas only ever RECEIVE quantized data; every read defers the
dequantize into the paged attention scan (k-scales fold into score
tiles, v-scales into weights — ops.attention.paged_decode_attention),
so no float copy of cached rows exists anywhere. The prefix trie is
keyed on TOKEN IDS, not bytes, so sharing/CoW/reclaim are dtype-blind.

TIERED HOST SPILL (host_bytes > 0): eviction no longer forgets a
chain — it DEMOTES it. When the reclaimable LRU must give up a
refcount-0 block, the block's rows (int8 rows AND f32 scale leaves,
through the same tree-generic `kv_row_leaf` paths that carry them
everywhere else) are copied into host numpy buffers and the trie entry
is re-keyed onto a stable negative VIRTUAL id, so the prefix index
keeps resolving chains that are no longer device-resident — the same
host⇄device split `embedding/host_spill.py` plays for embedding rows.
A later prompt that matches a spilled chain revives it by DEVICE
UPLOAD (a batched `dynamic_update_slice` scatter into freshly
allocated blocks, one executable per size bucket) instead of
re-running prefill; `plan`/`can_seat` charge each spilled chain block
exactly like a fresh draw, so admission and allocation cannot
disagree, and the admission cost of a warm prefix becomes upload
latency rather than prefill compute. Invariants:

* eviction is leaf-first in BOTH tiers: a block spills only when its
  indexed children are all spilled, and a spilled entry drops only
  when it has no indexed children at all — so every surviving trie
  path is complete (resident prefix, spilled suffix, never a hole);
* the host tier is BOUNDED (`host_bytes`, LRU drop of the oldest
  childless spilled entry) and never exceeds its budget;
* `flush_index` (hot reload) flushes BOTH tiers — stale-params rows
  must never seat a new request from either side of the PCIe bus;
* virtual ids are never reused, so a recycled device block id can
  never collide with a spilled entry's key.

Block ids enter the compiled decode step as DEVICE arrays (the tables),
so slot churn and sequence growth never recompile anything — the same
zero-recompile contract the dense pool holds, at block granularity.
The device table upload is CACHED and refreshed only when some table
actually changed (one device put per mutating step, not per slot —
mid-decode steps where no block boundary is crossed reuse the resident
array). The attention that consumes this layout is
`ops.attention.paged_decode_attention`.
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.api.generation import kv_row_leaf


class OutOfBlocks(Exception):
    """The pool cannot cover a request's block budget right now. The
    scheduler treats this as backpressure: the request stays queued
    until completions free blocks (admission rejects outright only
    requests that could NEVER fit)."""


def blocks_for(tokens, block_size):
    """Blocks covering `tokens` cache rows (0 tokens -> 0 blocks)."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator(object):
    """Host-side block accounting: free list, refcounts, per-slot block
    tables, the reservation ledger, and (share_prefix=True) the
    content-addressed prefix index with its reclaimable LRU.

    `alloc(slot, tokens, commit_tokens)` materializes the blocks for
    `tokens` rows and RESERVES (without materializing) enough blocks
    for `commit_tokens` total; `extend` then draws the growth blocks
    from that reservation, so a request admitted under its full budget
    can never strand mid-decode waiting for a block another request
    holds. With `prompt=` token ids, the prompt's full blocks are first
    matched against the prefix index and seated by incref — only the
    unmatched remainder draws fresh blocks. `available()` is what
    admission may promise to NEW work. Every operation is O(blocks
    touched); steady-state slot churn is O(1) per block."""

    def __init__(self, num_blocks, block_size, share_prefix=False,
                 host_blocks=0):
        if num_blocks < 1:
            raise ValueError(
                "num_blocks must be >= 1, got %d" % num_blocks)
        if block_size < 1:
            raise ValueError(
                "block_size must be >= 1, got %d" % block_size)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.share_prefix = bool(share_prefix)
        # host-spill tier capacity, in blocks (0 = eviction forgets)
        self.host_blocks = int(host_blocks)
        # LIFO: the most recently freed block is reused first (warm
        # reuse; also what the reuse-order tests lock)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}     # slot -> [block ids]
        self._committed = {}  # slot -> total blocks promised
        self._cow_credit = {}  # slot -> reserved CoW copies (0 or 1)
        self._reserved = 0    # promised-but-unmaterialized, all slots
        self._refcount = {}   # bid -> live references (allocated only)
        # prefix index: (parent id, block token tuple) -> id; -1 is
        # the root parent. Collision-free: the key IS the content
        # path. Ids >= 0 are device block ids (RESIDENT); ids <= -2
        # are virtual ids of SPILLED entries whose rows live host-side
        # — vids are minted monotonically and never reused, so a
        # recycled device bid can never collide with a spilled key.
        self._index = {}
        self._index_key = {}  # id -> its index key (reverse map)
        self._children = {}   # id -> set of indexed child ids
        # resident indexed children per parent id: the leaf-first
        # device-eviction predicate, maintained incrementally so
        # eviction never scans (a block is device-evictable iff it is
        # cached AND has no resident indexed children)
        self._rkids = {}
        # refcount-0 blocks still indexed, oldest-first (LRU eviction)
        self._cached = collections.OrderedDict()
        # the O(1) eviction frontier: the subset of _cached with no
        # resident indexed children, in the order each block became
        # evictable (a parent promoted by its last child's spill
        # re-enters at the tail — whole cold chains drain bottom-up
        # before a just-promoted parent jumps the line)
        self._evictable = collections.OrderedDict()
        # spilled entries: vid -> None, oldest spill first (host LRU)
        self._spilled = collections.OrderedDict()
        # droppable spilled entries (no indexed children), oldest first
        self._spill_leaves = collections.OrderedDict()
        self._next_vid = -2
        # data-path hooks the PagedKVPool wires: spill copies a dying
        # device block's rows out to the host store, drop discards a
        # host entry. Accounting here, bytes there.
        self._spill_sink = None   # fn(bid, vid)
        self._drop_sink = None    # fn(vid)
        self._revived = []        # [(vid, new bid)] drained by seat
        self.cow_copies = 0        # monotone: CoW faults served
        self.prefix_hits = 0       # monotone: seats that matched
        self.prefix_hit_tokens = 0  # monotone: tokens seated by incref
        self.spills = 0            # monotone: blocks demoted to host
        self.host_drops = 0        # monotone: spilled entries dropped
        self.blocks_revived = 0    # monotone: spilled blocks uploaded

    # ------------------------------------------------------------ queries

    def num_free(self):
        return len(self._free)

    def num_cached(self):
        """Reclaimable blocks: refcount 0 but still in the prefix
        index — revivable by a match, evictable under pressure."""
        return len(self._cached)

    def num_spilled(self):
        """Spilled entries: chains demoted to the host tier, still
        resolvable by the prefix index, revivable by upload."""
        return len(self._spilled)

    def blocks_in_use(self):
        """Blocks pinned by LIVE references (refcount > 0)."""
        return self.num_blocks - len(self._free) - len(self._cached)

    def shared_blocks(self):
        """Blocks currently referenced by more than one table."""
        return sum(1 for c in self._refcount.values() if c > 1)

    def available(self):
        """Blocks admission may promise to NEW work: free plus
        reclaimable, minus the reservations already promised to
        seated slots."""
        return len(self._free) + len(self._cached) - self._reserved

    def can_fit(self, tokens):
        return blocks_for(tokens, self.block_size) <= self.available()

    def table(self, slot):
        return list(self._tables.get(slot, ()))

    # ----------------------------------------------------- prefix index

    def _full_block_tuples(self, prompt):
        bs = self.block_size
        n = len(prompt) // bs
        return [tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
                for j in range(n)]

    def match_prefix(self, prompt):
        """Longest resident chain of full blocks covering a prefix of
        `prompt`: the block ids, root-first. Read-only (no refcount
        change) — `alloc(prompt=...)` seats on the result."""
        if not self.share_prefix:
            return []
        chain = []
        parent = -1
        for toks in self._full_block_tuples(prompt):
            bid = self._index.get((parent, toks))
            if bid is None:
                break
            chain.append(bid)
            parent = bid
        return chain

    def plan(self, prompt, tokens, commit_tokens=None):
        """(chain, needed) for seating `prompt` with `tokens` rows now
        and `commit_tokens` promised: the matched shared chain and how
        many blocks the seat would draw from `available()` (fresh
        blocks, the CoW credit for a full-prompt match, the
        RECLAIMABLE chain blocks the seat would revive — reviving pops
        a block out of the cache `available()` counts, so it costs
        capacity exactly like a fresh draw — and one fresh block per
        SPILLED chain entry, whose revival-by-upload materializes a
        new device block). The admission-time answer `can_seat` and
        the seat itself (`alloc`) both run through this, so they
        cannot disagree."""
        chain, needed, _cow = self._plan(prompt, tokens, commit_tokens)
        return chain, needed

    def _plan(self, prompt, tokens, commit_tokens=None):
        now = blocks_for(tokens, self.block_size)
        commit = max(
            now, blocks_for(commit_tokens or tokens, self.block_size)
        )
        chain = self.match_prefix(prompt) if prompt is not None else []
        chain = chain[:now]
        # full-prompt match: the engine must re-run the last prompt
        # token for its logits, which re-writes that token's row into
        # the shared tail block -> one planned CoW copy, reserved here.
        # EXCEPT when the tail is reclaimable (refcount 0) or SPILLED:
        # the seat revives it as sole owner and the re-write lands in
        # place, so no copy can fault — its cost is the revival/upload
        # charge below, and charging both would refuse a full-budget
        # reseat forever on an idle pool
        cow = 1 if (chain and len(chain) * self.block_size
                    >= int(tokens)
                    and chain[-1] >= 0
                    and chain[-1] not in self._cached) else 0
        # chain blocks at refcount 0 are counted by available(); the
        # seat revives them (incref pops the cache), so they must be
        # charged or reservations can exceed free + reclaimable and
        # a reservation-backed extend could strand mid-decode
        revived = sum(1 for b in chain if b in self._cached)
        # spilled entries (vids < 0) hold no device block: their
        # revival draws a fresh one, charged exactly like an unmatched
        # block — the chain only saves their PREFILL, not their bytes
        spilled = sum(1 for b in chain if b < 0)
        resident = len(chain) - spilled
        return chain, commit - resident + cow + revived, cow

    def can_seat(self, prompt, tokens, commit_tokens=None):
        _chain, needed = self.plan(prompt, tokens, commit_tokens)
        return needed <= self.available()

    def register_prefix(self, slot, prompt):
        """Index `slot`'s FULL prompt blocks so later prompts can seat
        on them. Walks the index: levels already present (this seat's
        own shared chain, or a concurrent duplicate) keep the existing
        block — chains may interleave blocks owned by different slots,
        which is sound because the key path pins the exact content."""
        if not self.share_prefix:
            return
        table = self._tables.get(slot)
        if table is None:
            return
        parent = -1
        for j, toks in enumerate(self._full_block_tuples(prompt)):
            if j >= len(table):
                break
            key = (parent, toks)
            bid = self._index.get(key)
            if bid is None:
                bid = table[j]
                if bid in self._index_key:
                    # already indexed under another path (shouldn't
                    # happen for fresh private blocks) — don't re-key
                    break
                self._index[key] = bid
                self._index_key[bid] = key
                self._children.setdefault(parent, set()).add(bid)
                if parent >= 0:
                    # the parent gained a resident child: it is no
                    # longer a device-eviction leaf
                    self._rkids[parent] = self._rkids.get(parent, 0) + 1
                    self._evictable.pop(parent, None)
            parent = bid

    def flush_index(self):
        """Drop the whole prefix index, BOTH tiers (hot reload: cached
        rows were computed under superseded params — new requests must
        never seat on them, whether the rows are device-resident or
        spilled host-side). Reclaimable blocks return to the free
        list; spilled entries drop their host buffers; live blocks
        just lose their index entry and free normally at refcount 0."""
        for bid in list(self._cached):
            self._free.append(bid)
            self._refcount.pop(bid, None)
        self._cached.clear()
        self._evictable.clear()
        for vid in list(self._spilled):
            if self._drop_sink is not None:
                self._drop_sink(vid)
            self.host_drops += 1
        self._spilled.clear()
        self._spill_leaves.clear()
        self._index.clear()
        self._index_key.clear()
        self._children.clear()
        self._rkids.clear()

    # -------------------------------------------------------- refcounts

    def incref(self, bid):
        """Add a live reference to `bid`, reviving it from the
        reclaimable cache when its refcount was 0. Every incref must
        be settled by a decref/free (edl-lint EDL501 tracks the
        pair)."""
        self._refcount[bid] = self._refcount.get(bid, 0) + 1
        self._cached.pop(bid, None)
        self._evictable.pop(bid, None)

    def decref(self, bid):
        """Drop a live reference; at refcount 0 the block becomes
        reclaimable (still indexed) or free (not indexed). A block is
        never on the free list while any table references it."""
        rc = self._refcount.get(bid, 0) - 1
        if rc > 0:
            self._refcount[bid] = rc
            return
        self._refcount.pop(bid, None)
        if bid in self._index_key:
            self._cached[bid] = None  # newest at the LRU tail
            if not self._rkids.get(bid):
                self._evictable[bid] = None  # leaf: evictable now
        else:
            self._free.append(bid)

    def _dec_resident_kid(self, parent):
        """A resident indexed child of `parent` left the device tier
        (evicted or spilled); at zero resident children a CACHED
        parent becomes device-evictable — leaf-first, incrementally,
        no scan."""
        if parent < 0:
            return
        n = self._rkids.get(parent, 0) - 1
        if n > 0:
            self._rkids[parent] = n
            return
        self._rkids.pop(parent, None)
        if parent in self._cached:
            self._evictable[parent] = None

    def _unindex(self, node):
        """Remove `node` (bid or vid) from the prefix index entirely.
        Only ever called on index leaves (no indexed children), so no
        child re-keying is needed."""
        key = self._index_key.pop(node)
        del self._index[key]
        parent = key[0]
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(node)
            if not kids:
                del self._children[parent]
                if parent in self._spilled:
                    # the parent just became a host-droppable leaf
                    self._spill_leaves[parent] = None
        self._children.pop(node, None)
        self._rkids.pop(node, None)
        if node >= 0:
            self._dec_resident_kid(parent)

    def _rekey_children(self, old, new):
        """Re-key `old`'s indexed children under id `new` (spill:
        bid -> vid, revive: vid -> bid). The key IS the content path,
        so only the parent-id half moves; the token tuples are
        untouched."""
        sub = self._children.pop(old, None)
        if not sub:
            return False
        self._children[new] = sub
        for child in sub:
            ckey = self._index_key.pop(child)
            del self._index[ckey]
            nkey = (new, ckey[1])
            self._index[nkey] = child
            self._index_key[child] = nkey
        return True

    def _drop_spilled(self):
        """Drop the oldest CHILDLESS spilled entry (leaf-first in the
        host tier too: dropping an interior entry would orphan its
        children's keys). Spilled entries always have a childless
        descendant — device eviction is leaf-first, so a spilled
        node's children are all spilled — hence progress."""
        try:
            vid = next(iter(self._spill_leaves))
        except StopIteration:
            raise OutOfBlocks(
                "no droppable spilled entry (host tier invariant "
                "broken)"
            ) from None
        del self._spill_leaves[vid]
        del self._spilled[vid]
        self._unindex(vid)
        if self._drop_sink is not None:
            self._drop_sink(vid)
        self.host_drops += 1

    def _spill(self, bid):
        """Demote evicted block `bid` to the host tier under a fresh
        virtual id: rows copy out through the spill sink BEFORE the
        device block id is recycled, the trie entry re-keys onto the
        vid (children — all spilled already — re-key under it), and
        the host LRU drops its oldest leaves to stay inside the
        budget."""
        while len(self._spilled) >= self.host_blocks:
            self._drop_spilled()
        vid = self._next_vid
        self._next_vid -= 1
        if self._spill_sink is not None:
            self._spill_sink(bid, vid)
        key = self._index_key.pop(bid)
        self._index[key] = vid
        self._index_key[vid] = key
        parent = key[0]
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(bid)
            kids.add(vid)
        if not self._rekey_children(bid, vid):
            self._spill_leaves[vid] = None
        self._rkids.pop(bid, None)
        self._spilled[vid] = None
        self._dec_resident_kid(parent)
        self.spills += 1

    def _revive(self, vid, bid):
        """Promote spilled entry `vid` back onto device block `bid`
        (the caller uploads the rows): the trie entry re-keys onto the
        bid, spilled children re-key under it, and the move is logged
        for the pool's batched upload."""
        del self._spilled[vid]
        self._spill_leaves.pop(vid, None)
        key = self._index_key.pop(vid)
        self._index[key] = bid
        self._index_key[bid] = key
        parent = key[0]
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(vid)
            kids.add(bid)
        self._rekey_children(vid, bid)
        if parent >= 0:
            self._rkids[parent] = self._rkids.get(parent, 0) + 1
            self._evictable.pop(parent, None)
        self._revived.append((vid, bid))
        self.blocks_revived += 1

    def take_revived(self):
        """Drain the (vid, bid) moves the last alloc revived — the
        pool uploads their host rows into the fresh device blocks in
        one batched scatter."""
        out = self._revived
        self._revived = []
        return out

    def _evict_cached(self):
        """Reclaim the oldest device-evictable block — O(1): the
        `_evictable` frontier is maintained on every incref / decref /
        index / spill transition, so eviction never scans the LRU (a
        live block's ancestors are live, so every reclaimable subtree
        has a reclaimable leaf — the frontier is empty iff the cache
        is). With a host tier the block SPILLS (the chain survives,
        demoted); without one it is forgotten outright."""
        try:
            bid = next(iter(self._evictable))
        except StopIteration:
            raise OutOfBlocks(
                "no evictable cached block (allocator invariant "
                "broken)"
            ) from None
        del self._evictable[bid]
        del self._cached[bid]
        if self.host_blocks > 0:
            self._spill(bid)
        else:
            self._unindex(bid)
        return bid

    def _pop_block(self):
        if self._free:
            return self._free.pop()
        return self._evict_cached()

    # ------------------------------------------------------------- churn

    def alloc(self, slot, tokens, commit_tokens=None, prompt=None):
        """Materialize blocks for `tokens` rows under `slot` and
        reserve up to `commit_tokens` total; raises OutOfBlocks when
        the full commitment is not coverable (nothing is taken then).
        With `prompt` (token ids) and share_prefix, the prompt's full
        blocks seat on the prefix index by incref where resident.
        Returns the number of SHARED tokens (0 without a match)."""
        if slot in self._tables:
            raise ValueError("slot %r already holds blocks" % (slot,))
        now = blocks_for(tokens, self.block_size)
        commit = max(
            now, blocks_for(commit_tokens or tokens, self.block_size)
        )
        chain, needed, cow = self._plan(prompt, tokens, commit_tokens)
        if needed > self.available():
            raise OutOfBlocks(
                "need %d new blocks (%d now, %d shared), %d available"
                % (needed, now, len(chain), self.available())
            )
        # seat the chain: resident entries by incref, spilled entries
        # by revival (pop a fresh block, re-key, log the upload). A
        # pop's own spill cascade can drop a not-yet-revived chain
        # entry under host-budget pressure — the chain truncates there
        # and the remainder draws fresh (the plan charged a fresh
        # block for every spilled entry either way, so accounting is
        # unchanged; only the shared-token count shrinks).
        table_ids = []
        shared_blocks = 0
        for node in chain:
            if node >= 0:
                self.incref(node)
                table_ids.append(node)
                shared_blocks += 1
                continue
            if node not in self._spilled:
                break  # dropped since plan time: rest of chain is gone
            bid = self._pop_block()
            if node in self._spilled:
                self._revive(node, bid)
                self.incref(bid)
                table_ids.append(bid)
                shared_blocks += 1
            else:
                # the pop's cascade dropped THIS entry: the drawn
                # block becomes a plain fresh draw for its position
                self.incref(bid)
                table_ids.append(bid)
                break
        while len(table_ids) < now:
            bid = self._pop_block()
            self.incref(bid)
            table_ids.append(bid)
        self._tables[slot] = table_ids
        self._committed[slot] = commit
        self._cow_credit[slot] = cow
        self._reserved += (commit - now) + cow
        if shared_blocks:
            self.prefix_hits += 1
            self.prefix_hit_tokens += shared_blocks * self.block_size
        return shared_blocks * self.block_size

    def extend(self, slot, total_tokens):
        """Grow `slot`'s table to cover `total_tokens` rows; growth
        inside the slot's commitment draws reserved blocks (never
        fails), growth beyond it competes with admission and can raise
        OutOfBlocks. Returns the appended block ids."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError("slot %r holds no blocks" % (slot,))
        need = blocks_for(total_tokens, self.block_size) - len(table)
        added = []
        for _ in range(max(0, need)):
            if len(table) < self._committed[slot]:
                self._reserved -= 1  # drawing our own reservation
            elif self.available() < 1:
                raise OutOfBlocks(
                    "slot %r grew past its commitment and no block is "
                    "available" % (slot,)
                )
            else:
                self._committed[slot] += 1
            bid = self._pop_block()
            self.incref(bid)
            table.append(bid)
            added.append(bid)
        return added

    def cow(self, slot, block_index):
        """Copy-on-write fault: `slot` is about to write into its
        table[block_index]. If that block is shared (refcount > 1), a
        fresh block replaces it in the table — drawing the slot's CoW
        credit reserved at seat time (falling back to free capacity
        for an UNPLANNED divergence) — and the shared original is
        decref'd, never freed out from under its other owners.
        Returns (old bid, new bid) when a copy happened, None when the
        block was private (write is safe in place)."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError("slot %r holds no blocks" % (slot,))
        old = table[block_index]
        if self._refcount.get(old, 0) <= 1:
            return None
        if self._cow_credit.get(slot, 0) > 0:
            self._cow_credit[slot] -= 1
            self._reserved -= 1  # the credit was reserved at seat
        elif self.available() < 1:
            raise OutOfBlocks(
                "CoW fault on slot %r with no block available (no "
                "credit reserved and the pool is dry)" % (slot,)
            )
        new = self._pop_block()
        self.incref(new)
        table[block_index] = new
        self.decref(old)
        self.cow_copies += 1
        return old, new

    def free(self, slot):
        """Release `slot`'s references and its remaining reservation;
        returns how many table entries were dropped. Shared blocks
        survive (decref only) — a block returns to the free list or
        the reclaimable cache strictly at refcount 0. Safe to call for
        a slot that holds nothing (0)."""
        table = self._tables.pop(slot, None)
        if table is None:
            return 0
        self._reserved -= (
            self._committed.pop(slot) - len(table)
            + self._cow_credit.pop(slot, 0)
        )
        # decref'd in table order so a fully-private table lands on the
        # free list with the block allocated LAST on top of the stack
        # (LIFO through the whole alloc -> free -> alloc cycle)
        for bid in table:
            self.decref(bid)
        return len(table)


def build_pools(kv_shapes, cache_len, num_blocks, block_size):
    """Device arenas from the model's batch-1 decode-cache template
    (api/generation._kv_shapes_for): every KV row leaf
    `[1, hkv, cache_len, d]` becomes `[num_blocks, block_size, hkv, d]`
    zeros; non-row leaves (the position counter) stay as zero-d
    placeholders so the pool tree keeps the cache tree's structure —
    the model slices its own layer's arenas out of it by name."""
    def arena(leaf):
        if kv_row_leaf(leaf, cache_len):
            _, hkv, _, d = leaf.shape
            return jnp.zeros((num_blocks, block_size, hkv, d),
                             leaf.dtype)
        return jnp.zeros(leaf.shape, leaf.dtype)

    return jax.tree.map(arena, kv_shapes)


def write_prompt_block(pools, kv, j, bid, block_size):
    """Insert block `j` of a freshly prefilled batch-1 cache tree into
    the arenas at block id `bid` — ONE `dynamic_update_slice` per row
    leaf at a TRACED (j, bid), so one compiled write serves every
    (prompt bucket, block, slot) combination. Rows past the true
    prompt length inside the last block are prefill junk; the paged
    attention masks `k_pos < length` so they are never read before the
    decode scatter overwrites them."""
    def upd(pool, leaf):
        if leaf.ndim != 4:  # the position counter placeholder
            return pool
        rows = jax.lax.dynamic_slice_in_dim(
            leaf[0], j * block_size, block_size, axis=1
        )  # [hkv, block_size, d]
        rows = rows.transpose(1, 0, 2)  # [block_size, hkv, d]
        return jax.lax.dynamic_update_slice(
            pool, rows[None], (bid, 0, 0, 0)
        )

    return jax.tree.map(upd, pools, kv)


def copy_block(pools, src, dst):
    """Device-side CoW: duplicate arena block `src` into `dst` on
    every row leaf (one gather + dynamic_update_slice per leaf, traced
    indices — one compiled copy serves every fault)."""
    def upd(pool):
        if pool.ndim != 4:
            return pool
        return jax.lax.dynamic_update_slice(
            pool,
            jax.lax.dynamic_slice_in_dim(pool, src, 1, axis=0),
            (dst, 0, 0, 0),
        )

    return jax.tree.map(upd, pools)


def scatter_rows(pools, rows, bids, offs):
    """Write decode rows into the arenas: `rows` is a tree whose
    structure is a SUBSET of `pools` (the model's "kv_out" sown
    collection) with leaves `[..., hkv, d]` — one row per leading
    index; `bids`/`offs` carry matching leading shape (`[S]` for the
    per-slot step, `[S, t]` for the speculative verify tile, `[t]` for
    a suffix prefill). Rows to drop (free lanes, rolled-back draft
    rows, pad rows) carry an out-of-bounds bid and are discarded by the
    scatter — they never touch a block a live sequence owns. Distinct
    live rows target distinct (block, offset) pairs, so the scatter
    indices never collide."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(pools)
    rmap = {
        jax.tree_util.keystr(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(rows)[0]
    }
    out = []
    for path, pool in flat:
        row = rmap.get(jax.tree_util.keystr(path))
        if row is None:
            out.append(pool)
        else:
            out.append(pool.at[bids, offs].set(row, mode="drop"))
    return jax.tree_util.tree_unflatten(treedef, out)


def _settle_chain_refs(alloc, bids):
    """Drop an import walk's keep-alive references root-first so the
    chain parks refcount-0 cached. Takes ownership of the references
    (the EDL501 settle for import_chain's increfs): called from a
    finally, it must run even when the walk or the upload failed."""
    for bid in bids:
        alloc.decref(bid)


def _pool_tjit(pool, name, fn, **jit_kwargs):
    """jax.jit with recompile-sentry adoption for the pool's compiled
    helpers — lazy like the engine's _tjit, so executables built
    before the server attaches the sentry still count later
    compiles."""
    from elasticdl_tpu.observability.runtime_health import tracked_jit

    return tracked_jit(
        fn, name, lambda: getattr(pool, "sentry", None), **jit_kwargs
    )


class PagedKVPool(object):
    """The device arenas + host tables for one serving engine.

    Owns the BlockAllocator, the `[num_slots, seq_len/block_size]`
    int32 table mirror the compiled step consumes (-1 = unallocated),
    and the jitted block write/copy. `cache_len % block_size == 0` is
    required so prompt blocks slice cleanly out of the prefill cache.

    The device copy of the table mirror is cached: `tables_device()`
    re-uploads only after a mutation (alloc/extend/CoW/release), so a
    decode step that crosses no block boundary costs zero host->device
    table traffic — the per-step assembly is one cached handle, not
    per-slot work."""

    def __init__(self, kv_shapes, cache_len, num_slots, num_blocks,
                 block_size, share_prefix=False, host_bytes=0):
        cache_len = int(cache_len)
        block_size = int(block_size)
        if cache_len % block_size:
            raise ValueError(
                "seq_len %d must be a multiple of kv_block_size %d"
                % (cache_len, block_size)
            )
        self.cache_len = cache_len
        self.block_size = block_size
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_slot = cache_len // block_size
        self.allocator = BlockAllocator(num_blocks, block_size,
                                        share_prefix=share_prefix)
        self.pools = build_pools(kv_shapes, cache_len, num_blocks,
                                 block_size)
        self.tables = np.full(
            (int(num_slots), self.max_blocks_per_slot), -1, np.int32
        )
        self._tables_dev = None  # cached device upload of `tables`
        # TRUE arena bytes: summed per leaf at its OWN dtype, so int8
        # arenas count their int8 rows AND f32 scale leaves exactly —
        # never a homogeneous row-dtype assumption. This is what
        # kv_bytes_in_use / bytes-per-generated-token report.
        row_leaves = [
            leaf for leaf in jax.tree.leaves(self.pools)
            if leaf.ndim == 4
        ]
        self.bytes_total = int(sum(leaf.nbytes for leaf in row_leaves))
        self.block_bytes = self.bytes_total // max(1, self.num_blocks)
        # the arenas' storage format, advertised on stats/ServerStatus:
        # any int8 row leaf means the quantized format (its f32 scale
        # leaves ride along)
        self.kv_cache_dtype = (
            "int8" if any(leaf.dtype == jnp.int8 for leaf in row_leaves)
            else ""
        )
        self._write_fn = None
        self._copy_fn = None
        # ---- tiered host spill (serving the ROADMAP "Tiered KV
        # cache" item): the budget is BYTES, the allocator accounts in
        # BLOCKS — one spilled block costs exactly block_bytes (full
        # blocks only enter the index, and a spill copies every row
        # leaf, scale leaves included)
        self.host_bytes_budget = int(host_bytes)
        host_blocks = (self.host_bytes_budget // self.block_bytes
                       if self.block_bytes else 0)
        self.allocator.host_blocks = int(host_blocks)
        self.allocator._spill_sink = self._spill_block
        self.allocator._drop_sink = self._drop_host_block
        self._host_rows = {}   # vid -> [np rows per 4-d leaf, in order]
        self.host_blocks_peak = 0
        self.revive_uploads = 0  # monotone: batched revival scatters
        # disaggregated handoff economy (serving/disagg.py): chains
        # exported to / imported from sibling replicas, and the prompt
        # tokens imports seated without re-running prefill here
        self.chain_exports = 0
        self.chain_imports = 0
        self.chain_import_tokens = 0
        self._gather_fn = None
        self._upload_fns = {}  # padded batch size -> compiled scatter
        # optional StepProfiler (serving/engine.py): the pool times its
        # revive uploads — the one decode phase only it can see
        self.profiler = None
        # recompile sentry (runtime health): the engine forwards its
        # sentry so the pool's own executables (spill gather, revival
        # upload buckets, prompt write, CoW copy) count into the same
        # edl_serving_recompiles_total{fn=} family. None = plain jit.
        self.sentry = None

    # ----------------------------------------------------------- lifecycle

    def can_seat(self, prompt, prompt_tokens, commit_tokens):
        return self.allocator.can_seat(prompt, prompt_tokens,
                                       commit_tokens)

    def seat(self, slot, prompt, commit_tokens):
        """Reserve the request's full block budget and materialize the
        prompt's blocks — shared-prefix blocks by incref, spilled
        chain blocks by revival upload, the rest fresh; raises
        OutOfBlocks with nothing taken. Returns the shared token count
        (0 without a match; revived tokens count as shared — they are
        seated without re-running prefill either way)."""
        shared = self.allocator.alloc(
            slot, len(prompt), commit_tokens=commit_tokens,
            prompt=prompt,
        )
        self._apply_revivals()
        self._sync_row(slot)
        return shared

    # ------------------------------------------------- host spill tier

    def _gather_rows(self, bid):
        """One block's rows as host numpy arrays — every 4-d arena
        leaf (int8 rows and f32 scale leaves alike) through ONE
        compiled gather with a traced bid. The spill sink and the
        chain export both read through here, so an exported chain is
        byte-identical to what the host spill tier would hold for the
        same blocks."""
        if self._gather_fn is None:
            def gather(pools, b):
                return [leaf[b] for leaf in jax.tree.leaves(pools)
                        if leaf.ndim == 4]

            self._gather_fn = _pool_tjit(
                self, "kv_spill_gather", gather
            )
        rows = self._gather_fn(self.pools, jnp.asarray(bid, jnp.int32))
        return [np.asarray(r) for r in rows]

    def _spill_block(self, bid, vid):
        """Allocator spill sink: copy device block `bid`'s rows into
        host numpy buffers under `vid`, BEFORE the bid is recycled."""
        self._host_rows[vid] = self._gather_rows(bid)
        self.host_blocks_peak = max(self.host_blocks_peak,
                                    len(self._host_rows))

    def _drop_host_block(self, vid):
        """Allocator drop sink: the host LRU (or a flush) discarded a
        spilled entry — its rows are gone for good."""
        self._host_rows.pop(vid, None)

    def _upload_rows(self, staged):
        """Upload staged `(bid, [np rows per leaf])` row sets into
        their device blocks: ONE batched scatter over the block axis,
        padded to a power-of-two bucket (pad lanes carry the
        out-of-bounds drop id), so a handful of executables serve
        every upload size. Revival and chain import both land here —
        the import path is the revival upload pointed at a sibling
        replica's bytes instead of this host's spill store."""
        prof = self.profiler
        t0 = prof.t() if prof is not None else 0.0
        k = len(staged)
        k_pad = 1
        while k_pad < k:
            k_pad *= 2
        bids = np.full(k_pad, self.num_blocks, np.int32)  # drop lanes
        per_leaf = None
        for i, (bid, rows) in enumerate(staged):
            bids[i] = bid
            if per_leaf is None:
                per_leaf = [
                    np.zeros((k_pad,) + r.shape, r.dtype) for r in rows
                ]
            for j, r in enumerate(rows):
                per_leaf[j][i] = r
        fn = self._upload_fns.get(k_pad)
        if fn is None:
            def upload(pools, rows_list, b):
                flat, treedef = jax.tree_util.tree_flatten(pools)
                out, it = [], iter(rows_list)
                for leaf in flat:
                    if leaf.ndim == 4:
                        out.append(
                            leaf.at[b].set(next(it), mode="drop")
                        )
                    else:
                        out.append(leaf)
                return jax.tree_util.tree_unflatten(treedef, out)

            fn = _pool_tjit(
                self, "kv_revive_upload[%d]" % k_pad, upload
            )
            self._upload_fns[k_pad] = fn
        self.pools = fn(
            self.pools,
            [jnp.asarray(r) for r in per_leaf],
            jnp.asarray(bids),
        )
        self.revive_uploads += 1
        if prof is not None:
            jax.block_until_ready(self.pools)
            prof.observe("revive_upload", prof.t() - t0)

    def _apply_revivals(self):
        """Upload the rows of every chain entry the last seat revived
        into its freshly allocated device block. The host copies are
        consumed — revival is a MOVE, not a copy."""
        moves = self.allocator.take_revived()
        if not moves:
            return
        self._upload_rows(
            [(bid, self._host_rows.pop(vid)) for vid, bid in moves]
        )

    # ------------------------------------------- disaggregated handoff

    def leaf_dtypes(self):
        """Row-leaf dtype names in jax.tree.leaves order — the arena
        format fingerprint a chain transfer carries so an importer can
        refuse a mismatched payload."""
        return [str(leaf.dtype) for leaf in jax.tree.leaves(self.pools)
                if leaf.ndim == 4]

    def export_chain(self, prompt):
        """Export the longest indexed chain covering `prompt` as a
        dense byte copy: `[(block token tuple, [np rows per leaf])]`
        root-first, resident blocks through the same compiled gather
        the spill tier uses and spilled blocks straight from the host
        store (copied, not consumed). Runs on the scheduler thread, so
        nothing can evict a chain entry mid-gather. Empty list = no
        full prompt block is indexed (nothing to hand off)."""
        alloc = self.allocator
        chain = alloc.match_prefix(prompt)
        tuples = alloc._full_block_tuples(prompt)[:len(chain)]
        blocks = []
        for node, toks in zip(chain, tuples):
            if node >= 0:
                rows = self._gather_rows(node)
            else:
                host = self._host_rows.get(node)
                if host is None:
                    break
                rows = [np.array(r) for r in host]
            blocks.append((toks, rows))
        if blocks:
            self.chain_exports += 1
        return blocks

    def import_chain(self, blocks, leaf_dtypes=None):
        """Import an exported chain into THIS pool: walk the
        `(parent, tokens)` keys root-first, dedup against entries the
        trie already resolves (resident or spilled), allocate a fresh
        block for each missing level and re-key it into the index as a
        refcount-0 reclaimable entry, then land every new block's rows
        in one batched upload. Returns `(blocks_added, tokens_added)`.
        A later prompt seats on the imported chain exactly like any
        prefix hit, so sharing, CoW and spec decode compose unchanged.
        Import stops early (partial chain, still a usable prefix) when
        the pool runs out of blocks."""
        alloc = self.allocator
        if not alloc.share_prefix:
            raise ValueError(
                "chain import requires a prefix-shared pool "
                "(kv_shared=True)"
            )
        if leaf_dtypes is not None:
            mine = self.leaf_dtypes()
            if list(leaf_dtypes) != mine:
                raise ValueError(
                    "chain leaf dtypes %r do not match this pool's %r"
                    % (list(leaf_dtypes), mine)
                )
        # validate the WHOLE payload before allocating anything: a
        # malformed level mid-chain must not leave earlier levels'
        # references un-settled
        blocks = [(tuple(int(t) for t in toks), rows)
                  for toks, rows in blocks]
        for toks, _ in blocks:
            if len(toks) != self.block_size:
                raise ValueError(
                    "chain block carries %d tokens, block_size is %d"
                    % (len(toks), self.block_size)
                )
        parent = -1
        staged = []   # (bid, rows) for the batched upload
        fresh = []    # bids held live until the walk finishes
        try:
            for toks, rows in blocks:
                key = (parent, toks)
                node = alloc._index.get(key)
                if node is not None:
                    # the trie already resolves this level (resident
                    # or spilled) — dedup: keep walking under the
                    # existing id
                    parent = node
                    continue
                if parent < -1:
                    # the chain continues under a SPILLED level this
                    # pool already held: importing a device child
                    # under a vid parent would invert the leaf-first
                    # spill invariant (resident child of a spilled
                    # parent) — stop; the spilled prefix still
                    # resolves and revives normally
                    break
                try:
                    bid = alloc._pop_block()
                except OutOfBlocks:
                    break
                # held live while the walk continues so a later pop's
                # eviction cascade cannot reclaim the chain under us
                alloc.incref(bid)
                alloc._index[key] = bid
                alloc._index_key[bid] = key
                alloc._children.setdefault(parent, set()).add(bid)
                if parent >= 0:
                    alloc._rkids[parent] = (
                        alloc._rkids.get(parent, 0) + 1
                    )
                    alloc._evictable.pop(parent, None)
                staged.append((bid, rows))
                fresh.append(bid)
                parent = bid
            if staged:
                self._upload_rows(staged)
        finally:
            # settle: imported blocks park refcount-0 in the
            # reclaimable cache (root-first, so each non-leaf has
            # resident children and only the chain tail joins the
            # eviction frontier) — in a finally so neither a failed
            # upload nor a mid-walk error can leave the chain pinned
            _settle_chain_refs(alloc, fresh)
        added = len(staged)
        if added:
            self.chain_imports += 1
            self.chain_import_tokens += added * self.block_size
        return added, added * self.block_size

    def host_bytes_in_use(self):
        """True host-tier bytes: spilled blocks hold every row leaf of
        one block at its own dtype, i.e. exactly block_bytes each."""
        return len(self._host_rows) * self.block_bytes

    def register_prefix(self, slot, prompt):
        """Index the slot's full prompt blocks for future sharing
        (call after their rows are actually resident)."""
        self.allocator.register_prefix(slot, prompt)

    def write_prompt(self, kv, slot, prompt_tokens, start_block=0):
        """Scatter the prefilled cache's blocks [start_block, ...)
        into the slot's allocated blocks — block-granular, no
        whole-slot copy (shared blocks below start_block are already
        resident and must not be re-written)."""
        if self._write_fn is None:
            self._write_fn = _pool_tjit(
                self, "kv_prompt_write", write_prompt_block,
                static_argnames=("block_size",),
            )
        table = self.allocator.table(slot)
        for j in range(start_block,
                       blocks_for(prompt_tokens, self.block_size)):
            self.pools = self._write_fn(
                self.pools, kv, jnp.asarray(j, jnp.int32),
                jnp.asarray(table[j], jnp.int32),
                block_size=self.block_size,
            )

    def ensure_blocks(self, slot, pos):
        """Make sure the block covering cache position `pos` exists
        (the decode step writes up to there this iteration); draws the
        slot's reservation, so it cannot fail for a seated request."""
        if self.allocator.extend(slot, pos + 1):
            self._sync_row(slot)

    # back-compat spelling (single position)
    ensure_block = ensure_blocks

    def cow_for_write(self, slot, pos):
        """Copy-on-write guard before `slot` writes cache position
        `pos`: if the covering block is shared, copy it (device) and
        repoint the table. Returns the (old, new) ids or None."""
        moved = self.allocator.cow(slot, pos // self.block_size)
        if moved is None:
            return None
        old, new = moved
        if self._copy_fn is None:
            self._copy_fn = _pool_tjit(
                self, "kv_cow_copy", copy_block
            )
        self.pools = self._copy_fn(
            self.pools, jnp.asarray(old, jnp.int32),
            jnp.asarray(new, jnp.int32),
        )
        self._sync_row(slot)
        return moved

    def release(self, slot):
        """Reclaim a finished/evicted slot's references (O(1) per
        block); private rows are dead the moment the table forgets
        them, shared rows live on under their other owners."""
        freed = self.allocator.free(slot)
        if freed:
            self.tables[slot, :] = -1
            self._tables_dev = None
        return freed

    def flush_prefix_cache(self):
        """Hot reload hook: stale-params rows must never seat a new
        request — BOTH tiers flush (BlockAllocator.flush_index drops
        every spilled entry through the drop sink, emptying the host
        store here)."""
        self.allocator.flush_index()

    def _sync_row(self, slot):
        table = self.allocator.table(slot)
        row = np.full(self.max_blocks_per_slot, -1, np.int32)
        row[: len(table)] = table
        self.tables[slot] = row
        self._tables_dev = None  # mutation: next step re-uploads once

    def tables_device(self):
        """The block tables as ONE cached device array — re-uploaded
        only after a mutation, so steady-state decode steps pay no
        host->device table transfer."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self.tables)
        return self._tables_dev

    # ------------------------------------------------------------- stats

    def bytes_in_use(self):
        return self.allocator.blocks_in_use() * self.block_bytes

    def stats(self):
        return {
            "kv_paged": True,
            "kv_shared": self.allocator.share_prefix,
            "kv_cache_dtype": self.kv_cache_dtype,
            "kv_block_size": self.block_size,
            "kv_blocks_total": self.num_blocks,
            # capacity available to new work: free + reclaimable —
            # cached prefixes are not "in use", they are a warm cache
            "kv_blocks_free": (self.allocator.num_free()
                               + self.allocator.num_cached()),
            "kv_blocks_cached": self.allocator.num_cached(),
            "kv_blocks_shared": self.allocator.shared_blocks(),
            "kv_bytes_total": self.bytes_total,
            "kv_bytes_in_use": self.bytes_in_use(),
            "prefix_hit_tokens": self.allocator.prefix_hit_tokens,
            "cow_copies": self.allocator.cow_copies,
            # tiered host spill: current host-tier occupancy (gauges)
            # and the monotone spill economy (counters). Tokens, not
            # blocks, for the revival headline — spilled blocks are
            # always full, so the product is exact.
            "kv_host_blocks": self.allocator.num_spilled(),
            "kv_host_bytes": self.host_bytes_in_use(),
            "kv_host_bytes_budget": self.host_bytes_budget,
            "revive_uploads": self.revive_uploads,
            "prefill_tokens_revived": (
                self.allocator.blocks_revived * self.block_size
            ),
            "host_drops": self.allocator.host_drops,
            # disaggregated handoff economy (serving/disagg.py)
            "chain_exports": self.chain_exports,
            "chain_imports": self.chain_imports,
            "chain_import_tokens": self.chain_import_tokens,
        }
