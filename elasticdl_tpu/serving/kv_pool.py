"""Block-paged KV storage for the serving engine.

The dense decode pool (engine.py) gives every slot a contiguous
`seq_len` stripe of cache per layer, so decode HBM scales as
`num_slots x seq_len` even though most requests finish far short of
`seq_len` — the padding is resident, bandwidth-neutral, and
unsellable. This module converts that padding into admissible work:

* KV rows live in per-layer block ARENAS shaped
  `[num_blocks, block_size, kv_heads, head_dim]`, shared by every
  sequence on the server;
* a sequence's logical cache is its BLOCK TABLE — the ordered block
  ids covering positions `[j*block_size, (j+1)*block_size)`;
* `BlockAllocator` is the host-side free-list: alloc/extend/free are
  O(1) per block, and a RESERVATION ledger guarantees that a seated
  request can always extend to its full token budget — out-of-blocks
  is an admission-time condition (backpressure), never a mid-decode
  crash;
* `PagedKVPool` owns the device arenas and the two write paths: the
  block-granular prompt insertion (one `dynamic_update_slice` per
  block, never a whole-slot copy) and the per-step decode-row scatter
  (`.at[bids, offs].set`, one row per active slot, free lanes dropped
  via an out-of-bounds sentinel).

Block ids enter the compiled decode step as DEVICE arrays (the tables),
so slot churn and sequence growth never recompile anything — the same
zero-recompile contract the dense pool holds, at block granularity.
The attention that consumes this layout is
`ops.attention.paged_decode_attention`.
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.api.generation import kv_row_leaf


class OutOfBlocks(Exception):
    """The pool cannot cover a request's block budget right now. The
    scheduler treats this as backpressure: the request stays queued
    until completions free blocks (admission rejects outright only
    requests that could NEVER fit)."""


def blocks_for(tokens, block_size):
    """Blocks covering `tokens` cache rows (0 tokens -> 0 blocks)."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator(object):
    """Host-side block accounting: LIFO free list, per-slot block
    tables, and a reservation ledger.

    `alloc(slot, tokens, commit_tokens)` materializes the blocks for
    `tokens` rows and RESERVES (without materializing) enough blocks
    for `commit_tokens` total; `extend` then draws the growth blocks
    from that reservation, so a request admitted under its full budget
    can never strand mid-decode waiting for a block another request
    holds. `available()` is what admission may promise to NEW work.
    Every operation is O(blocks touched); steady-state slot churn is
    O(1) per block."""

    def __init__(self, num_blocks, block_size):
        if num_blocks < 1:
            raise ValueError(
                "num_blocks must be >= 1, got %d" % num_blocks)
        if block_size < 1:
            raise ValueError(
                "block_size must be >= 1, got %d" % block_size)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO: the most recently freed block is reused first (warm
        # reuse; also what the reuse-order tests lock)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}     # slot -> [block ids]
        self._committed = {}  # slot -> total blocks promised
        self._reserved = 0    # promised-but-unmaterialized, all slots

    # ------------------------------------------------------------ queries

    def num_free(self):
        return len(self._free)

    def blocks_in_use(self):
        return self.num_blocks - len(self._free)

    def available(self):
        """Blocks admission may promise to NEW work: free minus the
        reservations already promised to seated slots."""
        return len(self._free) - self._reserved

    def can_fit(self, tokens):
        return blocks_for(tokens, self.block_size) <= self.available()

    def table(self, slot):
        return list(self._tables.get(slot, ()))

    # ------------------------------------------------------------- churn

    def alloc(self, slot, tokens, commit_tokens=None):
        """Materialize blocks for `tokens` rows under `slot` and
        reserve up to `commit_tokens` total; raises OutOfBlocks when
        the full commitment is not coverable (nothing is taken then)."""
        if slot in self._tables:
            raise ValueError("slot %r already holds blocks" % (slot,))
        now = blocks_for(tokens, self.block_size)
        commit = max(
            now, blocks_for(commit_tokens or tokens, self.block_size)
        )
        if commit > self.available():
            raise OutOfBlocks(
                "need %d blocks (%d now), %d available"
                % (commit, now, self.available())
            )
        self._tables[slot] = [self._free.pop() for _ in range(now)]
        self._committed[slot] = commit
        self._reserved += commit - now
        return self.table(slot)

    def extend(self, slot, total_tokens):
        """Grow `slot`'s table to cover `total_tokens` rows; growth
        inside the slot's commitment draws reserved blocks (never
        fails), growth beyond it competes with admission and can raise
        OutOfBlocks. Returns the appended block ids."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError("slot %r holds no blocks" % (slot,))
        need = blocks_for(total_tokens, self.block_size) - len(table)
        added = []
        for _ in range(max(0, need)):
            if len(table) < self._committed[slot]:
                self._reserved -= 1  # drawing our own reservation
            elif self.available() < 1:
                raise OutOfBlocks(
                    "slot %r grew past its commitment and no block is "
                    "available" % (slot,)
                )
            else:
                self._committed[slot] += 1
            bid = self._free.pop()
            table.append(bid)
            added.append(bid)
        return added

    def free(self, slot):
        """Release `slot`'s blocks and its remaining reservation;
        returns how many blocks went back on the free list. Safe to
        call for a slot that holds nothing (0)."""
        table = self._tables.pop(slot, None)
        if table is None:
            return 0
        self._reserved -= self._committed.pop(slot) - len(table)
        # pushed in table order so the block allocated LAST sits on top
        # of the stack and is reused first (LIFO through the whole
        # alloc -> free -> alloc cycle)
        self._free.extend(table)
        return len(table)


def build_pools(kv_shapes, cache_len, num_blocks, block_size):
    """Device arenas from the model's batch-1 decode-cache template
    (api/generation._kv_shapes_for): every KV row leaf
    `[1, hkv, cache_len, d]` becomes `[num_blocks, block_size, hkv, d]`
    zeros; non-row leaves (the position counter) stay as zero-d
    placeholders so the pool tree keeps the cache tree's structure —
    the model slices its own layer's arenas out of it by name."""
    def arena(leaf):
        if kv_row_leaf(leaf, cache_len):
            _, hkv, _, d = leaf.shape
            return jnp.zeros((num_blocks, block_size, hkv, d),
                             leaf.dtype)
        return jnp.zeros(leaf.shape, leaf.dtype)

    return jax.tree.map(arena, kv_shapes)


def write_prompt_block(pools, kv, j, bid, block_size):
    """Insert block `j` of a freshly prefilled batch-1 cache tree into
    the arenas at block id `bid` — ONE `dynamic_update_slice` per row
    leaf at a TRACED (j, bid), so one compiled write serves every
    (prompt bucket, block, slot) combination. Rows past the true
    prompt length inside the last block are prefill junk; the paged
    attention masks `k_pos < length` so they are never read before the
    decode scatter overwrites them."""
    def upd(pool, leaf):
        if leaf.ndim != 4:  # the position counter placeholder
            return pool
        rows = jax.lax.dynamic_slice_in_dim(
            leaf[0], j * block_size, block_size, axis=1
        )  # [hkv, block_size, d]
        rows = rows.transpose(1, 0, 2)  # [block_size, hkv, d]
        return jax.lax.dynamic_update_slice(
            pool, rows[None], (bid, 0, 0, 0)
        )

    return jax.tree.map(upd, pools, kv)


def scatter_rows(pools, rows, bids, offs):
    """Write one decode row per slot into the arenas: `rows` is a tree
    whose structure is a SUBSET of `pools` (the model's "kv_out" sown
    collection) with leaves `[S, hkv, d]`; `bids`/`offs` are `[S]`
    block ids and in-block offsets. Free lanes carry an out-of-bounds
    bid and are dropped by the scatter — they never touch a block a
    live sequence owns. Distinct live slots own distinct blocks, so
    the scatter indices never collide."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(pools)
    rmap = {
        jax.tree_util.keystr(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(rows)[0]
    }
    out = []
    for path, pool in flat:
        row = rmap.get(jax.tree_util.keystr(path))
        if row is None:
            out.append(pool)
        else:
            out.append(pool.at[bids, offs].set(row, mode="drop"))
    return jax.tree_util.tree_unflatten(treedef, out)


class PagedKVPool(object):
    """The device arenas + host tables for one serving engine.

    Owns the BlockAllocator, the `[num_slots, seq_len/block_size]`
    int32 table mirror the compiled step consumes (-1 = unallocated),
    and the jitted block write. `cache_len % block_size == 0` is
    required so prompt blocks slice cleanly out of the prefill cache."""

    def __init__(self, kv_shapes, cache_len, num_slots, num_blocks,
                 block_size):
        cache_len = int(cache_len)
        block_size = int(block_size)
        if cache_len % block_size:
            raise ValueError(
                "seq_len %d must be a multiple of kv_block_size %d"
                % (cache_len, block_size)
            )
        self.cache_len = cache_len
        self.block_size = block_size
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_slot = cache_len // block_size
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.pools = build_pools(kv_shapes, cache_len, num_blocks,
                                 block_size)
        self.tables = np.full(
            (int(num_slots), self.max_blocks_per_slot), -1, np.int32
        )
        row_bytes = [
            leaf.nbytes for leaf in jax.tree.leaves(self.pools)
            if leaf.ndim == 4
        ]
        self.bytes_total = int(sum(row_bytes))
        self.block_bytes = self.bytes_total // max(1, self.num_blocks)
        self._write_fn = None

    # ----------------------------------------------------------- lifecycle

    def seat(self, slot, prompt_tokens, commit_tokens):
        """Reserve the request's full block budget and materialize the
        prompt's blocks; raises OutOfBlocks with nothing taken."""
        self.allocator.alloc(slot, prompt_tokens,
                             commit_tokens=commit_tokens)
        self._sync_row(slot)

    def write_prompt(self, kv, slot, prompt_tokens):
        """Scatter the prefilled cache's first ceil(p/bs) blocks into
        the slot's allocated blocks — block-granular, no whole-slot
        copy."""
        if self._write_fn is None:
            self._write_fn = jax.jit(
                write_prompt_block, static_argnames=("block_size",)
            )
        table = self.allocator.table(slot)
        for j in range(blocks_for(prompt_tokens, self.block_size)):
            self.pools = self._write_fn(
                self.pools, kv, jnp.asarray(j, jnp.int32),
                jnp.asarray(table[j], jnp.int32),
                block_size=self.block_size,
            )

    def ensure_block(self, slot, pos):
        """Make sure the block covering cache position `pos` exists
        (the decode step writes there this iteration); draws the
        slot's reservation, so it cannot fail for a seated request."""
        self.allocator.extend(slot, pos + 1)
        self._sync_row(slot)

    def release(self, slot):
        """Reclaim a finished/evicted slot's blocks (O(1) per block);
        the rows are dead the moment the table forgets them."""
        freed = self.allocator.free(slot)
        self.tables[slot, :] = -1
        return freed

    def _sync_row(self, slot):
        table = self.allocator.table(slot)
        row = np.full(self.max_blocks_per_slot, -1, np.int32)
        row[: len(table)] = table
        self.tables[slot] = row

    # ------------------------------------------------------------- stats

    def bytes_in_use(self):
        return self.allocator.blocks_in_use() * self.block_bytes

    def stats(self):
        return {
            "kv_paged": True,
            "kv_block_size": self.block_size,
            "kv_blocks_total": self.num_blocks,
            "kv_blocks_free": self.allocator.num_free(),
            "kv_bytes_total": self.bytes_total,
            "kv_bytes_in_use": self.bytes_in_use(),
        }
