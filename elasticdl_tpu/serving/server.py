"""The generation server: scheduler thread + gRPC front-end.

Wiring (one process):

    gRPC threads ──submit──> RequestQueue ──pop──┐
         ^                                       v
         └──events (tokens/done/error)── _Scheduler thread
                                           │ engine.insert / engine.step
                                           │ watcher.poll  (hot reload)
                                           │ telemetry gauges
                                           v
                              ContinuousBatchingEngine (jit decode pool)

All jax work happens on the single scheduler thread; gRPC handler
threads only touch the admission queue and their request's event queue,
and block with a LIVENESS BOUND: every wait re-checks the request's
deadline and the scheduler's pulse, so a killed or wedged scheduler
turns into a clean RESOURCE_EXHAUSTED/DEADLINE_EXCEEDED, never a hung
client (the kill-drill's invariant).

Fault injection: the servicer is wrapped at the same choke point the
master uses (common/fault_injection.py, EDL_FAULT_SPEC) with the
serving RPC names — overload and kill drills are spec-driven, e.g.
``generate:error:3`` or ``generate:kill:1:skip=8``.
"""

import collections
import os
import threading
import time
from concurrent import futures

from elasticdl_tpu.common.fault_injection import (
    SERVING_RPCS,
    FaultInjector,
    InjectedRpcError,
    maybe_wrap_servicer,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.observability import forensics
from elasticdl_tpu.observability.tracing import recorder
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.admission import (
    AdmissionError,
    RequestQueue,
    ServingRequest,
)
from elasticdl_tpu.observability.metrics import (
    MetricsServer,
    metrics_port_default,
)
from elasticdl_tpu.serving.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
    StepProfiler,
    kv_host_bytes_default,
    kv_paged_default,
    kv_shared_default,
    prefill_budget_default,
    prefill_chunk_default,
    profile_default,
    role_default,
)
from elasticdl_tpu.observability.runtime_health import (
    RuntimeHealth,
    runtime_health_default,
    stall_after_default,
)
from elasticdl_tpu.serving.hot_reload import CheckpointWatcher, ReloadError
from elasticdl_tpu.serving.telemetry import ServingTelemetry


def forensics_default():
    """EDL_FORENSICS resolves the tail-forensics plane (histogram
    exemplars + tail-based trace retention + slow-cause attribution)
    when the config leaves it unset: on unless explicitly '0' — the
    plane's cost is bounded by the bench overhead A/B."""
    return os.environ.get("EDL_FORENSICS", "1") != "0"


def serve_span_classifier(span):
    """Tail-retention verdict for replica `serve` spans (installed on
    the process recorder when forensics is on): a span that expired,
    was rejected or errored is RETAINED — and so is a completed one
    that burned most of its own deadline budget (the replica's
    deadline IS the classifier; no new config surface). Healthy serves
    are sampled."""
    if span.name != "serve":
        return None
    if span.status != "ok":
        return True
    deadline_ms = span.attrs.get("deadline_ms") or 0
    if deadline_ms and span.end is not None:
        e2e_ms = (span.end - span.start) * 1000.0
        if forensics.is_terminally_slow("ok", e2e_ms, deadline_ms):
            return True
    return False


class ServingConfig(object):
    """Server knobs. num_slots sizes the decode pool (the compiled step);
    queue_capacity bounds the admitted backlog (backpressure beyond it);
    top_k/top_p are static server-level sampling filters (per-request
    temperature/seed select greedy vs sampling).

    KV layout: kv_paged=None resolves from EDL_KV_PAGED (the drills'
    env toggle). Paged mode stores KV rows in kv_num_blocks blocks of
    kv_block_size tokens (0 blocks = the dense-equivalent budget for
    num_slots); with a fixed block budget, num_slots can then be raised
    beyond what the same bytes would buy dense slots — short requests
    pack densely instead of pinning `seq_len` stripes.

    kv_shared (paged only; None resolves from EDL_KV_SHARED, default
    on) refcounts blocks and dedupes matching prompt prefixes to one
    resident chain (copy-on-write on divergence) — N requests with the
    same system prompt pay for its cache once. draft_k > 0 (with a
    draft model handed to GenerationServer) turns each scheduler tick
    into a speculative draft-verify step committing up to draft_k + 1
    tokens, token-exact with plain decode.

    kv_host_bytes (paged only; None resolves from EDL_KV_HOST_BYTES,
    default 0 = off) bounds the host-RAM spill tier: evicted prefix
    chains demote to host buffers and revive by device upload instead
    of re-paying prefill — a cell's system-prompt working set survives
    device pressure.

    metrics_port (None resolves from EDL_METRICS_PORT; unset = OFF)
    arms the Prometheus-text /metrics exposition on a stdlib HTTP
    thread (observability/metrics.py): the closed telemetry sets, the
    latency histograms and the per-step profiler phases, scrapeable by
    anything that speaks the text format (0 = ephemeral port, for
    drills/tests). profile (None resolves from EDL_PROFILE, default
    off) arms the per-step decode profiler (engine.StepProfiler) —
    phase-split compiled steps, <5% bound serve-smoke overhead; off,
    the engine does no timing work at all."""

    def __init__(self, num_slots=4, queue_capacity=64, top_k=0,
                 top_p=1.0, checkpoint_dir="", reload_poll_secs=2.0,
                 telemetry_dir="", telemetry_flush_every=50,
                 idle_wait_secs=0.05, handler_poll_secs=0.25,
                 port=0, max_workers=64, kv_paged=None,
                 kv_block_size=16, kv_num_blocks=0, kv_shared=None,
                 draft_k=0, kv_host_bytes=None, metrics_port=None,
                 profile=None, forensics=None, runtime_health=None,
                 stall_after_secs=None, health_reconcile_secs=2.0,
                 health_dir=None, role=None, prefill_chunk_tokens=None,
                 prefill_budget_ms=None):
        self.num_slots = int(num_slots)
        self.queue_capacity = int(queue_capacity)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.checkpoint_dir = checkpoint_dir
        self.reload_poll_secs = float(reload_poll_secs)
        self.telemetry_dir = telemetry_dir
        self.telemetry_flush_every = int(telemetry_flush_every)
        self.idle_wait_secs = float(idle_wait_secs)
        self.handler_poll_secs = float(handler_poll_secs)
        self.port = int(port)
        self.max_workers = int(max_workers)
        self.kv_paged = (
            kv_paged_default() if kv_paged is None else bool(kv_paged)
        )
        self.kv_block_size = int(kv_block_size)
        self.kv_num_blocks = int(kv_num_blocks)
        self.kv_shared = (
            kv_shared_default() if kv_shared is None
            else bool(kv_shared)
        )
        self.draft_k = int(draft_k)
        self.kv_host_bytes = (
            kv_host_bytes_default() if kv_host_bytes is None
            else int(kv_host_bytes)
        )
        self.metrics_port = (
            metrics_port_default() if metrics_port is None
            else int(metrics_port)
        )
        self.profile = (
            profile_default() if profile is None else bool(profile)
        )
        # the tail-forensics plane (None resolves from EDL_FORENSICS,
        # default on): histogram exemplars at the latency record
        # sites, the serve-span tail-retention classifier, and
        # slow-cause attribution into the slow_cause counter family —
        # one switch so the bench overhead A/B can price all of it
        self.forensics = (
            forensics_default() if forensics is None
            else bool(forensics)
        )
        # the runtime health plane (observability/runtime_health.py;
        # None resolves from EDL_RUNTIME_HEALTH, default on): the
        # recompile sentry on every engine/pool/decode jit site, the
        # device-memory ledger reconciliation, and the progress
        # watchdog + flight recorder behind ServerStatus
        # health_state/last_progress_age_ms — one switch so the bench
        # overhead A/B can price all three layers together
        self.runtime_health = (
            runtime_health_default() if runtime_health is None
            else bool(runtime_health)
        )
        # watchdog budget: work seated but no progress (tokens OR jit
        # compiles) for this long = stalled (None -> EDL_STALL_AFTER_
        # SECS -> 10 s: far above a healthy step, far below the 30 s
        # lease heuristic the self-report exists to beat)
        self.stall_after_secs = (
            stall_after_default() if stall_after_secs is None
            else float(stall_after_secs)
        )
        self.health_reconcile_secs = float(health_reconcile_secs)
        # bundle directory (None resolves from EDL_HEALTH_DIR; "" =
        # advertise-only: stalls count and self-report, no dump)
        self.health_dir = health_dir
        # disaggregated serving (serving/disagg.py). role (None
        # resolves from EDL_SERVING_ROLE, default "unified") is the
        # replica's advertised phase: a router keeps "prefill"
        # replicas out of normal rotation and targets them only for
        # cache-warming handoffs. prefill_chunk_tokens (None resolves
        # from EDL_PREFILL_CHUNK_TOKENS, 0 = off; paged only) splits
        # prompt prefill into fixed-token tiles the scheduler
        # interleaves with decode ticks; prefill_budget_ms (None
        # resolves from EDL_PREFILL_BUDGET_MS, default 8.0, <= 0 =
        # unbounded) caps the tile time one tick may spend while
        # decode slots are waiting — at least one tile always runs.
        self.role = role_default() if role is None else str(role)
        if self.role not in ("prefill", "decode", "unified"):
            raise ValueError(
                "role must be prefill|decode|unified, got %r"
                % (self.role,)
            )
        self.prefill_chunk_tokens = (
            prefill_chunk_default() if prefill_chunk_tokens is None
            else int(prefill_chunk_tokens)
        )
        self.prefill_budget_ms = (
            prefill_budget_default() if prefill_budget_ms is None
            else float(prefill_budget_ms)
        )


class _Scheduler(threading.Thread):
    """The continuous-batching loop. Each iteration: reload params if a
    newer checkpoint landed, evict expired sequences, seat queued
    prompts into free slots (prefill), run ONE pooled decode step, push
    the produced tokens. Idle (no active slots) it parks on the queue's
    condition with a short timeout so reload polling stays live."""

    def __init__(self, engine, queue, telemetry, watcher=None,
                 idle_wait_secs=0.05, clock=time.monotonic,
                 forensics_on=True, injector=None, health=None,
                 prefill_budget_ms=0.0):
        super().__init__(daemon=True, name="serving-scheduler")
        self.engine = engine
        self.queue = queue
        self.telemetry = telemetry
        self.watcher = watcher
        self.idle_wait_secs = idle_wait_secs
        # chunked prefill (paged engine only): seated-but-prefilling
        # jobs advance one tile per visit, budgeted per tick while
        # decode slots are waiting (engine.prefill_chunk_tokens = 0 or
        # a dense engine keeps the monolithic insert path)
        self._chunked = bool(getattr(engine, "prefill_chunk_tokens", 0)
                             and hasattr(engine, "begin_insert"))
        self.prefill_budget_ms = float(prefill_budget_ms)
        self._pending_prefills = []
        self._tile_ms = 0.0  # EWMA tile cost; prices the budget check
        # scheduler-thread work submitted by gRPC handlers (chain
        # export/import touch the jax pool, and ALL jax work belongs
        # to this thread); submit_job blocks with a liveness bound
        self._jobs = collections.deque()
        # runtime-health plane (RuntimeHealth or None): the loop feeds
        # its flight ring one snapshot per decode tick
        self.health = health
        # the engine_step fault hook (HEALTH_RPCS): drills inject a
        # scheduler stall (delay) or a dropped tick exactly here —
        # the choke point every decode tick passes through
        self._injector = injector
        # slow-cause attribution at terminal paths (forensics plane)
        self.forensics_on = bool(forensics_on)
        self._clock = clock
        self._stop_requested = threading.Event()
        self._drain = True
        self.crashed = None
        # drain advertisement (ServerStatus.draining): a router takes a
        # draining replica out of rotation for NEW requests while
        # in-flight streams finish. Two independent sources, tracked
        # SEPARATELY so they cannot clobber each other: _stopping is
        # set for good on SIGTERM drain, _reloading only spans a
        # hot-reload swap — a reload finishing while stop() lands
        # concurrently must not clear the permanent advertisement.
        self._stopping = threading.Event()
        self._reloading = threading.Event()

    def is_draining(self):
        return self._stopping.is_set() or self._reloading.is_set()

    def run(self):
        try:
            while not self._stop_requested.is_set():
                self._iterate()
            self._shutdown()
        except BaseException as e:  # noqa: BLE001 - surfaced to handlers
            self.crashed = e
            logger.error("serving scheduler crashed: %r", e)
            self._abort_all("RESOURCE_EXHAUSTED",
                            "scheduler crashed: %r" % (e,))

    def submit_job(self, fn, timeout=30.0):
        """Run `fn` on the scheduler thread and return its result (or
        re-raise its exception). Called from gRPC handler threads for
        work that must serialize with the decode loop — chain export/
        import mutate the jax pool. Liveness-bounded like _events: a
        dead scheduler turns into a clean error, never a hang."""
        done = threading.Event()
        cell = {}

        def job():
            try:
                cell["result"] = fn()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                cell["error"] = e
            done.set()

        self._jobs.append(job)
        self.queue.wake()
        deadline = self._clock() + timeout
        while not done.wait(0.05):
            if self.crashed is not None or not self.is_alive():
                raise AdmissionError(
                    "RESOURCE_EXHAUSTED",
                    "serving scheduler is not running",
                )
            if self._clock() > deadline:
                raise AdmissionError(
                    "DEADLINE_EXCEEDED", "scheduler job timed out"
                )
        if "error" in cell:
            raise cell["error"]
        return cell["result"]

    def _run_jobs(self):
        while self._jobs:
            self._jobs.popleft()()

    def reload_to(self, version):
        """Explicit checkpoint swap (the rollout controller's
        reload_checkpoint handshake). MUST run on the scheduler thread
        — handlers reach it through submit_job — because set_params is
        jax work that serializes with the decode loop. Unlike the poll
        path this accepts any target version, older included (that is
        what a rollback is). Raises ReloadError with the old params
        still serving when the watcher's retry ladder is exhausted.
        Returns the version now serving."""
        if self.watcher is None:
            raise ReloadError("no checkpoint watcher configured")
        # same flag discipline as the poll reload: only the reload's
        # OWN transient flag clears, so a SIGTERM drain that starts
        # mid-swap stays advertised
        self._reloading.set()
        try:
            got = self.watcher.load_version(version)
            if got is not None:
                state, ver = got
                self.engine.set_params(state, ver)
                self.telemetry.count("reloads")
        finally:
            self._reloading.clear()
        return int(self.engine.model_version)

    def _iterate(self):
        self._run_jobs()
        if self.watcher is not None:
            reloaded = self.watcher.poll()
            if reloaded is not None:
                state, version = reloaded
                # advertise draining across the swap so routers route
                # new work elsewhere while the reload applies; only the
                # reload's OWN flag clears, so a SIGTERM drain that
                # starts mid-swap stays advertised
                self._reloading.set()
                try:
                    self.engine.set_params(state, version)
                    self.telemetry.count("reloads")
                finally:
                    self._reloading.clear()
        now = self._clock()
        for req in self.engine.evict_expired(now):
            self.telemetry.count("expired")
            req.trace_event("expired", where="mid-decode")
            req.finish_span("DEADLINE_EXCEEDED")
            self._count_slow(req)
            req.push(("error", "DEADLINE_EXCEEDED",
                      "deadline expired mid-decode"))
        self._fill_slots()
        self._advance_prefills()
        if self.engine.active_count():
            if self._injector is not None:
                # the stall drill's injection point: a delay rule
                # wedges THIS thread mid-loop (work stays seated, no
                # tokens commit — exactly the failure the watchdog
                # must catch from its own thread); a drop rule skips
                # one tick
                try:
                    self._injector.intercept("engine_step")
                except InjectedRpcError:
                    return
            t0 = self._clock()
            results = self.engine.step()
            dt = self._clock() - t0
            committed = 0
            for _slot, req, tokens, finished in results:
                req.push(("tokens", list(tokens), req.model_version))
                committed += len(tokens)
                if finished:
                    self._complete(req)
            kv = self.engine.kv_stats()
            self.telemetry.record_step(
                len(self.queue), len(results), dt, committed,
                kv_bytes_in_use=kv["kv_bytes_in_use"],
                kv_blocks_free=kv["kv_blocks_free"],
                kv_host_blocks=kv.get("kv_host_blocks"),
                kv_host_bytes=kv.get("kv_host_bytes"),
            )
            if self.health is not None:
                self.health.record_tick(
                    len(self.queue), len(results), dt, committed
                )
        elif not self._pending_prefills:
            self.queue.wait_for_work(self.idle_wait_secs)
        # pending prefills and no decode: loop again immediately —
        # the next tick runs another budget's worth of tiles and
        # still polls admission between them

    def _advance_prefills(self):
        """Run pending chunked-prefill tiles, round-robin, under the
        per-tick budget. The budget bites only while decode slots are
        waiting (that is the latency being protected); at least one
        tile always runs, so prefill can never starve. Tile cost is
        priced by an EWMA of measured tile time — the same number the
        profiler's prefill_tile phase exports when armed."""
        budget = self.prefill_budget_ms
        spent, ran = 0.0, 0
        while self._pending_prefills:
            job = self._pending_prefills[0]
            req = job.request
            if req.expired(self._clock()):
                self._pending_prefills.pop(0)
                self.engine.abort_prefill(job)
                self.telemetry.count("expired")
                req.trace_event("expired", where="mid-prefill",
                                tiles=job.tiles)
                req.finish_span("DEADLINE_EXCEEDED")
                self._count_slow(req)
                req.push(("error", "DEADLINE_EXCEEDED",
                          "deadline expired mid-prefill"))
                continue
            if (ran and budget > 0.0 and self.engine.active_count()
                    and spent + self._tile_ms > budget):
                break
            t0 = self._clock()
            finished = self.engine.advance_prefill(job)
            dt_ms = (self._clock() - t0) * 1000.0
            # the tile held the scheduler: same busy clock insert()
            # advances, so prefill_blocked_by_other attribution and
            # the chunked A/B read one ledger
            self.engine.prefill_busy_ms = (
                getattr(self.engine, "prefill_busy_ms", 0.0) + dt_ms
            )
            spent += dt_ms
            self._tile_ms = (
                0.8 * self._tile_ms + 0.2 * dt_ms
                if self._tile_ms else dt_ms
            )
            ran += 1
            # rotate for fairness: concurrent prompts share the budget
            self._pending_prefills.append(self._pending_prefills.pop(0))
            if finished:
                self._pending_prefills.remove(job)
                self._first_token(job)

    def _first_token(self, job):
        """Prefill-completion bookkeeping shared by the monolithic and
        chunked paths: TTFT record, first-token push, and terminal
        completion for one-shot (max_new_tokens <= 1 / prefill-only)
        requests."""
        req = job.request
        ttft_ms = self.telemetry.record_ttft(req)
        req.trace_event("first_token", slot=job.slot,
                        ttft_ms=round(ttft_ms, 3))
        # the prefill produced this token; step() only counts the
        # decode-loop tokens
        self.telemetry.count("tokens_generated")
        req.push(("tokens", [job.first], req.model_version))
        if job.finished:
            self._complete(req)

    def _complete(self, req):
        """Terminal success bookkeeping: completion counter, e2e
        histogram, span seal, done event — one definition for the
        decode loop, the prefill-only fast path and the drain loop."""
        self.telemetry.count("completed")
        self.telemetry.record_e2e(
            (self._clock() - req.submitted_at) * 1000.0,
            trace_id=req.trace_id,
        )
        req.trace_event("completed", tokens=len(req.generated))
        req.finish_span("ok")
        self._count_slow(req)
        req.push(("done", req.model_version))

    def _count_slow(self, req):
        """Attribute one TERMINALLY-SLOW request (deadline breach, or
        a completion that burned most of its own deadline budget) to
        its dominant cause and bump the closed slow_cause counter
        family — the scrapeable distribution of WHY, next to the
        expired/completed that."""
        if not self.forensics_on:
            return
        span = req.span
        if span is None or span.end is None:
            return
        deadline_ms = (
            (req.deadline - req.submitted_at) * 1000.0
            if req.deadline is not None else 0.0
        )
        e2e_ms = (span.end - span.start) * 1000.0
        if not forensics.is_terminally_slow(
                span.status, e2e_ms, deadline_ms):
            return
        verdict = forensics.attribute([span.to_dict()])
        if verdict["dominant_cause"]:
            self.telemetry.count_slow_cause(verdict["dominant_cause"])

    def _blocked_ms(self, req):
        """Wall ms other requests' prefills held the scheduler while
        `req` waited: the engine's cumulative prefill-busy clock now
        minus its value when the servicer admitted the request. The
        forensics `prefill_blocked_by_other` component."""
        stamp = getattr(req, "prefill_busy_at_queued", None)
        if stamp is None:
            return 0.0
        busy = getattr(self.engine, "prefill_busy_ms", 0.0)
        return max(0.0, busy - stamp)

    def _fill_slots(self):
        while self.engine.free_slots():
            # the fit predicate is the paged pool's block budget: a
            # head-of-line request that cannot seat yet stays queued
            # (backpressure), and completions free the blocks it waits
            # for — out-of-blocks is never an insert-time crash
            req, expired = self.queue.pop_ready(fit=self.engine.can_seat)
            for e in expired:
                self.telemetry.count("expired")
                e.trace_event("expired", where="queued",
                              prefill_blocked_ms=round(
                                  self._blocked_ms(e), 3))
                e.finish_span("DEADLINE_EXCEEDED")
                self._count_slow(e)
                e.push(("error", "DEADLINE_EXCEEDED",
                        "deadline expired while queued"))
            if req is None:
                break
            req.seated_at = self._clock()
            wait_ms = self.telemetry.record_queue_wait(
                req.queue_wait_secs(), trace_id=req.trace_id
            )
            # the windowed prefix-hit-rate's denominator: EVERY prompt
            # token seated (the engine counts the prefix_hit_tokens
            # numerator — the ones seated without prefill compute)
            self.telemetry.count("prompt_tokens", len(req.prompt))
            req.trace_event("seated", queue_wait_ms=round(wait_ms, 3),
                            prefill_blocked_ms=round(
                                self._blocked_ms(req), 3))
            t0 = self._clock()
            if self._chunked:
                job = self.engine.begin_insert(req)
            else:
                job = None
                slot, first, finished = self.engine.insert(req)
            # advance the prefill-busy clock (insert = this request's
            # prefill / suffix tile / draft prefill on this thread);
            # getattr keeps bare test/bench engines valid
            self.engine.prefill_busy_ms = (
                getattr(self.engine, "prefill_busy_ms", 0.0)
                + (self._clock() - t0) * 1000.0
            )
            if job is not None:
                # chunked admission: a short/fully-shared prompt
                # completes inside begin_insert; a long one queues
                # for tile-at-a-time advancement between decode ticks
                if job.done():
                    self._first_token(job)
                else:
                    self._pending_prefills.append(job)
                continue
            ttft_ms = self.telemetry.record_ttft(req)
            req.trace_event("first_token", slot=slot,
                            ttft_ms=round(ttft_ms, 3))
            # the prefill produced this token; step() only counts the
            # decode-loop tokens
            self.telemetry.count("tokens_generated")
            req.push(("tokens", [first], req.model_version))
            if finished:
                self._complete(req)

    def _shutdown(self):
        """Graceful stop: reject the queued backlog immediately; with
        drain=True finish the in-flight slots first (they hold real
        compute progress), else abort them too. Either way every request
        terminates with done or a clean error — never silence."""
        for req in self.queue.close():
            self.telemetry.count("rejected")
            req.trace_event("rejected", why="shutdown")
            req.finish_span("RESOURCE_EXHAUSTED")
            req.push(("error", "RESOURCE_EXHAUSTED",
                      "server shutting down"))
        if not self._drain:
            self._abort_all("RESOURCE_EXHAUSTED", "server shutting down")
            return
        while self.engine.active_count() or self._pending_prefills:
            now = self._clock()
            for req in self.engine.evict_expired(now):
                self.telemetry.count("expired")
                req.trace_event("expired", where="mid-decode")
                req.finish_span("DEADLINE_EXCEEDED")
                self._count_slow(req)
                req.push(("error", "DEADLINE_EXCEEDED",
                          "deadline expired mid-decode"))
            # mid-prefill jobs hold real compute progress too: run
            # their remaining tiles (budget still paced by the loop)
            self._advance_prefills()
            if not self.engine.active_count():
                continue
            for _slot, req, tokens, finished in self.engine.step():
                req.push(("tokens", list(tokens), req.model_version))
                if finished:
                    self._complete(req)

    def _abort_all(self, code, message):
        # active_requests covers seated-but-prefilling jobs too (the
        # paged engine's override); the pending list just drops
        self._pending_prefills = []
        for req in self.engine.active_requests():
            req.finish_span(code)
            req.push(("error", code, message))
        for req in self.queue.close():
            req.finish_span(code)
            req.push(("error", code, message))

    def stop(self, drain=True):
        self._drain = drain
        self._stopping.set()  # advertise BEFORE admission closes
        self._stop_requested.set()
        self.queue.wake()  # wake the idle wait so shutdown is prompt


class ServingServicer(object):
    """gRPC handlers (proto/service.py Serving table). Works both over
    real gRPC (context aborts) and in-process (AdmissionError raised to
    the caller) — the same duality the master servicer tests use."""

    def __init__(self, queue, engine, telemetry, scheduler_alive,
                 handler_poll_secs=0.25, clock=time.monotonic,
                 draining=None, health=None, role="unified",
                 submit_job=None, watcher=None, reload_fn=None):
        self._queue = queue
        self._engine = engine
        self._telemetry = telemetry
        self._scheduler_alive = scheduler_alive
        self._poll = handler_poll_secs
        self._clock = clock
        self._draining = draining or (lambda: False)
        # runtime-health plane (RuntimeHealth or None): the status
        # RPC stamps its self-report onto ServerStatus — served from
        # gRPC threads, deliberately NOT the scheduler, so a wedged
        # scheduler can still confess
        self._health = health
        # disaggregated serving: the advertised phase role, and the
        # scheduler-thread executor for chain export/import (jax work
        # may not run on gRPC threads; None = run inline, which only
        # bare single-threaded tests use)
        self._role = role
        self._submit_job = submit_job or (lambda fn, timeout=30.0: fn())
        # explicit checkpoint handshake (serving/rollout.py): the
        # watcher is read for the reload_failed advertisement on
        # ServerStatus; reload_fn (scheduler.reload_to) runs through
        # submit_job because the swap is scheduler-thread jax work
        self._watcher = watcher
        self._reload_fn = reload_fn
        # transfer-family RPCs currently executing here; 0 after a
        # drain is the kill-drill's clean-handoff-ledger assertion
        self._transfers_inflight = 0
        self._transfer_aborts = 0
        self._transfers_lock = threading.Lock()

    # ------------------------------------------------------------- RPCs

    def generate(self, request, context=None):
        req = self._admit(request, context)
        for _chunk, _version in self._events(req, context):
            pass  # unary: accumulate; req.generated holds the tokens
        return pb.GenerateResponse(
            tokens=req.prompt + req.generated,
            model_version=req.model_version,
        )

    def generate_stream(self, request, context=None):
        req = self._admit(request, context)

        def stream():
            for chunk, version in self._events(req, context):
                yield pb.TokenChunk(
                    tokens=chunk, done=False, model_version=version
                )
            yield pb.TokenChunk(
                tokens=[], done=True, model_version=req.model_version
            )

        return stream()

    def export_chain(self, request, context=None):
        """Disaggregated handoff, exporter side: gather the prompt's
        resident chain (int8 rows + scale leaves, the same tree-
        generic gather the host spill tier reads through) into a dense
        TransferChainRequest the decode side imports verbatim. Holds
        NO references — exported chains park refcount-0 cached, so a
        crash mid-transfer leaks nothing (abort_transfer is the
        coordinator's accounting obligation, not a resource release)."""
        from elasticdl_tpu.serving import disagg

        kv = getattr(self._engine, "kv", None)
        alloc = getattr(kv, "allocator", None)
        if alloc is None or not alloc.share_prefix:
            self._fail(context, "FAILED_PRECONDITION",
                       "chain export needs the shared paged pool")
        prompt = list(request.prompt)
        with self._transfers_lock:
            self._transfers_inflight += 1
        try:
            chain, dtypes = self._submit_job(
                lambda: (kv.export_chain(prompt), kv.leaf_dtypes())
            )
            if not chain:
                self._fail(context, "NOT_FOUND",
                           "no resident chain for prompt")
            return disagg.chain_to_proto(
                chain, kv.block_size, dtypes, request.transfer_id
            )
        finally:
            with self._transfers_lock:
                self._transfers_inflight -= 1

    def transfer_chain(self, request, context=None):
        """Disaggregated handoff, importer side: one batched upload of
        the payload's blocks into fresh pool blocks, re-keyed into the
        content-addressed trie — the next generate with this prompt
        seats by prefix hit, exactly as if the chain were computed
        here. The response reports the chain's RESOLVED coverage on
        this pool (imported + already-resident levels): a fully
        deduped import is a success — the chain is warm either way —
        so blocks=0 means only that nothing of the chain landed
        (pool exhausted). Layout mismatches come back ok=False (the
        coordinator falls back to a plain dispatch), not as an RPC
        failure."""
        from elasticdl_tpu.serving import disagg

        kv = getattr(self._engine, "kv", None)
        alloc = getattr(kv, "allocator", None)
        if alloc is None or not alloc.share_prefix:
            self._fail(context, "FAILED_PRECONDITION",
                       "chain import needs the shared paged pool")
        with self._transfers_lock:
            self._transfers_inflight += 1
        try:
            blocks, dtypes = disagg.proto_to_blocks(request, kv)

            def _import_and_resolve():
                kv.import_chain(blocks, leaf_dtypes=dtypes)
                flat = [t for toks, _ in blocks for t in toks]
                return len(kv.allocator.match_prefix(flat))

            resolved = self._submit_job(_import_and_resolve)
            return pb.TransferChainResponse(
                transfer_id=request.transfer_id, ok=True,
                blocks=resolved, tokens=resolved * kv.block_size,
            )
        except AdmissionError:
            raise
        except ValueError as e:
            return pb.TransferChainResponse(
                transfer_id=request.transfer_id, ok=False,
                error=str(e),
            )
        finally:
            with self._transfers_lock:
                self._transfers_inflight -= 1

    def abort_transfer(self, request, context=None):
        """Close a failed handoff's obligation (EDL501 pairs every
        export_chain with import_chain or this). Structurally there is
        nothing to release — exports hold no references — so this is
        the failure's accounting record."""
        with self._transfers_lock:
            self._transfer_aborts += 1
        return pb.TransferChainResponse(
            transfer_id=request.transfer_id, ok=True
        )

    def reload_checkpoint(self, request, context=None):
        """Explicit checkpoint swap (the rollout controller's
        handshake): load exactly request.version — newer or older — on
        the scheduler thread, draining advertised for the duration.
        Load failures come back as a structured ok=False verdict (old
        params still serving, reload_failed latched on ServerStatus);
        only scheduler-liveness problems surface as RPC errors."""
        if self._reload_fn is None:
            self._fail(context, "FAILED_PRECONDITION",
                       "no checkpoint watcher configured")
        version = int(request.version)
        try:
            now_serving = self._submit_job(
                lambda: self._reload_fn(version), timeout=120.0
            )
        except AdmissionError:
            raise
        except Exception as e:  # noqa: BLE001 - structured verdict
            return pb.ReloadCheckpointResponse(
                ok=False,
                model_version=int(self._engine.model_version),
                error="%s" % (e,),
            )
        return pb.ReloadCheckpointResponse(
            ok=bool(now_serving == version), model_version=now_serving,
            error="" if now_serving == version else
            "serving version-%d after reload" % now_serving,
        )

    def server_status(self, request, context=None):
        snap = self._telemetry.snapshot()
        kv = self._engine.kv_stats()
        with self._transfers_lock:
            transfer_aborts = self._transfer_aborts
            transfers_inflight = self._transfers_inflight
        return pb.ServerStatusResponse(
            queue_depth=len(self._queue),
            active_slots=self._engine.active_count(),
            num_slots=self._engine.num_slots,
            model_version=self._engine.model_version,
            admitted=snap["admitted"],
            rejected=snap["rejected"],
            expired=snap["expired"],
            completed=snap["completed"],
            tokens_generated=snap["tokens_generated"],
            reloads=snap["reloads"],
            uptime_secs=snap["uptime_secs"],
            max_active_slots=snap["max_active_slots"],
            kv_paged=kv["kv_paged"],
            kv_shared=kv["kv_shared"],
            kv_cache_dtype=kv["kv_cache_dtype"],
            kv_block_size=kv["kv_block_size"],
            kv_blocks_total=kv["kv_blocks_total"],
            kv_blocks_free=kv["kv_blocks_free"],
            kv_blocks_cached=kv["kv_blocks_cached"],
            kv_blocks_shared=kv["kv_blocks_shared"],
            kv_bytes_total=kv["kv_bytes_total"],
            kv_bytes_in_use=kv["kv_bytes_in_use"],
            kv_bytes_in_use_peak=snap["kv_bytes_in_use_peak"],
            kv_bytes_per_token=snap["kv_bytes_per_token"],
            prefix_hit_tokens=kv["prefix_hit_tokens"],
            cow_copies=kv["cow_copies"],
            # tiered host spill: occupancy gauges + the monotone
            # revival economy (tokens seated by upload instead of
            # re-prefill) — .get so bare test engines stay valid
            kv_host_blocks=kv.get("kv_host_blocks", 0),
            kv_host_bytes=kv.get("kv_host_bytes", 0),
            revive_uploads=kv.get("revive_uploads", 0),
            prefill_tokens_revived=kv.get("prefill_tokens_revived", 0),
            host_drops=kv.get("host_drops", 0),
            draft_k=self._engine.draft_k,
            draft_proposed=self._engine.draft_proposed,
            draft_accepted=self._engine.draft_accepted,
            draining=self._draining(),
            queue_wait_ms=snap["queue_wait_ms"],
            # windowed warm-capacity signal (time-series ring): prompt
            # tokens seated without prefill compute over the trailing
            # horizon / all prompt tokens seated
            prefix_hit_rate_window=snap["prefix_hit_rate_window"],
            # percentiles + raw mergeable buckets from the shared
            # log-linear histograms (observability/histogram.py)
            ttft_p50_ms=snap["ttft_p50_ms"],
            ttft_p90_ms=snap["ttft_p90_ms"],
            ttft_p99_ms=snap["ttft_p99_ms"],
            queue_wait_p50_ms=snap["queue_wait_p50_ms"],
            queue_wait_p90_ms=snap["queue_wait_p90_ms"],
            queue_wait_p99_ms=snap["queue_wait_p99_ms"],
            ttft_hist=snap["ttft_hist"],
            queue_wait_hist=snap["queue_wait_hist"],
            # terminally-slow requests by dominant attributed cause,
            # aligned with ServingTelemetry.SLOW_CAUSES declared order
            slow_cause_counts=snap["slow_cause_counts"],
            # disaggregated serving: the advertised phase role plus
            # the handoff ledger (pool-side chain counters, the
            # transfer RPCs executing right now, and closed-out
            # failures) — .get so bare/dense engines stay valid
            role=self._role,
            chain_exports=kv.get("chain_exports", 0),
            chain_imports=kv.get("chain_imports", 0),
            chain_import_tokens=kv.get("chain_import_tokens", 0),
            transfer_aborts=transfer_aborts,
            transfers_inflight=transfers_inflight,
            # hot-reload failure latch: the watcher exhausted its retry
            # ladder — old params still serving, error carried verbatim
            reload_failed=(
                bool(self._watcher.reload_failed) if self._watcher
                else False
            ),
            reload_error=(
                self._watcher.last_error if self._watcher else ""
            ),
            # runtime health self-report (observability/
            # runtime_health.py); all-zero/"" with the plane off —
            # the wire signal routers/autoscalers key the fallback on
            **self._health_fields(),
        )

    def _health_fields(self):
        if self._health is None:
            return {}
        # a status read is also a watchdog evaluation: detection
        # cannot lag the poll that would have reported it
        self._health.check()
        h = self._health.snapshot()
        return {
            "last_progress_age_ms": h["last_progress_age_ms"],
            "health_state": h["health_state"],
            "jit_compiles": h["jit_compiles"],
            "steady_recompiles": h["steady_recompiles"],
            "memory_unaccounted_bytes":
                h["memory_unaccounted_bytes"],
        }

    # --------------------------------------------------------- internals

    def _admit(self, proto_req, context):
        req = ServingRequest(
            prompt=list(proto_req.prompt),
            max_new_tokens=proto_req.max_new_tokens,
            temperature=proto_req.temperature,
            seed=proto_req.seed,
            deadline_ms=proto_req.deadline_ms,
            trace_id=getattr(proto_req, "trace_id", ""),
            parent_span_id=getattr(proto_req, "parent_span_id", ""),
            prefill_only=getattr(proto_req, "prefill_only", False),
        )
        # the serve span: parented under the caller's dispatch span
        # when the RPC carried trace context (router/traced client),
        # a fresh root trace otherwise — either way THIS is where a
        # request's causal record on the replica begins
        req.span = recorder().start_span(
            "serve",
            trace_id=req.trace_id or None,
            parent_span_id=req.parent_span_id,
            request_id=req.request_id,
            prompt_len=len(req.prompt),
            max_new_tokens=req.max_new_tokens,
            # the tail-retention classifier and forensics read the
            # request's OWN deadline budget off the span
            deadline_ms=int(proto_req.deadline_ms or 0),
        )
        req.trace_id = req.span.trace_id
        # stamp the engine's cumulative prefill-busy clock: seating
        # reads it back to report how long OTHER requests' prefills
        # held the scheduler while this one queued (forensics:
        # prefill_blocked_by_other)
        req.prefill_busy_at_queued = getattr(
            self._engine, "prefill_busy_ms", 0.0
        )
        try:
            self._queue.submit(req)
        except AdmissionError as e:
            self._telemetry.count(
                "expired" if e.code == "DEADLINE_EXCEEDED" else "rejected"
            )
            req.trace_event(
                "expired" if e.code == "DEADLINE_EXCEEDED"
                else "rejected", why=str(e),
            )
            req.finish_span(e.code)
            self._fail(context, e.code, str(e))
        req.trace_event("queued", queue_depth=len(self._queue))
        self._telemetry.count("admitted")
        return req

    def _events(self, req, context):
        """Yield ("tokens" chunks, version) until done; terminate with a
        clean status on error/expiry/scheduler loss. The timeout'd wait
        is the no-hang backstop: even if the scheduler vanishes without
        pushing a terminal event, the handler notices within one poll."""
        while True:
            ev = req.next_event(timeout=self._poll)
            if ev is None:
                now = self._clock()
                if req.expired(now):
                    # backstop only: the scheduler normally evicts and
                    # counts the expiry before this wait times out
                    self._fail(context, "DEADLINE_EXCEEDED",
                               "deadline expired")
                if not self._scheduler_alive():
                    self._fail(context, "RESOURCE_EXHAUSTED",
                               "serving scheduler is not running")
                continue
            kind = ev[0]
            if kind == "tokens":
                yield ev[1], ev[2]
            elif kind == "done":
                return
            else:  # ("error", code, message)
                self._fail(context, ev[1], ev[2])

    def _fail(self, context, code_name, message):
        if context is not None:
            import grpc

            context.abort(
                getattr(grpc.StatusCode, code_name,
                        grpc.StatusCode.UNKNOWN),
                message,
            )
        raise AdmissionError(code_name, message)


class GenerationServer(object):
    """Owns the engine, queue, scheduler thread and (optionally) the
    gRPC server. start(grpc_server=False) runs everything in-process —
    the servicer is callable directly, which is what the unit tests and
    the in-process bench mode use."""

    def __init__(self, trainer, state, config=None, injector=None,
                 draft=None):
        self.config = config or ServingConfig()
        cfg = self.config
        if cfg.kv_paged:
            self.engine = PagedContinuousBatchingEngine(
                trainer, state, cfg.num_slots,
                top_k=cfg.top_k, top_p=cfg.top_p,
                block_size=cfg.kv_block_size,
                num_blocks=cfg.kv_num_blocks,
                share_prefix=cfg.kv_shared,
                draft=draft, draft_k=cfg.draft_k,
                host_bytes=cfg.kv_host_bytes,
                prefill_chunk_tokens=cfg.prefill_chunk_tokens,
            )
        else:
            if draft is not None and cfg.draft_k:
                raise ValueError(
                    "speculative decode needs the paged pool "
                    "(kv_paged=True) — the reclaimed blocks are what "
                    "seat the draft"
                )
            self.engine = ContinuousBatchingEngine(
                trainer, state, cfg.num_slots,
                top_k=cfg.top_k, top_p=cfg.top_p,
            )
        self.queue = RequestQueue(
            cfg.queue_capacity, self.engine.seq_len,
            max_cached_tokens=self.engine.max_cached_tokens(),
        )
        self.telemetry = ServingTelemetry(
            log_dir=cfg.telemetry_dir or None,
            flush_every=cfg.telemetry_flush_every,
            exemplars=cfg.forensics,
        )
        if cfg.forensics:
            # tail-based trace retention: slow/failed serve spans
            # survive ring pressure (idempotent per function object)
            recorder().add_classifier(serve_span_classifier)
        # the engine reports the events only it can see (prefix hits,
        # CoW faults, draft accepts) through the same closed counters
        self.engine.telemetry = self.telemetry
        # per-step decode profiler (phase-split compiled steps); the
        # paged engine forwards it to the KV pool for revive timing
        if cfg.profile:
            self.engine.profiler = StepProfiler()
        # one injector serves the servicer wrapper AND the health/
        # scheduler hooks, so a single EDL_FAULT_SPEC drives a drill
        # end-to-end (rule state is shared, as it must be)
        self._injector = injector or FaultInjector.from_env()
        # the runtime health plane (observability/runtime_health.py):
        # recompile sentry adopted by the engine (which forwards it to
        # the paged pool and the offline decode caches), device-memory
        # ledger reconciliation, progress watchdog + flight recorder —
        # driven by its OWN daemon thread, because the scheduler being
        # wedged is the failure under observation
        self.health = None
        if cfg.runtime_health:
            self.health = RuntimeHealth(
                self.engine, self.queue, self.telemetry,
                stall_after_secs=cfg.stall_after_secs,
                reconcile_secs=cfg.health_reconcile_secs,
                health_dir=cfg.health_dir,
                injector=self._injector,
            )
            self.engine.sentry = self.health.sentry
            # the dense engine carries a plain attribute (no property
            # forwarding), so the offline decode caches adopt here
            from elasticdl_tpu.api.generation import set_decode_sentry

            set_decode_sentry(self.health.sentry)
        watcher = None
        if cfg.checkpoint_dir:
            watcher = CheckpointWatcher(
                cfg.checkpoint_dir, state,
                poll_secs=cfg.reload_poll_secs,
                start_version=self.engine.model_version,
                injector=self._injector,
            )
        self.watcher = watcher
        self.scheduler = _Scheduler(
            self.engine, self.queue, self.telemetry, watcher=watcher,
            idle_wait_secs=cfg.idle_wait_secs,
            forensics_on=cfg.forensics,
            injector=self._injector, health=self.health,
            prefill_budget_ms=cfg.prefill_budget_ms,
        )
        servicer = ServingServicer(
            self.queue, self.engine, self.telemetry,
            scheduler_alive=self.scheduler.is_alive,
            handler_poll_secs=cfg.handler_poll_secs,
            draining=self.scheduler.is_draining,
            health=self.health,
            role=cfg.role,
            submit_job=self.scheduler.submit_job,
            watcher=watcher,
            reload_fn=self.scheduler.reload_to if watcher else None,
        )
        # the unwrapped servicer: in-process warmup (serving/main.py
        # --warmup_tokens) goes through it so a warmup request can
        # never consume an armed fault rule meant for real traffic
        self.raw_servicer = servicer
        # EDL_FAULT_SPEC (or an explicit injector) arms drop/error/
        # delay/kill at the RPC boundary, exactly like the master
        self.servicer = maybe_wrap_servicer(
            servicer, self._injector, rpcs=SERVING_RPCS
        )
        self._server = None
        self.port = None
        self.metrics = None  # MetricsServer when cfg.metrics_port set

    def _metrics_families(self):
        """One replica scrape: the closed telemetry sets + latency
        histograms, plus the profiler's phase histogram when armed
        (called on the exposition HTTP thread; each collector locks
        itself)."""
        fams = self.telemetry.prometheus()
        if self.engine.profiler is not None:
            fams.extend(self.engine.profiler.prometheus())
        if self.health is not None:
            # the per-fn recompile family (the scalar health gauges/
            # counters already ride the closed telemetry sets)
            fams.extend(self.health.prometheus())
        return fams

    def mark_steady(self):
        """Declare warmup over (runtime health): recompiles become
        counted anomalies and the memory baseline re-anchors. No-op
        with the plane off — warmup call sites never need to care."""
        if self.health is not None:
            self.health.mark_steady()

    def start(self, grpc_server=True):
        self.scheduler.start()
        if self.health is not None:
            self.health.start()
        if self.config.metrics_port is not None:
            self.metrics = MetricsServer(
                self._metrics_families, port=self.config.metrics_port
            )
            logger.info(
                "Serving /metrics exposition on port %d",
                self.metrics.port,
            )
        if grpc_server:
            from elasticdl_tpu.proto.service import (
                add_serving_servicer_to_server,
                build_server,
            )

            server = build_server(
                futures.ThreadPoolExecutor(
                    max_workers=self.config.max_workers
                )
            )
            add_serving_servicer_to_server(self.servicer, server)
            self.port = server.add_insecure_port(
                "[::]:%d" % self.config.port
            )
            server.start()
            self._server = server
            logger.info(
                "Serving gRPC server started on port %d (slots=%d, "
                "queue=%d)", self.port, self.config.num_slots,
                self.config.queue_capacity,
            )
        return self

    def stop(self, drain=True, grace=5.0):
        """Graceful: stop admission, drain (or abort) in-flight work,
        then stop the transport. Safe to call twice."""
        self.scheduler.stop(drain=drain)
        self.scheduler.join(timeout=60.0)
        if self.health is not None:
            self.health.stop()
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        if self.metrics is not None:
            self.metrics.close()
            self.metrics = None
        self.telemetry.close()
        # export this process's span ring when EDL_TRACE_DIR is set
        # (no-op otherwise) — the dump tool merges per-process files
        recorder().flush()
