"""Hot checkpoint reload: follow the training run's checkpoint dir.

The trainer's CheckpointSaver writes versioned sharded checkpoints
(checkpoint/saver.py `version-<V>/variables-*-of-M.ckpt`, atomic via
temp-dir rename, valid iff the M-file set is complete). The watcher
polls for a NEWER valid version than the one serving, loads it on the
scheduler thread, and rebuilds the params against the server's own
state template (re-sharded to the serving mesh by device_put — the
shard count at save time is irrelevant, same property the elastic
trainer restore relies on).

The swap itself is just engine.set_params between two decode steps:
in-flight requests keep their KV caches and positions, and their
remaining tokens come from the new weights. That is the intended
semantics — a mid-stream request observes a version bump exactly like a
request whose prompt straddled a training checkpoint boundary, and the
response carries the version that produced its last token. Requests
never drop: nothing about the pool changes shape.

Failure isolation: a checkpoint that fails to load (torn write beaten
by the validity check, architecture drift, ...) logs and keeps serving
the current params; the watcher retries on the next poll only when a
newer version appears.
"""

import time

from elasticdl_tpu.checkpoint.saver import (
    get_latest_checkpoint_version,
    load_checkpoint,
    restore_state_from_flat,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


class CheckpointWatcher(object):
    """Poll `checkpoint_dir` for new valid versions.

    template_state: a TrainState-shaped pytree (the serving trainer's
    own init_state) that gives every leaf its dtype and sharding;
    strict=False so a dense training checkpoint can warm-start a
    serving model with extra leaves (e.g. LoRA adapters)."""

    def __init__(self, checkpoint_dir, template_state,
                 poll_secs=2.0, start_version=-1, clock=time.monotonic):
        self.checkpoint_dir = checkpoint_dir
        self.template_state = template_state
        self.poll_secs = float(poll_secs)
        self.version = int(start_version)
        self._clock = clock
        self._next_poll = 0.0
        self._failed_version = None

    def poll(self, force=False):
        """Returns (new_state, version) when a newer valid checkpoint
        loaded, else None. Rate-limited to poll_secs; `force` bypasses
        the limiter (tests, explicit reload RPCs)."""
        if not self.checkpoint_dir:
            return None
        now = self._clock()
        if not force and now < self._next_poll:
            return None
        self._next_poll = now + self.poll_secs
        latest = get_latest_checkpoint_version(self.checkpoint_dir)
        if latest <= self.version or latest == self._failed_version:
            return None
        try:
            flat, version = load_checkpoint(
                self.checkpoint_dir, version=latest
            )
            state = restore_state_from_flat(
                self.template_state, flat, strict=False
            )
        except Exception as e:  # noqa: BLE001 - keep serving on failure
            logger.error(
                "hot reload of version-%d failed (still serving "
                "version-%d): %s", latest, self.version, e,
            )
            self._failed_version = latest
            return None
        self.version = version
        self._failed_version = None
        logger.info("hot reload: serving checkpoint version-%d", version)
        return state, version
