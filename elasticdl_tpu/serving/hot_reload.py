"""Hot checkpoint reload: follow the training run's checkpoint dir.

The trainer's CheckpointSaver writes versioned sharded checkpoints
(checkpoint/saver.py `version-<V>/variables-*-of-M.ckpt`, atomic via
temp-dir rename, valid iff the M-file set is complete). The watcher
polls for a NEWER valid version than the one serving, loads it on the
scheduler thread, and rebuilds the params against the server's own
state template (re-sharded to the serving mesh by device_put — the
shard count at save time is irrelevant, same property the elastic
trainer restore relies on).

The swap itself is just engine.set_params between two decode steps:
in-flight requests keep their KV caches and positions, and their
remaining tokens come from the new weights. That is the intended
semantics — a mid-stream request observes a version bump exactly like a
request whose prompt straddled a training checkpoint boundary, and the
response carries the version that produced its last token. Requests
never drop: nothing about the pool changes shape.

Failure isolation: a checkpoint that fails integrity or load (torn
write beaten by the validity check, digest mismatch, architecture
drift, ...) MUST leave the old params serving. Each attempt retries
with backoff up to `retries` times inside the same poll; exhaustion
latches `reload_failed` (surfaced on ServerStatus so the router and the
rollout controller can see a replica that cannot take the new version)
until a load eventually succeeds. The poll path additionally remembers
the failed version so it doesn't re-chew the same bytes every tick —
only a NEWER version clears that latch.

`load_version` is the rollout controller's explicit handshake: unlike
poll it accepts any target — including an OLDER version, which is
exactly what a rollback is — and raises ReloadError on exhaustion so
the reload RPC can return a structured failure instead of a silent
no-op.
"""

import time

from elasticdl_tpu.checkpoint.saver import (
    get_latest_checkpoint_version,
    load_checkpoint,
    restore_state_from_flat,
    verify_checkpoint,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


class ReloadError(Exception):
    """All load attempts for an explicitly requested checkpoint version
    failed; the old params are still serving."""


class CheckpointWatcher(object):
    """Poll `checkpoint_dir` for new valid versions.

    template_state: a TrainState-shaped pytree (the serving trainer's
    own init_state) that gives every leaf its dtype and sharding;
    strict=False so a dense training checkpoint can warm-start a
    serving model with extra leaves (e.g. LoRA adapters).

    retries/backoff_secs: per-reload retry ladder (attempt, sleep b,
    attempt, sleep 2b, ...). injector: optional FaultInjector whose
    `checkpoint_read` hook fires before every filesystem read, so
    drills can manufacture torn/slow checkpoint stores."""

    def __init__(self, checkpoint_dir, template_state,
                 poll_secs=2.0, start_version=-1, clock=time.monotonic,
                 retries=3, backoff_secs=0.2, sleep=time.sleep,
                 injector=None):
        self.checkpoint_dir = checkpoint_dir
        self.template_state = template_state
        self.poll_secs = float(poll_secs)
        self.version = int(start_version)
        self._clock = clock
        self._sleep = sleep
        self._next_poll = 0.0
        self._failed_version = None
        self.retries = max(1, int(retries))
        self.backoff_secs = float(backoff_secs)
        self.injector = injector
        self.reload_failed = False
        self.last_error = ""

    # ------------------------------------------------------------ internals

    def _intercept(self):
        if self.injector is not None:
            self.injector.intercept("checkpoint_read")

    def _try_load(self, version):
        """One integrity-checked load attempt. Raises on any failure."""
        self._intercept()
        verify_checkpoint(self.checkpoint_dir, version)
        flat, got = load_checkpoint(self.checkpoint_dir, version=version)
        state = restore_state_from_flat(
            self.template_state, flat, strict=False
        )
        return state, got

    def _load_with_retries(self, version):
        """Retry ladder around _try_load. Returns (state, version) or
        raises the LAST error after `retries` attempts; never mutates
        self.version on failure — old params keep serving."""
        last = None
        for attempt in range(self.retries):
            try:
                out = self._try_load(version)
                self.reload_failed = False
                self.last_error = ""
                return out
            except Exception as e:  # noqa: BLE001 - keep serving
                last = e
                logger.error(
                    "checkpoint version-%d load attempt %d/%d failed "
                    "(still serving version-%d): %s",
                    version, attempt + 1, self.retries, self.version, e,
                )
                if attempt + 1 < self.retries:
                    self._sleep(self.backoff_secs * (2 ** attempt))
        self.reload_failed = True
        self.last_error = "%s: %s" % (type(last).__name__, last)
        raise last

    # ------------------------------------------------------------ public

    def poll(self, force=False):
        """Returns (new_state, version) when a newer valid checkpoint
        loaded, else None. Rate-limited to poll_secs; `force` bypasses
        the limiter (tests, explicit reload RPCs)."""
        if not self.checkpoint_dir:
            return None
        if self.poll_secs <= 0 and not force:
            # explicit-reload-only mode (--reload_poll_secs 0): a
            # rollout-managed fleet must not self-upgrade behind the
            # controller's back — or self-REVERT a rollback the moment
            # its own poll sees the (newer) version it was rolled off
            return None
        now = self._clock()
        if not force and now < self._next_poll:
            return None
        self._next_poll = now + self.poll_secs
        latest = get_latest_checkpoint_version(self.checkpoint_dir)
        if latest <= self.version or latest == self._failed_version:
            return None
        try:
            state, version = self._load_with_retries(latest)
        except Exception:  # noqa: BLE001 - keep serving on failure
            self._failed_version = latest
            return None
        self.version = version
        self._failed_version = None
        logger.info("hot reload: serving checkpoint version-%d", version)
        return state, version

    def load_version(self, version):
        """Explicitly load `version` (newer OR older — rollbacks go
        through here). Returns (state, version) on success; raises
        ReloadError after the retry ladder is exhausted, with the old
        params untouched and reload_failed latched."""
        version = int(version)
        if not self.checkpoint_dir:
            raise ReloadError("no checkpoint_dir configured")
        if version == self.version:
            return None  # already serving it — idempotent no-op
        try:
            state, got = self._load_with_retries(version)
        except Exception as e:  # noqa: BLE001 - structured failure
            raise ReloadError(
                "reload to version-%d failed after %d attempts: %s"
                % (version, self.retries, e)
            )
        self.version = got
        self._failed_version = None
        logger.info(
            "explicit reload: serving checkpoint version-%d", got
        )
        return state, got
