"""Multi-cell router tier: journaled registry sharing + cell failover.

One router process is a single point of failure in front of the whole
serving fleet: SIGKILL it and admission, dispatch, SLO accounting and
the autoscaler's eyes die together, however fault-tolerant the
replicas behind it are. This module removes that SPOF with the same
journaled-state machinery that makes the master restartable
(master/state_store.py):

``CellRegistryJournal``
    A write-ahead journal + compacted snapshot of the REPLICA
    REGISTRY, shared by every cell through one ``--cell_journal_dir``.
    Membership transitions (``adopt``/``retire``) and periodic
    ``lease`` beacons are appended write-ahead and replayed on cell
    start, so a cell that crashes — or a brand-new cell started with
    NO ``--replica`` flags — rebuilds the fleet view from disk alone.
    Cross-process safety is one ``flock`` around every append/refresh/
    compact; each cell tails the journal from its own byte offset, so
    a membership change recorded by cell 0 reaches cell 1 at its next
    heartbeat tick. Compaction (snapshot + journal truncate) happens
    at tick boundaries, like the PR 9 supervisor roster; a tailing
    cell that sees the journal shrink under its offset resyncs from
    the snapshot.

``RouterCell``
    A ``Router`` whose membership is journal-backed: local
    ``add_replica``/``remove_replica`` journal the transition, remote
    transitions arrive via ``refresh()`` at each heartbeat tick, and
    ``router_status`` grows the cell block (cell_id/cells,
    journal_events/journal_replayed/cell_restarts). The ``cell_kill``
    fault hook fires at the tick, so a chaos spec can SIGKILL a live
    cell exactly the way pod eviction would.

``CellFront``
    The thin client-side cell map: requests are consistent-hashed by
    prefix fingerprint across cells (shared-prompt traffic lands on
    ONE cell, whose affinity index then keeps it on ONE replica — the
    prefill-once-per-cell property), and a dead cell's requests walk
    the ring to the surviving cells under the common/retry.py
    classification: transient failures reroute with full-jitter
    backoff inside a bounded window, backpressure propagates (the
    registry is SHARED — every cell would shed the same fleet), and a
    stream reroutes only before its first delivered chunk. Per-cell
    circuit breakers keep a dead cell from eating a probe per request.
"""

import contextlib
import json
import os
import threading
import time

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback
    fcntl = None

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.retry import (
    RetryPolicy,
    is_backpressure_rpc_error,
    is_transient_rpc_error,
)
from elasticdl_tpu.analysis.typestate import JournalProtocol
from elasticdl_tpu.master.state_store import JOURNAL_FILE, JobStateStore
from elasticdl_tpu.serving.prefix_affinity import (
    HashRing,
    prefix_fingerprint,
)
from elasticdl_tpu.serving.router import (
    CircuitBreaker,
    Router,
    RouterError,
    _code_name,
)

#: registry lock file inside the journal dir: ONE flock serializes
#: append/refresh/compact across every cell process
REGISTRY_LOCK_FILE = ".registry.lock"

#: journal protocol declaration, verified by edl-lint EDL701-704 and
#: walked by the spec-derived crash-replay battery in tests. The
#: machine is PER ADDRESS: adopt/retire are deliberately legal from
#: EITHER state (idempotent re-adopt of a seed, retire of an address a
#: sibling cell already removed), which is what lets compaction
#: truncate mid-stream. ``lease`` is a liveness beacon — informational
#: under replay; every cell re-earns leases through its own heartbeat.
PROTOCOL = JournalProtocol(
    name="router_cell",
    kind_key="op",
    emit="record",
    replay="_apply_event",
    states=("absent", "member"),
    initial="absent",
    events={
        "adopt": {"entity_key": "address",
                  "from": ("absent", "member"), "to": "member"},
        "retire": {"entity_key": "address",
                   "from": ("absent", "member"), "to": "absent"},
        "lease": {"informational": True, "requires": ("addresses",)},
    },
    recoverable={
        "absent": "nothing to resume",
        "member": "replay re-adds the replica; the heartbeat decides "
                  "rotation",
    },
)


class CellRegistryJournal(object):
    """flock-serialized write-ahead journal of the replica registry,
    shared by the cells of one router tier through a common directory.

    Event schema (one JSON object per journal line):

        {"op": "adopt",  "address": "<addr>", "cell": <id>}
        {"op": "retire", "address": "<addr>", "cell": <id>}
        {"op": "lease",  "addresses": ["<addr>", ...], "cell": <id>}

    ``adopt``/``retire`` are the membership transitions; ``lease`` is
    a periodic liveness beacon (which addresses the recording cell saw
    in rotation) — informational under replay, since every cell runs
    its own heartbeat and re-earns leases itself. All three are
    idempotent under replay: adopt of a present address and retire of
    an absent one are no-ops, which is what lets compaction truncate
    mid-stream and crashes replay the journal against the newest
    snapshot. The snapshot is ``{"replicas": [addr, ...]}``.

    Offsets: each process tails the journal from its own byte offset
    (advanced past its OWN appends inside the same flock, so refresh
    never re-applies them). A journal shorter than the offset means
    another cell compacted — the tailer resyncs from snapshot+journal.
    """

    def __init__(self, journal_dir, cell_id=0, snapshot_every=64):
        self._dir = journal_dir
        self.cell_id = int(cell_id)
        self._store = JobStateStore(journal_dir,
                                    snapshot_every=snapshot_every)
        self._journal_path = os.path.join(journal_dir, JOURNAL_FILE)
        self._lock_path = os.path.join(journal_dir, REGISTRY_LOCK_FILE)
        # one mutex per process: the heartbeat tick and a concurrent
        # membership change must not interleave inside the flock
        self._mutex = threading.RLock()
        self._offset = 0
        self._pending_compact = False
        self._apply = None
        self._snapshot_state = None
        self.replayed = 0
        self.appends = 0
        self.resyncs = 0

    @property
    def restarts(self):
        return self._store.restart_count

    @contextlib.contextmanager
    def _flock(self):
        with self._mutex:
            f = open(self._lock_path, "a+")
            try:
                if fcntl is not None:
                    fcntl.flock(f.fileno(), fcntl.LOCK_EX)
                yield
            finally:
                if fcntl is not None:
                    fcntl.flock(f.fileno(), fcntl.LOCK_UN)
                f.close()

    def bind(self, apply_event, snapshot_state):
        """Wire the owning cell in: ``apply_event(event)`` applies one
        journal event to the registry (without re-journaling it);
        ``snapshot_state()`` returns the compaction snapshot dict."""
        self._apply = apply_event
        self._snapshot_state = snapshot_state

    # ---------------------------------------------------------- replay

    def replay(self):
        """Rebuild the registry view from disk at cell start: snapshot
        first, then every surviving journal event, in order. Returns
        the number of membership items replayed."""
        with self._flock():
            return self._resync_locked(initial=True)

    def _resync_locked(self, initial=False):
        snapshot, events = self._store.load()
        n = 0
        if snapshot:
            for addr in snapshot.get("replicas", ()):
                self._apply({"op": "adopt", "address": addr})
                n += 1
        for event in events:
            self._apply(event)
            n += 1
        self._offset = self._journal_size()
        if initial:
            self.replayed = n
        else:
            self.resyncs += 1
        return n

    def _journal_size(self):
        try:
            return os.path.getsize(self._journal_path)
        except OSError:
            return 0

    # ----------------------------------------------------------- tailing

    def refresh(self):
        """Apply every event other cells appended since our offset.
        Called at each heartbeat tick (and before every append, so a
        record can never reorder against an unseen remote event)."""
        with self._flock():
            return self._refresh_locked()

    def _refresh_locked(self):
        size = self._journal_size()
        if size < self._offset:
            # another cell compacted under us: the snapshot now owns
            # our prefix — resync the whole view (events idempotent)
            return self._resync_locked()
        if size == self._offset:
            return 0
        n = 0
        with open(self._journal_path) as f:
            f.seek(self._offset)
            for line in f.readlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    self._apply(json.loads(line))
                    n += 1
                except ValueError:
                    # torn tail mid-append by another cell: leave the
                    # offset short of it; the next refresh re-reads
                    break
            self._offset = f.tell()
        return n

    # ---------------------------------------------------------- writing

    def record(self, event):
        """Write-ahead one registry event (refresh-first under the same
        flock, so local appends serialize AFTER every remote event we
        had not yet applied)."""
        event = dict(event)
        event.setdefault("cell", self.cell_id)
        with self._flock():
            self._refresh_locked()
            if self._store.append(event):
                self._pending_compact = True
            self.appends += 1
            # our own append is already applied locally: advance past
            # it so refresh never replays it at us
            self._offset = self._journal_size()

    def compact_at_tick(self):
        """Tick-boundary compaction (the PR 9 roster discipline): when
        an append crossed the snapshot_every threshold, write the
        snapshot and truncate the journal under one flock — no event
        is lost because refresh runs first inside the same critical
        section."""
        if not self._pending_compact:
            return False
        with self._flock():
            if not self._pending_compact:
                return False
            self._refresh_locked()
            self._store.write_snapshot(self._snapshot_state())
            self._offset = 0
            self._pending_compact = False
        return True

    def close(self):
        self._store.close()


class RouterCell(Router):
    """A Router whose replica registry is journal-backed and shared
    with sibling cells. Construction order matters: the journal attrs
    exist BEFORE Router.__init__ runs (which registers the seed
    replicas through our overridden add_replica)."""

    #: journal a lease beacon every N heartbeat ticks (liveness is
    #: re-earned per cell; the beacon is forensic, not authoritative)
    LEASE_JOURNAL_EVERY = 8

    def __init__(self, replica_addrs, config=None, journal_dir=None,
                 **kwargs):
        # set before super().__init__: Router's constructor calls
        # add_replica for every seed, and the override consults these
        self._journal = None
        self._tick = 0
        self._cell_injector = None
        super(RouterCell, self).__init__(replica_addrs, config=config,
                                         **kwargs)
        if journal_dir:
            self._journal = CellRegistryJournal(
                journal_dir, cell_id=self.config.cell_id,
            )
            self._journal.bind(self._apply_event, self._snapshot_state)
            replayed = self._journal.replay()
            # seeds the journal had not seen yet become adopt events,
            # so a sibling cell started with NO --replica flags still
            # learns the full fleet
            for rep in self.replicas():
                self._journal.record(
                    {"op": "adopt", "address": rep.address}
                )
            logger.info(
                "router cell %d/%d: journal %s replayed %d items "
                "(restart #%d)", self.config.cell_id,
                self.config.cells, journal_dir, replayed,
                self._journal.restarts,
            )

    # ------------------------------------------------- journal plumbing

    def _apply_event(self, event):
        """One journal event into the registry, WITHOUT re-journaling:
        apply goes through the base-class membership calls."""
        op = event.get("op")
        addr = event.get("address")
        if op == "adopt" and addr:
            Router.add_replica(self, addr)
        elif op == "retire" and addr:
            Router.remove_replica(self, addr)
        # "lease" beacons and unknown (newer-schema) ops: forensic
        # only — every cell re-earns leases through its own heartbeat

    def _snapshot_state(self):
        return {"replicas": sorted(r.address
                                   for r in self.replicas())}

    # ------------------------------------------------------- membership

    def add_replica(self, address):
        with self._lock:
            known = address in self._replicas
        rep = Router.add_replica(self, address)
        if not known and self._journal is not None:
            self._journal.record({"op": "adopt", "address": address})
        return rep

    def remove_replica(self, address):
        rep = Router.remove_replica(self, address)
        if rep is not None and self._journal is not None:
            self._journal.record({"op": "retire", "address": address})
        return rep

    # -------------------------------------------------------- heartbeat

    def poll_once(self):
        if self._journal is not None:
            try:
                self._journal.refresh()
            except Exception as e:  # noqa: BLE001 - next tick retries
                logger.warning("cell %d journal refresh failed: %r",
                               self.config.cell_id, e)
        healthy = Router.poll_once(self)
        self._tick += 1
        if self._journal is not None:
            if self._tick % self.LEASE_JOURNAL_EVERY == 0:
                now = self._clock()
                self._journal.record({
                    "op": "lease",
                    "addresses": sorted(
                        r.address for r in self.replicas()
                        if r.in_rotation(now)
                    ),
                })
            try:
                self._journal.compact_at_tick()
            except Exception as e:  # noqa: BLE001 - next tick retries
                logger.warning("cell %d journal compact failed: %r",
                               self.config.cell_id, e)
        if self._cell_injector is not None:
            # the chaos drill's router-kill phase: a `cell_kill:kill`
            # rule SIGKILLs this very process at a tick boundary —
            # exactly the pod-eviction shape the tier must survive
            self._cell_injector.intercept("cell_kill", context=None,
                                          when="before")
        return healthy

    # -------------------------------------------------------- lifecycle

    def start(self, grpc_server=True, injector=None):
        self._cell_injector = injector
        return Router.start(self, grpc_server=grpc_server,
                            injector=injector)

    def stop(self, grace=5.0):
        Router.stop(self, grace=grace)
        if self._journal is not None:
            self._journal.close()

    # ----------------------------------------------------------- status

    def status_response(self):
        resp = Router.status_response(self)
        if self._journal is not None:
            resp.journal_events = self._journal.appends
            resp.journal_replayed = self._journal.replayed
            resp.cell_restarts = self._journal.restarts
        return resp


def _default_cell_stub_factory(address):
    from elasticdl_tpu.proto.service import RouterStub, build_channel

    channel = build_channel(address)
    stub = RouterStub(channel)
    stub.close = channel.close
    return stub


class CellFront(object):
    """Client-side cell map with consistent-hash dispatch and bounded
    reroute on cell death.

    Requests are keyed by prefix fingerprint (whole shared-prompt
    families land on one cell — whose affinity index then lands them
    on one replica) and walk the ring's successor order on failure.
    Classification mirrors the router's own re-dispatch ladder
    (common/retry.py): transient (UNAVAILABLE/CANCELLED/timeout) means
    THIS CELL died or wedged — reroute to the next ring successor with
    full-jitter backoff inside `reroute_window_secs`; backpressure
    (RESOURCE_EXHAUSTED) means the FLEET is out of capacity — the
    registry is shared, every surviving cell sees the same replicas,
    so rerouting would only add load, and the shed propagates;
    anything else is the request's own fault and propagates untouched.
    Streams reroute only before their first delivered chunk. Unary
    router_generate is idempotent end to end, so a reroute at any
    point — including after a cell accepted the request and died
    mid-dispatch — is safe: zero accepted-request loss is the drill's
    acceptance bar."""

    def __init__(self, cell_addrs, stub_factory=None,
                 reroute_window_secs=15.0, base_delay_secs=0.05,
                 max_delay_secs=0.5, timeout_secs=120.0,
                 breaker_threshold=3, breaker_cooldown_secs=1.0,
                 block_tokens=16, max_blocks=4,
                 clock=time.monotonic, sleep=time.sleep):
        self._stub_factory = stub_factory or _default_cell_stub_factory
        self._clock = clock
        self._sleep = sleep
        self._timeout = float(timeout_secs)
        self._window = float(reroute_window_secs)
        self._block_tokens = int(block_tokens)
        self._max_blocks = int(max_blocks)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown = float(breaker_cooldown_secs)
        self._policy = RetryPolicy(
            base_delay_secs=base_delay_secs,
            max_delay_secs=max_delay_secs,
            reconnect_window_secs=reroute_window_secs,
        )
        self._lock = threading.Lock()
        self._ring = HashRing()
        self._stubs = {}
        self._breakers = {}
        self.counters = {"routed": 0, "completed": 0, "rerouted": 0,
                         "cell_failures": 0, "shed": 0}
        for addr in cell_addrs:
            self.add_cell(addr)

    # ---------------------------------------------------------- cell map

    def add_cell(self, address):
        with self._lock:
            if address in self._stubs:
                return
            self._stubs[address] = self._stub_factory(address)
            self._breakers[address] = CircuitBreaker(
                self._breaker_threshold, self._breaker_cooldown,
            )
            self._ring.add(address)

    def remove_cell(self, address):
        with self._lock:
            stub = self._stubs.pop(address, None)
            self._breakers.pop(address, None)
            self._ring.remove(address)
        if stub is not None:
            close = getattr(stub, "close", None)
            if callable(close):
                try:
                    close()
                except Exception as e:  # noqa: BLE001 - best effort
                    logger.debug("closing cell channel %s failed: %r",
                                 address, e)

    def cells(self):
        with self._lock:
            return self._ring.nodes()

    def close(self):
        for addr in list(self.cells()):
            self.remove_cell(addr)

    # ----------------------------------------------------------- routing

    def _route_key(self, request):
        fp = prefix_fingerprint(request.prompt,
                                block_tokens=self._block_tokens,
                                max_blocks=self._max_blocks)
        if fp is not None:
            return fp
        # short prompts have no shareable prefix: any deterministic
        # key spreads them; affinity inside the cell is moot anyway
        return "short:%d:%s" % (
            len(request.prompt),
            ",".join(str(t) for t in list(request.prompt)[:8]),
        )

    def _targets(self, key):
        """The ring's failover walk for this key: owner first, then
        every other cell in ring order (deterministic across
        processes)."""
        with self._lock:
            return [
                (addr, self._stubs[addr], self._breakers[addr])
                for addr in self._ring.successors(key)
                if addr in self._stubs
            ]

    def _count(self, name):
        with self._lock:
            self.counters[name] += 1

    def generate(self, request, timeout=None):
        """Unary generate through the owning cell, walking the ring on
        transient cell failure. Raises RouterError with the terminal
        status name, exactly like the router itself."""
        self._count("routed")
        key = self._route_key(request)
        timeout = self._timeout if timeout is None else timeout
        deadline = self._clock() + self._window
        attempt = 0
        last_exc = None
        while True:
            dispatched = False
            for addr, stub, breaker in self._targets(key):
                now = self._clock()
                if not breaker.acquire(now):
                    continue
                if attempt or dispatched:
                    self._count("rerouted")
                dispatched = True
                try:
                    resp = stub.router_generate(request,
                                                timeout=timeout)
                except Exception as e:  # noqa: BLE001 - classified
                    last_exc = e
                    if is_backpressure_rpc_error(e):
                        # the cell ANSWERED: alive, fleet saturated.
                        # Every cell shares the registry — reroute
                        # would re-shed — so propagate the shed.
                        breaker.record_success()
                        self._count("shed")
                        raise RouterError(_code_name(e), str(e))
                    if is_transient_rpc_error(e):
                        breaker.record_failure(self._clock())
                        self._count("cell_failures")
                        continue  # next cell in ring order
                    breaker.release_probe()
                    raise RouterError(_code_name(e), str(e))
                breaker.record_success()
                self._count("completed")
                return resp
            if self._clock() >= deadline:
                raise RouterError(
                    _code_name(last_exc) if last_exc is not None
                    else "UNAVAILABLE",
                    "no router cell reachable inside the %.1fs "
                    "reroute window: %r" % (self._window, last_exc),
                )
            self._sleep(min(self._policy.backoff(attempt),
                            max(0.0, deadline - self._clock())))
            attempt += 1

    def generate_stream(self, request, timeout=None):
        """Streaming generate: reroute to the next cell only BEFORE
        the first chunk reaches the caller — after that a replay would
        duplicate delivered tokens, so a mid-stream cell loss fails
        the stream explicitly (the router's own stream contract)."""
        self._count("routed")
        key = self._route_key(request)
        call_timeout = self._timeout if timeout is None else timeout
        deadline = self._clock() + self._window

        def gen():
            attempt = 0
            delivered = 0
            last_exc = None
            while True:
                for addr, stub, breaker in self._targets(key):
                    now = self._clock()
                    if not breaker.acquire(now):
                        continue
                    if attempt or last_exc is not None:
                        self._count("rerouted")
                    try:
                        stream = stub.router_generate_stream(
                            request, timeout=call_timeout,
                        )
                        for chunk in stream:
                            delivered += len(chunk.tokens)
                            yield chunk
                        breaker.record_success()
                        self._count("completed")
                        return
                    except Exception as e:  # noqa: BLE001
                        last_exc = e
                        if delivered:
                            breaker.record_failure(self._clock())
                            raise RouterError(
                                "UNAVAILABLE",
                                "cell %s lost mid-stream after %d "
                                "delivered tokens (%s)"
                                % (addr, delivered, _code_name(e)),
                            )
                        if is_backpressure_rpc_error(e):
                            breaker.record_success()
                            self._count("shed")
                            raise RouterError(_code_name(e), str(e))
                        if is_transient_rpc_error(e):
                            breaker.record_failure(self._clock())
                            self._count("cell_failures")
                            continue
                        breaker.release_probe()
                        raise RouterError(_code_name(e), str(e))
                if self._clock() >= deadline:
                    raise RouterError(
                        _code_name(last_exc)
                        if last_exc is not None else "UNAVAILABLE",
                        "no router cell reachable inside the %.1fs "
                        "reroute window: %r"
                        % (self._window, last_exc),
                    )
                self._sleep(min(self._policy.backoff(attempt),
                                max(0.0, deadline - self._clock())))
                attempt += 1

        return gen()

    def status(self, request=None, timeout=5.0):
        """router_status from the first answering cell (ring order by
        a fixed key, so repeated calls prefer the same cell)."""
        from elasticdl_tpu.proto import elasticdl_pb2 as pb

        request = request or pb.RouterStatusRequest()
        last_exc = None
        for _addr, stub, breaker in self._targets("status"):
            if not breaker.acquire(self._clock()):
                continue
            try:
                resp = stub.router_status(request, timeout=timeout)
            except Exception as e:  # noqa: BLE001 - try next cell
                last_exc = e
                if is_transient_rpc_error(e):
                    breaker.record_failure(self._clock())
                else:
                    breaker.release_probe()
                continue
            breaker.record_success()
            return resp
        raise RouterError(
            _code_name(last_exc) if last_exc is not None
            else "UNAVAILABLE",
            "no router cell answered status: %r" % (last_exc,),
        )
