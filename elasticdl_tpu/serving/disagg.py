"""Disaggregated prefill/decode serving: KV chain handoff plumbing.

The two serving phases want opposite machines: prefill is compute-bound
(one long arithmetic burst over the whole prompt), decode is
memory-bound (thousands of tiny steps walking the KV cache). A unified
replica sizes for both and wastes one. This module is the glue that
lets a fleet split instead:

* replicas advertise a ROLE (``prefill`` / ``decode`` / ``unified``,
  ServingConfig.role -> ServerStatus.role -> ReplicaStatus.role): the
  router keeps ``prefill`` replicas out of normal rotation and targets
  them only for cache warming;
* a dedicated prefill replica runs a prompt to completion via
  ``GenerateRequest.prefill_only`` — seat, prefill, register the chain,
  release — leaving the chain parked refcount-0 cached (matchable,
  exportable, reclaimable);
* the finished chain moves as a DENSE BYTE COPY: ``export_chain``
  gathers the chain's blocks (int8 rows + f32 scale leaves, the same
  tree-generic gather the host spill tier reads through) into a
  ``TransferChainRequest``; ``transfer_chain`` on the decode side lands
  them in one batched upload into fresh blocks re-keyed into the
  content-addressed trie. The next generate with that prompt seats by
  prefix hit — sharing, CoW and speculative decode compose unchanged,
  so the handoff is token-exact by the same argument prefix sharing is.

HandoffCoordinator is the router-side orchestrator and the EDL501
obligation receiver: every ``export_chain`` must settle through
``import_chain`` (success) or ``abort_transfer`` (failure accounting)
on the same coordinator — the lint rule (analysis/resource_rules.py)
holds call sites to that shape. Exports hold no pool references
(chains park refcount-0), so a coordinator or replica crash mid-
transfer leaks nothing; abort is the failure's RECORD, not a resource
release.

Wire codec: rows travel as raw little-endian bytes per arena leaf
(``KvChainBlock.leaves``, jax.tree.leaves order) plus the dtype list,
so the importer can refuse a mismatched arena layout cheaply — a
mismatch downgrades to a plain cold dispatch, never an error the
client sees.
"""

import itertools
import threading

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb


class HandoffError(Exception):
    """A handoff leg failed (prefill generate, export, or import). The
    coordinator's caller falls back to a plain dispatch — a failed
    handoff costs the warm-start, never the request."""


def chain_to_proto(chain, block_size, leaf_dtypes, transfer_id):
    """Serialize a pool export (``[(block token tuple, [np rows per
    leaf])]``, kv_pool.export_chain's shape) into the wire payload the
    decode side imports verbatim."""
    return pb.TransferChainRequest(
        transfer_id=transfer_id,
        block_size=block_size,
        leaf_dtypes=list(leaf_dtypes),
        blocks=[
            pb.KvChainBlock(
                tokens=list(toks),
                leaves=[np.ascontiguousarray(r).tobytes()
                        for r in rows],
            )
            for toks, rows in chain
        ],
    )


def proto_to_blocks(msg, pool):
    """Decode a TransferChainRequest against the IMPORTING pool's own
    arena geometry: each leaf's bytes reshape to that pool's per-block
    row shape, so a size mismatch (different model dims, different
    block_size) surfaces as a ValueError the servicer downgrades to
    ok=False. Returns ``(blocks, leaf_dtypes)`` in import_chain's
    argument shape."""
    import jax

    shapes = [leaf.shape[1:] for leaf in jax.tree.leaves(pool.pools)
              if leaf.ndim == 4]
    dtypes = list(msg.leaf_dtypes)
    if len(dtypes) != len(shapes):
        raise ValueError(
            "chain carries %d row leaves, this pool has %d"
            % (len(dtypes), len(shapes))
        )
    if msg.block_size != pool.block_size:
        raise ValueError(
            "chain block_size %d does not match this pool's %d"
            % (msg.block_size, pool.block_size)
        )
    blocks = []
    for blk in msg.blocks:
        if len(blk.leaves) != len(shapes):
            raise ValueError(
                "chain block carries %d leaves, expected %d"
                % (len(blk.leaves), len(shapes))
            )
        rows = [
            np.frombuffer(raw, dtype=dt).reshape(shape)
            for raw, dt, shape in zip(blk.leaves, dtypes, shapes)
        ]
        blocks.append((tuple(blk.tokens), rows))
    return blocks, dtypes


class HandoffCoordinator(object):
    """One prefill->decode handoff, three obligations. The router
    binds this as a local (``disagg = self._disagg``) so edl-lint
    EDL501 can hold every ``disagg.export_chain`` to a same-receiver
    ``disagg.import_chain`` or ``disagg.abort_transfer`` on all paths.

    Transport-agnostic like Router: replicas only need the ServingStub
    surface (generate / export_chain / transfer_chain / abort_transfer,
    each taking ``timeout=``)."""

    _ids = itertools.count(1)
    _ids_lock = threading.Lock()

    def __init__(self, timeout_secs=10.0, clock=None):
        self.timeout_secs = float(timeout_secs)

    def new_transfer_id(self):
        with HandoffCoordinator._ids_lock:
            return "xfer-%d" % next(HandoffCoordinator._ids)

    def export_chain(self, rep, request, transfer_id, timeout=None):
        """Warm the prefill replica and export the chain: one
        prefill_only generate (seat, prefill, register, release — the
        sampled token is discarded; the decode side re-derives it from
        the shared chain, which is what makes the handoff token-exact)
        followed by the export RPC. Returns the transfer payload.
        Opens the EDL501 obligation: settle with import_chain or
        abort_transfer."""
        timeout = self.timeout_secs if timeout is None else timeout
        rep.stub.generate(
            pb.GenerateRequest(
                prompt=list(request.prompt),
                max_new_tokens=1,
                temperature=request.temperature,
                seed=request.seed,
                prefill_only=True,
            ),
            timeout=timeout,
        )
        payload = rep.stub.export_chain(
            pb.ExportChainRequest(
                prompt=list(request.prompt),
                transfer_id=transfer_id,
            ),
            timeout=timeout,
        )
        if not payload.blocks:
            raise HandoffError(
                "prefill replica exported an empty chain"
            )
        return payload

    def import_chain(self, rep, payload, timeout=None):
        """Land an exported chain on the decode replica (the success
        settle). The response's ``blocks`` is the chain's RESOLVED
        coverage on the importer — imported plus already-resident
        levels, so a fully deduped transfer still succeeds (the chain
        is warm either way). Raises HandoffError when the importer
        refused the payload (arena mismatch) or none of the chain
        landed (pool exhausted) so the caller aborts and falls
        back."""
        timeout = self.timeout_secs if timeout is None else timeout
        resp = rep.stub.transfer_chain(payload, timeout=timeout)
        if not resp.ok or not resp.blocks:
            raise HandoffError(
                "decode replica refused chain import: %s"
                % (resp.error or "no blocks imported",)
            )
        return resp

    def abort_transfer(self, rep, transfer_id, timeout=None):
        """Close a failed handoff's obligation on the exporter (the
        failure settle). Best-effort: the exporter holds no references
        for this transfer, so a lost abort leaks nothing — it only
        costs the failure a ledger entry."""
        timeout = self.timeout_secs if timeout is None else timeout
        try:
            rep.stub.abort_transfer(
                pb.AbortTransferRequest(transfer_id=transfer_id),
                timeout=timeout,
            )
        except Exception as e:  # noqa: BLE001 - accounting only
            logger.debug("abort_transfer(%s) to %s failed: %r",
                         transfer_id, rep.address, e)
