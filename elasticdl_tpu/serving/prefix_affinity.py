"""Prefix-affinity primitives for the multi-cell router tier.

Three small, dependency-free pieces:

``prefix_fingerprint``
    Hashes a request's *leading full KV blocks* with the same chain
    structure the PR 8 content-addressed trie uses — each link is
    ``H(parent_digest || block_tokens)`` with the trie's root-parent
    sentinel seeding the chain — so two prompts share a fingerprint
    exactly when their leading block chain would share trie nodes
    (and therefore shared KV blocks) on a replica. Prompts shorter
    than one full block return ``None``: there is nothing to share,
    and the router decays to pure least-loaded.

``HashRing``
    A deterministic consistent-hash ring (blake2b points, NOT
    Python's salted ``hash``) used twice: the cell front consistent-
    hashes request fingerprints across router cells, and the drill
    asserts bounded reshuffle under cell add/remove. ``successors``
    yields distinct nodes in ring order — the failover walk.

``AffinityIndex``
    A TTL'd, capacity-bounded LRU of fingerprint → replica address,
    learned on successful dispatch. Staleness is handled by decay,
    not by trust: an expired or evicted entry simply means the router
    falls back to least-loaded for that request.
"""

import bisect
import hashlib
import struct
import threading
from collections import OrderedDict

#: the trie's root-parent sentinel (the ``parent bid = -1`` analog):
#: every chain starts here so the first block's digest depends only
#: on its tokens, exactly like the content-addressed block key.
_ROOT_DIGEST = b"\xff" * 8

#: digest width: 8 bytes is plenty for an affinity hint (collisions
#: cost one misrouted dispatch, not correctness).
_DIGEST_SIZE = 8


def _chain_digest(parent, tokens):
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    h.update(parent)
    for tok in tokens:
        h.update(struct.pack("<q", int(tok)))
    return h.digest()


def prefix_fingerprint(prompt, block_tokens=16, max_blocks=4):
    """Fingerprint the leading full blocks of ``prompt``.

    Returns a hex digest stable across processes (suitable both as an
    affinity-index key and as a consistent-hash key), or ``None`` when
    the prompt holds no complete block — short prompts have no
    shareable prefix chain and should be routed purely by load.

    ``max_blocks`` caps the chain: system prompts dominate sharing,
    and hashing the whole prompt would make every request's
    fingerprint unique, defeating affinity.
    """
    if block_tokens < 1:
        raise ValueError("block_tokens must be >= 1")
    toks = list(prompt)
    full = len(toks) // block_tokens
    if full < 1:
        return None
    digest = _ROOT_DIGEST
    for i in range(min(full, max_blocks)):
        block = toks[i * block_tokens:(i + 1) * block_tokens]
        digest = _chain_digest(digest, block)
    return digest.hex()


def _ring_point(data):
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    )


class HashRing(object):
    """Deterministic consistent-hash ring with virtual nodes.

    Every process that builds a ring from the same node set computes
    the same mapping (blake2b, never the salted builtin ``hash``), so
    the cell front in the drill process and the cells themselves agree
    on which cell owns which fingerprint.
    """

    def __init__(self, nodes=(), vnodes=64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self._vnodes = int(vnodes)
        self._points = []  # sorted [(point, node)]
        self._nodes = set()
        for node in nodes:
            self.add(node)

    def __len__(self):
        return len(self._nodes)

    def __contains__(self, node):
        return node in self._nodes

    def nodes(self):
        return sorted(self._nodes)

    def add(self, node):
        node = str(node)
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(self._vnodes):
            point = _ring_point(
                ("%s#%d" % (node, v)).encode("utf-8")
            )
            bisect.insort(self._points, (point, node))

    def remove(self, node):
        node = str(node)
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [p for p in self._points if p[1] != node]

    def lookup(self, key):
        """The node owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        point = _ring_point(str(key).encode("utf-8"))
        idx = bisect.bisect_right(self._points, (point, chr(0x10FFFF)))
        if idx == len(self._points):
            idx = 0
        return self._points[idx][1]

    def successors(self, key):
        """All distinct nodes in ring order starting at ``key``'s
        owner — the failover walk order for the cell front."""
        if not self._points:
            return []
        point = _ring_point(str(key).encode("utf-8"))
        idx = bisect.bisect_right(self._points, (point, chr(0x10FFFF)))
        out, seen = [], set()
        n = len(self._points)
        for off in range(n):
            node = self._points[(idx + off) % n][1]
            if node not in seen:
                seen.add(node)
                out.append(node)
        return out


class AffinityIndex(object):
    """TTL'd LRU mapping prefix fingerprint → replica address.

    Learned on successful dispatch; consulted before least-loaded.
    Entries expire after ``ttl_secs`` (affinity data older than a few
    lease periods says nothing about current residency) and the table
    is capacity-bounded so a fingerprint flood cannot balloon router
    memory. ``forget_address`` drops every entry pointing at a retired
    replica so affinity never resurrects a dead address.
    """

    def __init__(self, ttl_secs=60.0, capacity=4096):
        self._ttl = float(ttl_secs)
        self._cap = int(capacity)
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # fp -> (address, learned_at)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def learn(self, fingerprint, address, now):
        if fingerprint is None:
            return
        with self._lock:
            self._entries.pop(fingerprint, None)
            self._entries[fingerprint] = (str(address), float(now))
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)

    def lookup(self, fingerprint, now):
        """The learned address, or None when unknown or stale."""
        if fingerprint is None:
            return None
        with self._lock:
            entry = self._entries.get(fingerprint)
            if entry is None:
                return None
            address, learned_at = entry
            if now - learned_at > self._ttl:
                del self._entries[fingerprint]
                return None
            self._entries.move_to_end(fingerprint)
            return address

    def forget_address(self, address):
        address = str(address)
        with self._lock:
            stale = [fp for fp, (addr, _) in self._entries.items()
                     if addr == address]
            for fp in stale:
                del self._entries[fp]
            return len(stale)
