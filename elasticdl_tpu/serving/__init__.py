"""Online serving subsystem: continuous-batching generation server.

The training half of the north star is elastic training (master-owned
task queue, workers pull work); this package is the first subsystem on
the inference half — it turns the offline decode library
(api/generation.py) into a standing server:

* admission.py   bounded request queue with backpressure + deadlines
* engine.py      continuous-batching decode scheduler over a fixed
                 pool of KV-cache slots (one jit step, no recompiles
                 on membership change); dense per-slot stripes or the
                 block-paged pool (EDL_KV_PAGED / ServingConfig)
* kv_pool.py     block-paged KV storage: free-list allocator, per-slot
                 block tables, shared per-layer block arenas, and the
                 tiered host-spill cache (evicted prefix chains park
                 in bounded host RAM and revive by upload)
* server.py      gRPC front-end (Generate / GenerateStream /
                 ServerStatus) + the scheduler thread
* router.py      health-checked multi-replica routing tier: heartbeat
                 leases, least-loaded dispatch, per-replica circuit
                 breakers, bounded re-dispatch + hedging, shed-load
                 (entry: python -m elasticdl_tpu.serving.router_main)
* hot_reload.py  checkpoint-dir watcher that swaps params between
                 decode steps without dropping in-flight requests
* telemetry.py   serving gauges/counters (closed name sets) on the
                 common/tb_events.py path, each backed by a windowed
                 time-series ring feeding the Prometheus /metrics
                 exposition and the router's SLO burn-rate engine
                 (observability/metrics.py, observability/slo.py)

See docs/designs/serving.md for the slot lifecycle and failure modes.
"""

from elasticdl_tpu.serving.admission import (  # noqa: F401
    AdmissionError,
    RequestQueue,
    ServingRequest,
)
from elasticdl_tpu.serving.engine import (  # noqa: F401
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from elasticdl_tpu.serving.kv_pool import (  # noqa: F401
    BlockAllocator,
    OutOfBlocks,
    PagedKVPool,
)
from elasticdl_tpu.serving.router import (  # noqa: F401
    CircuitBreaker,
    Router,
    RouterConfig,
    RouterError,
    RouterServicer,
)
from elasticdl_tpu.serving.server import (  # noqa: F401
    GenerationServer,
    ServingConfig,
)
