from elasticdl_tpu.checkpoint.saver import (  # noqa: F401
    CheckpointSaver,
    flatten_state,
    get_latest_checkpoint_version,
    load_checkpoint,
    restore_state_from_checkpoint,
    restore_state_from_flat,
)
