"""Orbax interop: read/write TrainState checkpoints in the JAX
ecosystem's standard format.

The framework's own sharded format (saver.py — the reference's
`variables-i-of-M.ckpt` semantics, re-shardable by construction) remains
the primary; this adapter lets users exchange checkpoints with the rest
of the JAX world (orbax is what flax/t5x/maxtext standardize on):

    save_with_orbax(state, path)                 # one orbax step dir
    state = restore_with_orbax(template, path)   # re-sharded onto the
                                                 # template's mesh
    import_orbax_to_native(template, orbax_path, saver, version)

Restores go through the same `restore_state_from_flat` machinery as the
native format, so a checkpoint written on one mesh restores onto any
other (device_put against the template's shardings).
"""

import numpy as np

from elasticdl_tpu.checkpoint.saver import (
    flatten_state,
    restore_state_from_flat,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


def _checkpointer():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise RuntimeError(
            "The orbax-checkpoint package is not installed; pip install "
            "elasticdl-tpu[orbax] (or orbax-checkpoint) for orbax interop"
        ) from e
    return ocp.PyTreeCheckpointer()


def save_with_orbax(state, path):
    """Write `state` as an orbax PyTree checkpoint at `path` (a directory
    that must not already exist — orbax owns its layout). The tree is
    flattened to {keystr: ndarray} first (flatten_state materializes
    host-side), so device shardings never leak into the artifact."""
    flat = flatten_state(state)  # materializes every leaf host-side
    _checkpointer().save(path, flat)
    logger.info("Saved orbax checkpoint to %s (%d leaves)",
                path, len(flat))
    return path


def restore_with_orbax(template_state, path):
    """Rebuild a TrainState-shaped pytree from an orbax checkpoint,
    re-sharded to `template_state`'s own shardings."""
    flat = _checkpointer().restore(path)
    flat = {key: np.asarray(value) for key, value in flat.items()}
    return restore_state_from_flat(template_state, flat)


def export_native_to_orbax(checkpoint_dir, orbax_path, version=None):
    """Convert a native sharded checkpoint (saver.py layout) into an
    orbax one without needing the model: the flat {keystr: ndarray} map
    is the common currency. Returns (orbax_path, version)."""
    from elasticdl_tpu.checkpoint.saver import load_checkpoint

    flat, version = load_checkpoint(checkpoint_dir, version)
    _checkpointer().save(orbax_path, flat)
    logger.info(
        "Exported native checkpoint version-%d to orbax at %s",
        version, orbax_path,
    )
    return orbax_path, version


def import_orbax_to_native(template_state, orbax_path, saver, version):
    """Bring an orbax checkpoint into the native format: restore onto the
    template's mesh, then write through the given CheckpointSaver."""
    state = restore_with_orbax(template_state, orbax_path)
    saver.save(state, version)
    if getattr(saver, "async_save", False):
        saver.wait()
    return state
