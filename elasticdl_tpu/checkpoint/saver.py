"""Sharded, re-shardable checkpoints.

Layout parity with the reference (common/save_utils.py:93-294 and
go/pkg/ps/checkpoint.go:31-141):

    <dir>/version-<V>/variables-<i>-of-<M>.ckpt

* each shard file holds a subset of leaves, assigned by sha256(name) mod M
  (the reference's dense-variable placement rule, hash_utils.string_to_id);
* a version dir is valid iff it contains exactly M ``variables-*-of-M`` files
  (reference save_utils.py `_get_valid_lastest_version_dir` semantics);
* old versions are pruned keeping the newest ``keep_max`` (reference
  `_delete_old_checkpoints`);
* restore merges ALL shard files then re-places onto the target mesh, so a
  checkpoint written with M shards restores onto any device count / mesh
  shape (reference `restore_params_from_checkpoint` re-sharding,
  save_utils.py:229-282 — there a hash re-partition, here a
  ``jax.device_put`` with the new state's NamedSharding).

TPU-native differences: the unit of state is the whole TrainState pytree
(params + optimizer slots + batch stats + rng + step) rather than PS-resident
variables, so resume restores the *optimizer* exactly, and shard files are
written by hosts (process h writes shards h, h+P, ...) instead of PS pods.
"""

import hashlib
import json
import os
import re
import shutil
import tempfile

import jax
import numpy as np

from elasticdl_tpu.common.hash_utils import string_to_id
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.tensor_utils import (
    deserialize_ndarray_dict,
    serialize_ndarray_dict,
)

_SHARD_RE = re.compile(r"^variables-(\d+)-of-(\d+)\.ckpt$")
_VERSION_RE = re.compile(r"^version-(\d+)$")


def flatten_state(state):
    """Flatten any pytree to {keystr: ndarray} with jax path strings as the
    stable leaf names (e.g. ``.params['Dense_0']['kernel']``)."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    out = {}
    for path, leaf in leaves:
        out[jax.tree_util.keystr(path)] = _to_numpy(leaf)
    return out


def _to_numpy(leaf):
    """Materialize a (possibly sharded, possibly multi-host) jax.Array on the
    host. Non-fully-addressable arrays are all-gathered across processes."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        leaf = multihost_utils.process_allgather(leaf, tiled=True)
    return np.asarray(leaf)


def _unflatten_into(state, flat, strict=True):
    """Rebuild a pytree shaped like `state` from {keystr: ndarray}, keeping
    each leaf's dtype and the target's sharding (device_put against the
    existing leaf's sharding when present). strict=False keeps the
    target's freshly-initialized leaf for missing keys — the warm-start
    path (e.g. restoring a dense pretraining checkpoint into a model
    with net-new LoRA adapter params)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    new_leaves = []
    missing = []
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            missing.append(key)
            new_leaves.append(leaf)
            continue
        arr = flat[key]
        target_dtype = getattr(leaf, "dtype", None)
        if target_dtype is not None and arr.dtype != target_dtype:
            arr = arr.astype(target_dtype)
        if isinstance(leaf, jax.Array):
            arr = jax.device_put(arr, leaf.sharding)
        new_leaves.append(arr)
    if missing and strict:
        raise ValueError(
            "Checkpoint is missing %d leaves, e.g. %s. A common cause is "
            "a changed optimizer-state layout — e.g. an embedding table "
            "crossing the sparse-grad threshold (embedding/sparse_update"
            ".py) between save and restore; pin sparse_grads on the layer "
            "to restore older checkpoints. Pass strict=False to warm-"
            "start: missing leaves keep their fresh initialization."
            % (len(missing), missing[:3])
        )
    if missing:
        logger.info(
            "warm start: %d leaves kept their fresh init (e.g. %s)",
            len(missing), missing[:3],
        )
    return treedef.unflatten(new_leaves)


class CheckpointSaver(object):
    """Writes and prunes versioned sharded checkpoints.

    Args mirror the reference CheckpointSaver (save_utils.py:93-120):
    checkpoint_dir, checkpoint_steps (save every N model versions; 0 =
    disabled), keep_max_version (0 = keep all), num_shards (defaults to the
    process count so every host writes one file).
    """

    def __init__(self, checkpoint_dir, checkpoint_steps=0,
                 keep_max_version=0, num_shards=None,
                 extra_state_fn=None, async_save=False):
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_steps = int(checkpoint_steps)
        self.keep_max_version = int(keep_max_version)
        # Optional () -> {keystr: ndarray} merged into every save — the
        # host-spill embedding engines ride the same sharded checkpoint
        # (embedding/host_bridge.HostEmbeddingManager.flat_state).
        self.extra_state_fn = extra_state_fn
        # async_save: device->host materialization stays synchronous
        # (correct snapshot of donated buffers), but serialization + IO
        # + pruning move to a background thread so the train loop only
        # pays the copy, not the disk. Single-process only: multi-host
        # saves are collective (process_allgather) and must stay on the
        # calling thread.
        self.async_save = bool(async_save) and jax.process_count() == 1
        self._write_thread = None
        self._write_error = None
        if self.async_save:
            import atexit

            # drain an in-flight write on clean interpreter exit so the
            # final checkpoint is never lost to the daemon thread dying
            atexit.register(self.wait)
        self.num_shards = int(
            num_shards if num_shards is not None else jax.process_count()
        )
        if self.num_shards <= 0:
            raise ValueError("num_shards must be positive")
        self._last_saved_version = -1

    def is_enabled(self):
        return bool(self.checkpoint_dir) and self.checkpoint_steps > 0

    def maybe_save(self, state, version=None):
        """Save iff `version` crosses a checkpoint_steps boundary (the
        reference PS saves inside push_gradients every checkpoint_steps —
        ps/servicer.py:255-270)."""
        if not self.is_enabled():
            return False
        version = int(version if version is not None else state.step)
        if version <= 0 or version % self.checkpoint_steps != 0:
            return False
        if version == self._last_saved_version:
            return False
        self.save(state, version)
        return True

    def save(self, state, version):
        """Write version-<V> atomically (temp dir + rename), then prune.

        With async_save, returns after materializing the snapshot; the
        write happens in a background thread (at most one in flight —
        a new save joins the previous one first)."""
        version = int(version)
        extra = {}
        if self.extra_state_fn is not None:
            # Extra leaves (host-spill engine state) are PROCESS-LOCAL:
            # each host's flat map holds only its own engines, so these
            # keys must land in a shard file THIS process writes — the
            # hash assignment in _partition would route them to files
            # other processes own, silently dropping them multi-host.
            # The shard-count check runs on EVERY process BEFORE the
            # collective flatten: raising on a subset mid-save would let
            # the rest finish a valid-looking checkpoint missing those
            # hosts' partitions.
            if self.num_shards < jax.process_count():
                raise ValueError(
                    "process-local checkpoint state (extra_state_fn) "
                    "needs num_shards (%d) >= process count (%d) so "
                    "every process has a shard file to write"
                    % (self.num_shards, jax.process_count())
                )
            extra = dict(self.extra_state_fn())
        flat = flatten_state(state)
        if self.async_save:
            import threading

            self.wait()  # at most one in-flight write; re-raises failures
            self._write_thread = threading.Thread(
                target=self._write_guarded,
                args=(flat, extra, version),
                daemon=True,
                name="ckpt-write-v%d" % version,
            )
            # eager: maybe_save must not double-fire this version while
            # the write is in flight (a FAILED write resets this so the
            # next cadence retries)
            self._last_saved_version = version
            self._write_thread.start()
            return self._version_dir(version)
        out = self._write_and_log(flat, extra, version)
        self._last_saved_version = version
        return out

    def wait(self):
        """Block until any in-flight async write completes, re-raising
        its failure (call before reading the checkpoint back; also
        registered atexit so clean shutdown drains the write)."""
        if self._write_thread is not None:
            self._write_thread.join()
            self._write_thread = None
        if self._write_error is not None:
            err, self._write_error = self._write_error, None
            raise err

    def _write_guarded(self, flat, extra, version):
        try:
            self._write_and_log(flat, extra, version)
        except BaseException as e:  # noqa: BLE001 - re-raised in wait()
            self._write_error = e
            # the version was NOT durably written: let maybe_save retry
            self._last_saved_version = -1
            logger.error(
                "async checkpoint write of version-%d failed: %s",
                version, e,
            )

    def _write_and_log(self, flat, extra, version):
        final_dir = self._version_dir(version)
        os.makedirs(self.checkpoint_dir, exist_ok=True)

        proc, nproc = jax.process_index(), jax.process_count()
        tmp_dir = None
        try:
            if nproc == 1:
                # single-process: write to a temp dir, rename for atomicity
                tmp_dir = tempfile.mkdtemp(
                    prefix=".version-%d." % version, dir=self.checkpoint_dir
                )
                write_dir = tmp_dir
            else:
                # multi-host: every process writes its shards straight into
                # the final dir (assumed shared storage). No atomic rename —
                # a partially-written dir fails the M-files validity check,
                # which is exactly the reference's protection too. Stale
                # shard files from an earlier run with a DIFFERENT shard
                # count would make load merge two runs' tensors, so each
                # process clears foreign-count files it would orphan.
                write_dir = final_dir
                os.makedirs(write_dir, exist_ok=True)
                for name in os.listdir(write_dir):
                    m = _SHARD_RE.match(name)
                    if m and int(m.group(2)) != self.num_shards:
                        try:
                            os.remove(os.path.join(write_dir, name))
                        except OSError:
                            pass
            shards = self._partition(flat)
            if extra:
                # process-local leaves ride this process's first shard
                shards[proc].update(extra)
            digests = {}
            for i in range(proc, self.num_shards, nproc):
                name = "variables-%d-of-%d.ckpt" % (i, self.num_shards)
                payload = serialize_ndarray_dict(shards[i])
                with open(os.path.join(write_dir, name), "wb") as f:
                    f.write(payload)
                digests[name] = hashlib.sha256(payload).hexdigest()
            if proc == 0:
                meta = {
                    "version": version,
                    "num_shards": self.num_shards,
                    # counts the GLOBAL (dense-state) leaves only:
                    # process-local extra leaves live in per-process
                    # shards whose counts process 0 cannot know
                    "leaf_count": len(flat),
                }
                if nproc == 1:
                    # integrity manifest: single-process saves cover the
                    # full shard set, so the digests let a rollout
                    # controller reject a torn/bit-flipped checkpoint
                    # BEFORE any replica swaps (verify_checkpoint).
                    # Multi-host saves skip it — process 0 never sees the
                    # other hosts' shard bytes.
                    meta["shard_digests"] = digests
                with open(os.path.join(write_dir, "meta.json"), "w") as f:
                    json.dump(meta, f)
                if tmp_dir is not None:
                    if os.path.isdir(final_dir):
                        shutil.rmtree(final_dir)
                    os.rename(tmp_dir, final_dir)
                    tmp_dir = None
        finally:
            if tmp_dir is not None and os.path.isdir(tmp_dir):
                shutil.rmtree(tmp_dir, ignore_errors=True)
        logger.info(
            "Saved checkpoint version-%d (%d shards) to %s",
            version, self.num_shards, self.checkpoint_dir,
        )
        if proc == 0:
            self._prune()
        return final_dir

    # ------------------------------------------------------------ internals

    def _version_dir(self, version):
        return os.path.join(self.checkpoint_dir, "version-%d" % version)

    def _partition(self, flat):
        shards = [dict() for _ in range(self.num_shards)]
        for name, arr in flat.items():
            shards[string_to_id(name, self.num_shards)][name] = arr
        return shards

    def _prune(self):
        if self.keep_max_version <= 0:
            return
        versions = _list_versions(self.checkpoint_dir)
        for v in versions[: -self.keep_max_version]:
            shutil.rmtree(self._version_dir(v), ignore_errors=True)
            logger.info("Pruned checkpoint version-%d", v)


def _list_versions(checkpoint_dir):
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return []
    versions = []
    for name in os.listdir(checkpoint_dir):
        m = _VERSION_RE.match(name)
        if m:
            versions.append(int(m.group(1)))
    return sorted(versions)


def _complete_set_counts(path):
    """Shard counts M for which all M ``variables-*-of-M.ckpt`` exist."""
    if not os.path.isdir(path):
        return []
    counts = {}
    for name in os.listdir(path):
        m = _SHARD_RE.match(name)
        if m:
            counts.setdefault(int(m.group(2)), set()).add(int(m.group(1)))
    return [
        total for total, seen in counts.items()
        if seen == set(range(total))
    ]


def _has_complete_set(path, total):
    return total in _complete_set_counts(path)


def _is_valid_version_dir(path):
    """Valid iff it holds exactly M ``variables-*-of-M.ckpt`` files (the
    reference's validity rule: file count equals the N in the filename)."""
    return bool(_complete_set_counts(path))


def get_latest_checkpoint_version(checkpoint_dir):
    """Largest version whose dir is valid, or -1."""
    for v in reversed(_list_versions(checkpoint_dir)):
        if _is_valid_version_dir(
            os.path.join(checkpoint_dir, "version-%d" % v)
        ):
            return v
    return -1


def load_checkpoint(checkpoint_dir, version=None):
    """Merge all shard files of a version into one {keystr: ndarray}.

    Shard count at load time is irrelevant — this is what makes checkpoints
    re-shardable to any mesh (reference save_utils.py:229-282).
    Returns (flat_dict, version).
    """
    if version is None:
        version = get_latest_checkpoint_version(checkpoint_dir)
    if version < 0:
        raise FileNotFoundError(
            "No valid checkpoint under %r" % checkpoint_dir
        )
    vdir = os.path.join(checkpoint_dir, "version-%d" % version)
    if not _is_valid_version_dir(vdir):
        raise FileNotFoundError("Invalid checkpoint dir %r" % vdir)
    # restrict to one complete shard set: meta.json's count when present,
    # else the largest complete set — never merge files across shard counts
    want = None
    meta_path = os.path.join(vdir, "meta.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                want = int(json.load(f).get("num_shards"))
        except (ValueError, TypeError, OSError):
            want = None
    if want is None or not _has_complete_set(vdir, want):
        want = max(_complete_set_counts(vdir))
    flat = {}
    for name in sorted(os.listdir(vdir)):
        m = _SHARD_RE.match(name)
        if m and int(m.group(2)) == want:
            with open(os.path.join(vdir, name), "rb") as f:
                flat.update(deserialize_ndarray_dict(f.read()))
    return flat, version


class CheckpointCorruptError(Exception):
    """A checkpoint version failed integrity verification (torn shard
    set, digest mismatch, unreadable meta). Raised by verify_checkpoint
    so callers can distinguish 'bad bytes on disk' from 'no checkpoint
    yet' (FileNotFoundError)."""


def verify_checkpoint(checkpoint_dir, version):
    """Integrity-check one checkpoint version WITHOUT deserializing it.

    Returns a manifest dict {version, num_shards, leaf_count, bytes,
    verified_digests} suitable for journaling. Raises FileNotFoundError
    when the version dir does not exist at all, CheckpointCorruptError
    when it exists but is torn or corrupt:

    * the shard set must be complete (M files of ``variables-*-of-M``);
    * when meta.json names a shard count, that exact set must be the
      complete one (a stale foreign-count set does not pass);
    * when meta.json carries shard_digests (single-process saves), every
      named shard must exist and hash to its recorded sha256 — this is
      the check that catches a poisoned/bit-flipped weight file before a
      rollout swaps any replica.
    """
    vdir = os.path.join(checkpoint_dir, "version-%d" % int(version))
    if not os.path.isdir(vdir):
        raise FileNotFoundError("No checkpoint dir %r" % vdir)
    complete = _complete_set_counts(vdir)
    if not complete:
        raise CheckpointCorruptError(
            "torn checkpoint %r: no complete shard set" % vdir
        )
    meta = {}
    meta_path = os.path.join(vdir, "meta.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (ValueError, OSError) as e:
            raise CheckpointCorruptError(
                "unreadable meta.json in %r: %s" % (vdir, e)
            )
    want = meta.get("num_shards")
    if want is not None and int(want) not in complete:
        raise CheckpointCorruptError(
            "torn checkpoint %r: meta names %s shards but complete "
            "sets are %s" % (vdir, want, complete)
        )
    if want is None:
        want = max(complete)
    digests = meta.get("shard_digests") or {}
    verified = 0
    total_bytes = 0
    for name, recorded in sorted(digests.items()):
        path = os.path.join(vdir, name)
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                "missing digested shard %r: %s" % (path, e)
            )
        total_bytes += len(payload)
        if hashlib.sha256(payload).hexdigest() != recorded:
            raise CheckpointCorruptError(
                "digest mismatch for %r: checkpoint bytes do not match "
                "the manifest written at save time" % path
            )
        verified += 1
    return {
        "version": int(version),
        "num_shards": int(want),
        "leaf_count": meta.get("leaf_count"),
        "bytes": total_bytes,
        "verified_digests": verified,
    }


def restore_state_from_flat(state, flat, strict=True):
    """Rebuild a TrainState-shaped pytree from an already-loaded flat
    checkpoint dict, re-sharded to `state`'s own shardings. Extra keys
    (e.g. host-embedding engine state) are ignored here. strict=False
    warm-starts: leaves absent from the checkpoint keep their fresh
    initialization (dense checkpoint -> LoRA model, new heads, ...)."""
    return _unflatten_into(state, flat, strict=strict)


def restore_state_from_checkpoint(state, checkpoint_dir, version=None,
                                  strict=True):
    """Rebuild a TrainState-shaped pytree from a checkpoint, re-sharded to
    `state`'s own shardings. Returns (new_state, restored_version).
    strict=False: see restore_state_from_flat (warm start)."""
    flat, version = load_checkpoint(checkpoint_dir, version)
    return restore_state_from_flat(state, flat, strict=strict), version
