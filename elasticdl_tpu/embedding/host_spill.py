"""Host-spill embedding engine: tables too large for HBM.

The third tier of the sparse embedding design (embedding/layer.py holds
HBM-sharded tables; this holds host-DRAM tables), playing the role PS
pod memory played in the reference: rows live host-side
(native/host_embedding.cc store — or its numpy fallback), the device
only ever sees the rows a batch touches.

Two-phase step around the jit-compiled device computation:

    unique_ids, rows, inverse = engine.pull(batch_ids)
    # device: embed = rows[inverse]; forward/backward under jit;
    # grads come back per unique row (dedup already done by pull)
    engine.apply_gradients(unique_ids, row_grads)

Optimizer slots are co-located host-side with constant-zero init
(reference: slot tables use constant init —
ps/embedding_table.py create_embedding_table / OptimizerWrapper)."""

import numpy as np

from elasticdl_tpu.native.host_embedding import HostEmbeddingStore

_SLOT_NAMES = {
    "sgd": (),
    "momentum": ("momentum",),
    "adam": ("m", "v"),
    "adagrad": ("accumulator",),
}


class HostSpillEmbeddingEngine(object):
    def __init__(self, dim, optimizer="adam", seed=0,
                 init_low=-0.05, init_high=0.05, force_python=False,
                 **hyperparams):
        if optimizer not in _SLOT_NAMES:
            raise ValueError(
                "Unknown optimizer %r (supported: %s)"
                % (optimizer, sorted(_SLOT_NAMES))
            )
        self.dim = dim
        self.optimizer = optimizer
        self.hyperparams = hyperparams
        self._ctor_kwargs = dict(
            seed=seed, init_low=init_low, init_high=init_high,
            force_python=force_python,
        )
        self.param = HostEmbeddingStore(
            dim, seed=seed, init_low=init_low, init_high=init_high,
            force_python=force_python,
        )
        # slot stores: constant-zero lazy init
        self.slots = {
            name: HostEmbeddingStore(
                dim, seed=seed, init_low=0.0, init_high=0.0,
                force_python=force_python,
            )
            for name in _SLOT_NAMES[optimizer]
        }
        self._step = 0

    def fresh_clone(self):
        """A NEW empty engine with this one's configuration — used to
        restore checkpoint state without mutating live stores
        (api/exporter.export_from_checkpoint)."""
        return HostSpillEmbeddingEngine(
            self.dim, optimizer=self.optimizer, **self._ctor_kwargs,
            **self.hyperparams,
        )

    # ------------------------------------------------------------- pull

    def pull(self, ids):
        """Dedup `ids` (any shape) and fetch their rows.

        Returns (unique_ids [k], rows [k, dim] float32, inverse with
        the original shape) so the device computes
        `rows[inverse]` — the dedup the reference worker does before
        talking to the PS (worker.py:505-617)."""
        ids = np.asarray(ids, np.int64)
        unique_ids, inverse = np.unique(ids, return_inverse=True)
        rows = self.param.lookup(unique_ids)
        return unique_ids, rows, inverse.reshape(ids.shape)

    # ------------------------------------------------------- apply grads

    def apply_gradients(self, unique_ids, row_grads, lr=None, lr_scale=1.0):
        """Apply per-unique-row gradients with the engine's optimizer.
        Only these rows (and their slots) move. `lr` overrides the
        engine's configured rate; `lr_scale` multiplies it (scheduler
        hook, host_bridge.HostEmbeddingManager.apply)."""
        self._step += 1
        hp = dict(self.hyperparams)
        if lr is not None:
            hp["lr"] = lr
        hp.setdefault("lr", 0.001 if self.optimizer == "adam" else 0.1)
        hp["lr"] = hp["lr"] * float(lr_scale)
        if self.optimizer == "sgd":
            self.param.sgd(unique_ids, row_grads, hp["lr"])
        elif self.optimizer == "momentum":
            self.param.momentum(
                self.slots["momentum"], unique_ids, row_grads,
                hp["lr"], hp.get("momentum", 0.9),
                hp.get("nesterov", False),
            )
        elif self.optimizer == "adam":
            self.param.adam(
                self.slots["m"], self.slots["v"], unique_ids, row_grads,
                hp["lr"], hp.get("beta1", 0.9), hp.get("beta2", 0.999),
                hp.get("eps", 1e-8), step=self._step,
            )
        elif self.optimizer == "adagrad":
            self.param.adagrad(
                self.slots["accumulator"], unique_ids, row_grads,
                hp["lr"], hp.get("eps", 1e-10),
            )

    # ------------------------------------------------------- checkpoint

    def state_dict(self):
        """Exportable state: param + slot rows + step (the re-shardable
        checkpoint payload, reference checkpoint.go SaveModelToCheckpoint
        semantics)."""
        ids, values = self.param.export_rows()
        out = {"step": self._step, "param": (ids, values)}
        for name, store in self.slots.items():
            out[name] = store.export_rows()
        return out

    def load_state_dict(self, state):
        """Restore REPLACES store contents: rows materialized since the
        checkpoint revert to their deterministic lazy-init values, so
        restore-into-used-engine == restore-into-fresh-engine."""
        self._step = int(state["step"])
        ids, values = state["param"]
        self.param.clear()
        self.param.set_rows(ids, values)
        for name, store in self.slots.items():
            ids, values = state[name]
            store.clear()
            store.set_rows(ids, values)
