"""Row-sparse embedding update engine: O(touched rows) per step.

The reference's OptimizerWrapper (ps/optimizer_wrapper.py:70-351) moves
ONLY the embedding rows a minibatch touched — it looks rows + slot values
up from the PS kv store, applies the stock optimizer to those rows, and
writes them back. `make_row_sparse` (sparse_optim.py) reproduces the
*semantics* with a dense update + mask, which costs O(vocab) memory
traffic per step; this module reproduces the *cost model* too:

* the Embedding layer stop-gradients its table and taps the gathered
  rows with a flax perturbation (`Embedding._tap_rows`), so the backward
  pass produces a [batch, ids, dim] row-gradient instead of a dense
  [vocab, dim] scatter-add — nothing O(vocab) is materialized;
* the layer sows the minibatch ids in the `edl_sparse_ids` collection;
* the Trainer excludes tapped tables from the dense optax transform
  (optax.multi_transform with set_to_zero) and instead calls
  `apply_row_updates`: dedup ids (static shapes), gather the touched
  rows and their optimizer-state rows, run the *same* optax transform on
  just those rows, scatter results back in place (donated buffers).

Per-step cost: O(batch_ids * dim) reads/writes regardless of vocab,
which is the Go PS's cost model (go/pkg/ps/optimizer.go per-row kernel
dispatch) rebuilt on XLA gather/scatter.
"""

import jax
import jax.numpy as jnp
import optax
from flax import traverse_util

from elasticdl_tpu.embedding.layer import EMBEDDING_PARAM_NAME
from elasticdl_tpu.ops.embedding_ops import dedup_indexed_slices

# Collection the Embedding layer sows minibatch ids into.
SPARSE_IDS_COLLECTION = "edl_sparse_ids"
# Collection + leaf name of the row-gradient tap (flax perturbations).
PERTURB_COLLECTION = "perturbations"
PERTURB_NAME = "rows"


def sparse_table_paths(perturb_tree):
    """Map each perturbation tap to its embedding-table param path.

    The layer names its tap `rows` at its own module path, so the table
    lives at the same path with leaf name EMBEDDING_PARAM_NAME.
    Returns {table_path_tuple: perturb_path_tuple} (paths are flax
    flatten_dict key tuples within the params / perturbations trees).
    """
    flat = traverse_util.flatten_dict(_plain_dict(perturb_tree))
    out = {}
    for path in flat:
        if path and path[-1] == PERTURB_NAME:
            out[path[:-1] + (EMBEDDING_PARAM_NAME,)] = path
    return out


def _plain_dict(tree):
    try:
        from flax.core import unfreeze

        return unfreeze(tree)
    except Exception:  # already a plain mapping
        return dict(tree)


def path_str(path):
    return "/".join(str(p) for p in path)


def make_label_tree(params, sparse_paths):
    """Per-leaf labels for optax.multi_transform: 'sparse' for tapped
    embedding tables (their dense grads are identically zero — the layer
    stop-gradients the table), 'dense' for everything else. Built with
    tree_map so the label tree's pytree structure matches params
    exactly (dict / FrozenDict agnostic)."""
    sset = {tuple(str(x) for x in p) for p in sparse_paths}

    def label(key_path, _leaf):
        keys = tuple(
            str(getattr(k, "key", getattr(k, "name", k)))
            for k in key_path
        )
        return "sparse" if keys in sset else "dense"

    return jax.tree_util.tree_map_with_path(label, params)


def split_dense_tx(tx, sparse_paths):
    """Wrap `tx` so tapped tables are excluded from the dense update."""
    if not sparse_paths:
        return tx
    sset = set(sparse_paths)
    return optax.multi_transform(
        {"dense": tx, "sparse": optax.set_to_zero()},
        lambda params: make_label_tree(params, sset),
    )


def init_row_opt_states(row_tx, params, sparse_paths):
    """{table_path_str: row_tx.init(table)} — the per-table optimizer
    slots (Adam mu/nu etc.), co-shaped with the table so the sharding
    rules place slot rows next to their embedding rows (the reference
    keeps slot tables on the same PS shard, ps/parameters.py
    create_slot_params)."""
    flat = traverse_util.flatten_dict(_plain_dict(params))
    return {
        path_str(p): row_tx.init(flat[p]) for p in sorted(sparse_paths)
    }


def _get_path(tree, path):
    node = tree
    for k in path:
        node = node[k]
    return node


def _set_path(tree, path, value):
    """Replace one leaf, preserving the tree's exact pytree structure
    (dict vs FrozenDict) so optimizer/sharding trees keep matching."""
    target = tuple(str(p) for p in path)

    def repl(key_path, leaf):
        keys = tuple(
            str(getattr(k, "key", getattr(k, "name", k)))
            for k in key_path
        )
        return value if keys == target else leaf

    return jax.tree_util.tree_map_with_path(repl, tree)


def row_sparse_apply(row_tx, table, row_opt_state, ids, row_grads):
    """Apply `row_tx` to exactly the rows named by `ids`.

    ids: int [n] (may repeat; PADDING_ID/-1 entries are dropped);
    row_grads: [n, dim] gradient wrt the gathered rows.
    Returns (new_table, new_row_opt_state). All data movement is
    O(n * dim); scalar state leaves (step counts) advance globally,
    matching the reference where the wrapped optimizer's `iterations`
    is shared (optimizer_wrapper.py applies through the stock optimizer).
    """
    vocab = table.shape[0]
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    row_grads = row_grads.reshape(ids.shape[0], -1).astype(table.dtype)
    uniq, summed = dedup_indexed_slices(ids, row_grads)
    safe = jnp.clip(uniq, 0, vocab - 1)
    # out-of-range and padding ids must not scatter anywhere: .at[] wraps
    # negatives, so push them past the table and drop
    scatter_ids = jnp.where((uniq < 0) | (uniq >= vocab), vocab, uniq)

    def gather_rows(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == vocab:
            return jnp.take(leaf, safe, axis=0)
        return leaf

    row_params = jnp.take(table, safe, axis=0)
    row_state = jax.tree.map(gather_rows, row_opt_state)
    updates, new_row_state = row_tx.update(summed, row_state, row_params)
    new_table = table.at[scatter_ids].add(
        updates.astype(table.dtype), mode="drop"
    )

    k = uniq.shape[0]

    def scatter_rows(old, new):
        if (
            getattr(old, "ndim", 0) >= 1
            and old.shape[0] == vocab
            and getattr(new, "shape", None) == (k,) + old.shape[1:]
        ):
            return old.at[scatter_ids].set(
                new.astype(old.dtype), mode="drop"
            )
        return new

    new_opt_state = jax.tree.map(scatter_rows, row_opt_state, new_row_state)
    return new_table, new_opt_state


def extract_ids(ids_collection, perturb_path):
    """The sown ids for a tap: sow() stores a 1-tuple per call. The layer
    raises on double calls at init time; a second call that only happens
    under training=True would slip past that guard and sum both call
    sites' gradients into one tap, so fail loudly here too."""
    node = _get_path(_plain_dict(ids_collection), perturb_path[:-1])
    ids = node["ids"]
    if isinstance(ids, (tuple, list)):
        if len(ids) != 1:
            raise ValueError(
                "sparse-grad Embedding at %r was called %d times in one "
                "forward; its row gradients cannot be attributed. Use one "
                "layer instance per call site or set sparse_grads=False."
                % ("/".join(perturb_path[:-1]), len(ids))
            )
        ids = ids[0]
    return ids


def apply_flat_row_updates(row_tx, params, embed_opt_state, staged,
                           sparse_paths):
    """Row-sparse update from pre-flattened (ids, grads) per table —
    the macro-step application of gradient accumulation (the trainer
    stages each microbatch's row grads host-side and applies the
    concatenation once per cycle; dedup_indexed_slices inside
    row_sparse_apply sums repeats across microbatches).

    staged: {table_path_str: (ids [m], grads [m, dim])}.
    Returns (new_params, new_embed_opt_state).
    """
    new_params = params
    new_embed = dict(embed_opt_state)
    for table_path, _ in sorted(sparse_paths.items()):
        key = path_str(table_path)
        ids, grads = staged[key]
        new_table, new_state = row_sparse_apply(
            row_tx, _get_path(params, table_path), embed_opt_state[key],
            ids, grads,
        )
        new_params = _set_path(new_params, table_path, new_table)
        new_embed[key] = new_state
    return new_params, new_embed


def apply_row_updates(row_tx, params, embed_opt_state, perturb_grads,
                      ids_collection, sparse_paths):
    """Run the row-sparse update for every tapped table.

    params: full params tree (tables still at their original paths);
    perturb_grads: grads of the perturbation tree (dL/d gathered rows);
    ids_collection: the sown `edl_sparse_ids` collection from the same
    forward. Returns (new_params, new_embed_opt_state).
    """
    new_params = params
    new_embed = dict(embed_opt_state)
    pg_flat = traverse_util.flatten_dict(_plain_dict(perturb_grads))
    for table_path, perturb_path in sorted(sparse_paths.items()):
        key = path_str(table_path)
        table = _get_path(params, table_path)
        ids = extract_ids(ids_collection, perturb_path)
        grads = pg_flat[perturb_path]
        new_table, new_state = row_sparse_apply(
            row_tx, table, embed_opt_state[key], ids, grads
        )
        new_params = _set_path(new_params, table_path, new_table)
        new_embed[key] = new_state
    return new_params, new_embed
