"""Row-sparse optimizer semantics for embedding tables.

The reference's OptimizerWrapper (ps/optimizer_wrapper.py:70-351) makes a
stock optimizer update ONLY the embedding rows a minibatch touched, together
with their slot values (Adam m/v etc.); untouched rows and slots don't move.
A plain dense optax update over a [vocab, dim] table violates that: Adam
moves every row each step (moment decay + bias correction), and so would
weight decay.

``make_row_sparse(tx)`` wraps ANY optax GradientTransformation with the same
sparse contract, fully vectorized for XLA (no data-dependent shapes):

* rows whose gradient is exactly zero (i.e. not gathered this step — gather
  backward writes exact zeros elsewhere) keep their parameter value;
* optimizer-state leaves that mirror an embedding param (mu/nu/trace/…)
  keep their previous value on untouched rows;
* scalar state (step counts) advances globally, matching the reference where
  the wrapped Keras optimizer's `iterations` is global
  (optimizer_wrapper.py applies through the stock optimizer).

Identification is structural: a pytree leaf belongs to an embedding table iff
its key path ends with the embedding param's path (optax state subtrees
mirror the params tree), keyed on EMBEDDING_PARAM_NAME.
"""

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.embedding.layer import is_embedding_path


def _keystr(path):
    return jax.tree_util.keystr(path)


def _embedding_suffixes(params):
    """Key-path strings of embedding-table leaves within the params tree."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        if is_embedding_path(path):
            out.append((_keystr(path), getattr(leaf, "shape", ())))
    return out


def _row_mask(grad):
    """[vocab, 1, ...] bool: True where any element of the row is nonzero."""
    axes = tuple(range(1, grad.ndim))
    return jnp.any(grad != 0, axis=axes, keepdims=True)


def make_row_sparse(tx):
    """Wrap an optax transform with row-sparse embedding-table updates.

    No-op (beyond a cheap path scan) for models without embedding tables.
    """

    def init(params):
        return tx.init(params)

    def update(grads, state, params=None):
        suffixes = _embedding_suffixes(grads)
        if not suffixes:
            return tx.update(grads, state, params)

        # row masks keyed by the embedding leaf's params-tree path string
        masks = {}
        shapes = dict(suffixes)
        for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
            ks = _keystr(path)
            if ks in shapes:
                masks[ks] = _row_mask(leaf)
        # longest suffix first, so nested paths can't shadow each other
        ordered = sorted(masks, key=len, reverse=True)

        def mask_for(path, leaf):
            ks = _keystr(path)
            for suffix in ordered:
                if ks.endswith(suffix) and (
                    getattr(leaf, "ndim", 0) >= 1
                    and leaf.shape[0] == shapes[suffix][0]
                ):
                    return masks[suffix]
            return None

        updates, new_state = tx.update(grads, state, params)

        def mask_update(path, upd):
            m = mask_for(path, upd)
            if m is None:
                return upd
            return jnp.where(m, upd, jnp.zeros_like(upd))

        updates = jax.tree_util.tree_map_with_path(mask_update, updates)

        old_leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        new_leaves = jax.tree_util.tree_flatten_with_path(new_state)[0]
        merged = []
        for (old_path, old_leaf), (new_path, new_leaf) in zip(
            old_leaves, new_leaves
        ):
            m = mask_for(new_path, new_leaf)
            if m is not None and getattr(old_leaf, "shape", None) == getattr(
                new_leaf, "shape", None
            ):
                merged.append(jnp.where(m, new_leaf, old_leaf))
            else:
                merged.append(new_leaf)
        new_state = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(new_state), merged
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)
