"""Host-spill embedding bridge: trains models whose embedding tables live
in host DRAM (embedding/host_spill.HostSpillEmbeddingEngine) — the third
storage tier after replicated-HBM and sharded-HBM tables.

This is the TPU-native integration of the reference's PS-resident
embedding path (ps/embedding_table.py:23-136 + worker.py:380-409
pull_embedding_vectors / :505-617 report_gradient_to_ps): where the
reference worker RPC'd rows out of PS pod memory before the forward and
RPC'd row gradients back after the backward, here the host side of the
*same process* pulls rows out of the C++ host store before the compiled
step and applies row gradients after it:

    features = manager.prepare(features)   # pull + dedup, host-side
    state, loss, host_grads = compiled_train_step(...)
    manager.apply(host_grads)              # native row optimizer update

Inside the jit step the pulled rows are an ordinary *differentiable
input* (`<table>.rows` [cap, dim]): the backward of `rows[idx]` is the
scatter-add XLA inserts, so the per-unique-row gradient needs no custom
machinery at all — `jax.grad` w.r.t. the rows input IS the deduped row
gradient the reference assembled by hand (tensor_utils
deduplicate_indexed_slices).

Static shapes: the pulled-row count varies per batch, so rows are padded
to a fixed cap (the id tensor's size rounded up), keeping one compiled
step. Multi-host SPMD: `HostEmbeddingManager.enable_spmd` partitions the
id space over hosts (owner_of) so capacity scales with the fleet — see
the class docstring; HBM sharding (parallel/sharding.py) remains the
default home for big tables.
"""

import numpy as np
from flax import linen as nn
import jax.numpy as jnp

from elasticdl_tpu.embedding.layer import PADDING_ID, combine_gathered

# Feature-key suffixes the manager adds and HostEmbedding consumes.
ROWS_SUFFIX = ".rows"
IDX_SUFFIX = ".idx"

# Checkpoint key prefix for engine state (merged into the sharded
# checkpoint's flat {keystr: ndarray} map, checkpoint/saver.py).
CKPT_PREFIX = ".host_embeddings"


class HostEmbedding(nn.Module):
    """Model-side lookup over pre-pulled host rows.

    A drop-in for embedding.Embedding when the table is registered with a
    HostEmbeddingManager under `table`: reads `<table>.rows` (the pulled
    unique rows) and `<table>.idx` (each id slot's row index) from the
    features dict the manager prepared. With a combiner, `ids_feature`
    names the raw padded-ragged id tensor used for the PADDING_ID mask
    (reference Embedding._sparse_input_call semantics).
    """

    table: str
    ids_feature: str = None
    combiner: str = None

    @nn.compact
    def __call__(self, features, weights=None):
        rows = jnp.asarray(features[self.table + ROWS_SUFFIX])
        idx = jnp.asarray(features[self.table + IDX_SUFFIX])
        gathered = jnp.take(rows, idx, axis=0)
        if self.combiner is None:
            return gathered
        if self.ids_feature is None:
            raise ValueError(
                "HostEmbedding(table=%r): combiner=%r needs ids_feature "
                "for the padding mask" % (self.table, self.combiner)
            )
        ids = jnp.asarray(features[self.ids_feature])
        return combine_gathered(
            gathered, ids, combiner=self.combiner, weights=weights
        )


class _HostTable(object):
    def __init__(self, name, ids_feature, engine):
        self.name = name
        self.ids_feature = ids_feature
        self.engine = engine
        self.last_unique = None


def _round_up(n, k):
    return ((n + k - 1) // k) * k


def owner_of(ids, num_partitions):
    """Host partition owning each id: id % num_partitions — the same
    scatter rule the reference used to spread ids over PS pods
    (elasticdl/python/common/hash_utils.py:17-27 int_to_id). With the
    splitmix64-deterministic lazy init (native/host_embedding.cc), the
    owner materializes an id's initial row identically on every host, so
    the partitioning needs no coordination."""
    return np.asarray(ids, np.int64) % int(num_partitions)


class HostEmbeddingManager(object):
    """Owns the host engines and the pull/apply halves of the step.

    Two modes:
    * single-process (default): each batch's unique rows are pulled from
      the local store and fed to the step as `<table>.rows` / `.idx`.
    * SPMD multi-host (enable_spmd): the id space is partitioned over
      hosts (owner_of) the way the reference scattered ids over PS pods;
      each host stores and updates ONLY its owned rows, so embedding
      capacity scales with the worker fleet (the reference's
      parameter_server.md:42-78 scaling property). Per round, hosts
      allgather the batch's candidate ids, each pulls its owned subset,
      and the global `rows` feature is assembled batch-sharded with
      `idx` pointing at GLOBAL row positions; the row-gradient output is
      replicated, so each host applies exactly its owned slice.
    """

    def __init__(self, pad_multiple=8):
        self._tables = {}
        self.pad_multiple = int(pad_multiple)
        self._spmd_ctx = None
        # gradient-accumulation staging: {table: [(ids, grads), ...]}
        self._staged = {}

    def register(self, name, ids_feature, engine):
        if name in self._tables:
            raise ValueError("host table %r already registered" % name)
        self._tables[name] = _HostTable(name, ids_feature, engine)
        return self

    def enable_spmd(self, ctx):
        """Switch to id-partitioned multi-host mode (no-op for a
        single-process context: the local path is already exact)."""
        self._spmd_ctx = ctx if ctx.is_multiprocess else None
        return self

    @property
    def spmd_ctx(self):
        return self._spmd_ctx

    def __bool__(self):
        return bool(self._tables)

    def tables(self):
        return dict(self._tables)

    def fresh_clone(self):
        """A NEW manager with the same registrations but fresh, empty
        engines — for restoring checkpoint state without touching the
        live stores (engines mutate in place)."""
        clone = HostEmbeddingManager(pad_multiple=self.pad_multiple)
        for name, t in self._tables.items():
            clone.register(name, t.ids_feature, t.engine.fresh_clone())
        clone._spmd_ctx = self._spmd_ctx
        return clone

    def rows_keys(self):
        """Feature keys holding differentiable pulled rows, sorted for a
        stable compiled-signature order."""
        return tuple(sorted(n + ROWS_SUFFIX for n in self._tables))

    # -------------------------------------------------------------- pull

    def prepare(self, features):
        """Pull + dedup each registered table's rows for this batch.

        Returns a new features dict with `<table>.rows` [cap, dim] f32 and
        `<table>.idx` (id-tensor shape, int32) added. PADDING_ID ids map
        to row 0 — their gradient contribution is zeroed by the combiner
        mask / the model's own mask, exactly like the reference's padded
        lookups (embedding_delegate.py safe lookup).
        """
        if self._spmd_ctx is not None:
            return self._prepare_spmd(features)
        features = dict(features)
        for name, t in self._tables.items():
            ids = np.asarray(features[t.ids_feature])
            clean = np.where(ids == PADDING_ID, 0, ids).astype(np.int64)
            unique_ids, rows, inverse = t.engine.pull(clean)
            cap = _round_up(max(int(ids.size), 1), self.pad_multiple)
            padded = np.zeros((cap, t.engine.dim), np.float32)
            padded[: unique_ids.size] = rows
            features[name + ROWS_SUFFIX] = padded
            features[name + IDX_SUFFIX] = inverse.astype(np.int32)
            t.last_unique = unique_ids
        return features

    def _spmd_cap(self, total_slots):
        """Static per-host row capacity: must hold the worst case (every
        global id slot unique AND owned by one host) and divide evenly
        over the batch sharding's dim-0 partitions after the nproc
        blocks are concatenated."""
        ctx = self._spmd_ctx
        unit = self.pad_multiple * ctx.batch_partitions
        return _round_up(max(int(total_slots), 1), unit)

    def _prepare_spmd(self, features):
        """Multi-host prepare: one host-level allgather of the batch's
        ids per table, then each host pulls only the globally-unique ids
        it OWNS. `<table>.rows` is this host's padded owned block (the
        SPMD assemble concatenates the blocks batch-sharded), and
        `<table>.idx` maps every local id slot to its row's GLOBAL
        position. Every host must call this the same number of times per
        round (the allgather is a host collective) — the lockstep loop
        guarantees that."""
        ctx = self._spmd_ctx
        nproc, rank = ctx.num_processes, ctx.process_index
        features = dict(features)
        # one allgather + partition per DISTINCT ids_feature: tables
        # sharing an id tensor (e.g. deepfm's embedding + id-bias) must
        # not pay the host collective twice per step
        shared = {}
        for name, t in self._tables.items():
            if t.ids_feature not in shared:
                ids = np.asarray(features[t.ids_feature])
                clean = np.where(
                    ids == PADDING_ID, 0, ids
                ).astype(np.int64)
                uniq = np.unique(ctx.allgather(clean))  # sorted; same
                # on every host
                owners = owner_of(uniq, nproc)
                owned = [uniq[owners == p] for p in range(nproc)]
                cap = self._spmd_cap(int(clean.size) * nproc)
                pos = ctx.rows_positions(nproc * cap)
                # global row position of every globally-unique id:
                # owner p's j-th owned id sits at p's j-th local row
                # (uniq[owners==p] IS owned[p], in order)
                uniq_pos = np.zeros(uniq.size, np.int64)
                for p in range(nproc):
                    uniq_pos[owners == p] = pos[p][: owned[p].size]
                idx = uniq_pos[np.searchsorted(uniq, clean)]
                shared[t.ids_feature] = (owned[rank], cap, idx)
            mine, cap, idx = shared[t.ids_feature]
            padded = np.zeros((cap, t.engine.dim), np.float32)
            if mine.size:
                _, rows, _ = t.engine.pull(mine)
                padded[: mine.size] = rows
            features[name + ROWS_SUFFIX] = padded
            features[name + IDX_SUFFIX] = idx.astype(np.int32)
            t.last_unique = mine
        return features

    # ------------------------------------------------------------- apply

    def pending_row_count(self):
        """Rows the NEXT apply()/stage() would update (unique pulled ids
        across tables, from the last prepare) — the denominator the
        Trainer's tier-health counters use when an apply fails and those
        row updates are dropped."""
        return sum(
            t.last_unique.size
            for t in self._tables.values()
            if t.last_unique is not None
        )

    def staged_row_count(self):
        """Row updates held in the accumulation buffer (all staged
        microbatches, repeats included) — at risk if the macro-boundary
        apply_staged fails. The Trainer snapshots this BEFORE
        apply_staged (which drains the buffer up front) so the drop
        counter covers the whole lost cycle; a failed stage() loses
        only the current microbatch's pending rows, counted
        separately."""
        return sum(
            ids.size
            for pairs in self._staged.values()
            for ids, _ in pairs
        )

    def apply(self, host_grads, lr_scale=1.0):
        """Apply the step's row gradients ({rows_key: [cap, dim]}, the
        grads of the compiled step w.r.t. the pulled rows) through each
        engine's native optimizer. Must follow the prepare() that fed the
        same step. `lr_scale` multiplies each engine's own lr — the LR
        scheduler the Trainer compiled into the dense chain applies to
        host rows through this knob."""
        # Materialize EVERY table's gradients before mutating ANY engine:
        # np.asarray is where async device errors surface, keeping the
        # common failure out of the mutation loop. A failure INSIDE an
        # engine's in-place update (realistically only host OOM) can
        # still leave later tables un-stepped — the Trainer therefore
        # never retries an apply (trainer.train_step logs and moves on),
        # so a partial step degrades to "those rows missed one update"
        # rather than double-applying.
        staged = self._local_row_grads(host_grads)
        for t, grads in staged:
            t.engine.apply_gradients(
                t.last_unique, grads, lr_scale=lr_scale
            )

    # ------------------------------------------- gradient accumulation

    def _local_row_grads(self, host_grads):
        """Materialize each table's row grads for THIS host (SPMD mode
        slices the replicated global output down to the owned block)."""
        ctx = self._spmd_ctx
        out = []
        for name, t in self._tables.items():
            if t.last_unique is None:
                raise RuntimeError(
                    "apply()/stage() before prepare() for host table %r"
                    % name
                )
            grads = np.asarray(host_grads[name + ROWS_SUFFIX])
            if ctx is not None:
                grads = grads[ctx.rows_positions(grads.shape[0])[
                    ctx.process_index]]
            out.append((t, grads[: t.last_unique.size]))
        return out

    def stage(self, host_grads, weight=1.0):
        """Accumulate one microbatch's row grads (times `weight`, e.g.
        1/accum_steps so the macro apply is the mean) without touching
        the engines. Paired with apply_staged at the macro boundary.
        Staged grads live in process memory only: a preemption inside an
        accumulation cycle drops the partial cycle — the same
        miss-one-update degradation the non-accumulated apply path
        accepts on failure."""
        for t, grads in self._local_row_grads(host_grads):
            self._staged.setdefault(t.name, []).append(
                (t.last_unique.copy(), grads * weight)
            )

    def apply_staged(self, lr_scale=1.0):
        """Apply all staged microbatches in ONE engine update per table
        (dedup-summed across microbatches), advancing each engine's step
        once per macro step — the schedule every other tier follows."""
        from elasticdl_tpu.common.tensor_utils import (
            deduplicate_indexed_slices,
        )

        staged, self._staged = self._staged, {}
        for name, t in self._tables.items():
            pairs = staged.get(name, [])
            if not pairs:
                continue
            ids = np.concatenate([p[0] for p in pairs])
            grads = np.concatenate([p[1] for p in pairs])
            summed, uniq = deduplicate_indexed_slices(grads, ids)
            t.engine.apply_gradients(uniq, summed, lr_scale=lr_scale)

    # -------------------------------------------------------- checkpoint

    def _ckpt_base(self, name):
        """Checkpoint key base for a table. In SPMD mode the keys carry
        the host partition (``.partP``): each host's flat map holds only
        its owned rows, and the saver routes these process-local leaves
        into a shard file this process writes (checkpoint/saver.py)."""
        base = "%s['%s']" % (CKPT_PREFIX, name)
        if self._spmd_ctx is not None:
            base += ".part%d" % self._spmd_ctx.process_index
        return base

    def flat_state(self):
        """Engine state as checkpoint leaves {keystr: ndarray}, merged
        into the sharded checkpoint next to the TrainState leaves."""
        out = {}
        for name, t in self._tables.items():
            sd = t.engine.state_dict()
            base = self._ckpt_base(name)
            out[base + ".step"] = np.asarray(sd["step"], np.int64)
            for key, value in sd.items():
                if key == "step":
                    continue
                ids, values = value
                out["%s.%s.ids" % (base, key)] = np.asarray(ids)
                out["%s.%s.values" % (base, key)] = np.asarray(values)
        return out

    def load_flat_state(self, flat):
        """Inverse of flat_state(); restore REPLACES engine contents
        (host_spill.load_state_dict semantics).

        Re-partitions on load: all ``.partP`` blocks present in the
        merged checkpoint (load_checkpoint merges every shard file) are
        concatenated, then filtered to the ids THIS host owns under the
        current process count — so a checkpoint written by M hosts
        restores onto N hosts, the same re-shard-on-load property the
        HBM tiers have."""
        import re

        for name, t in self._tables.items():
            base = "%s['%s']" % (CKPT_PREFIX, name)
            esc = re.escape(base)
            part_re = re.compile(esc + r"(\.part\d+)?\.step$")
            bases = sorted(
                m.group(0)[: -len(".step")]
                for m in (part_re.match(k) for k in flat)
                if m
            )
            if not bases:
                raise KeyError(
                    "checkpoint has no host-embedding state for table %r"
                    % name
                )
            step = max(int(flat[b + ".step"]) for b in bases)
            state = {"step": step}
            for key in ["param"] + list(t.engine.slots):
                ids = np.concatenate(
                    [np.atleast_1d(flat["%s.%s.ids" % (b, key)])
                     for b in bases]
                )
                values = np.concatenate(
                    [np.atleast_2d(flat["%s.%s.values" % (b, key)])
                     for b in bases]
                ) if ids.size else np.zeros((0, t.engine.dim), np.float32)
                if self._spmd_ctx is not None and ids.size:
                    sel = owner_of(
                        ids, self._spmd_ctx.num_processes
                    ) == self._spmd_ctx.process_index
                    ids, values = ids[sel], values[sel]
                state[key] = (ids, values)
            t.engine.load_state_dict(state)


def build_manager_from_spec(spec, force_python=False):
    """Construct a HostEmbeddingManager from the zoo convention: a module
    -level `host_embeddings()` returning

        {table_name: dict(ids_feature=..., dim=..., optimizer="adam",
                          <hyperparams>)}

    Returns None when the spec declares no host tables. (The reference's
    analogue is the model handler auto-moving Embedding layers to the PS;
    host placement here is an explicit model declaration, because HBM
    sharding — not host DRAM — is the default home for big tables.)
    """
    from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine

    fn = getattr(spec, "host_embeddings_fn", None)
    if fn is None:
        return None
    config = fn()
    if not config:
        return None
    manager = HostEmbeddingManager()
    for name, cfg in config.items():
        cfg = dict(cfg)
        ids_feature = cfg.pop("ids_feature")
        dim = cfg.pop("dim")
        engine = HostSpillEmbeddingEngine(
            dim, force_python=force_python, **cfg
        )
        manager.register(name, ids_feature, engine)
    return manager


def attach_from_spec(trainer, spec, force_python=False):
    """Build the manager a spec declares (if any) and attach it to the
    trainer. The single wiring point shared by Worker and LocalExecutor.
    Returns the manager or None."""
    manager = build_manager_from_spec(spec, force_python=force_python)
    if manager:
        trainer.attach_host_embeddings(manager)
    return manager


def restore_host_state(manager, checkpoint_dir, version=None):
    """Restore engine state from a checkpoint that was written with the
    manager's flat_state() merged in (see CheckpointSaver extra_state_fn).

    Callers that already restored the TrainState should prefer
    restore_with_host_state (ONE checkpoint read, one version).
    """
    from elasticdl_tpu.checkpoint.saver import load_checkpoint

    flat, version = load_checkpoint(checkpoint_dir, version)
    manager.load_flat_state(flat)
    return version


def restore_with_host_state(state, manager, checkpoint_dir, version=None):
    """Restore the TrainState AND (when `manager` is truthy) the host
    engines from one checkpoint read — the shared resume path for Worker
    and LocalExecutor. A single load also pins both tiers to the same
    version: resolving "latest" twice could straddle a concurrent save
    and mix dense params from version N with host rows from N+k.
    Returns (new_state, version)."""
    from elasticdl_tpu.checkpoint.saver import (
        load_checkpoint,
        restore_state_from_flat,
    )

    flat, version = load_checkpoint(checkpoint_dir, version)
    new_state = restore_state_from_flat(state, flat)
    if manager:
        manager.load_flat_state(flat)
    return new_state, version
