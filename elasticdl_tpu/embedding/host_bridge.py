"""Host-spill embedding bridge: trains models whose embedding tables live
in host DRAM (embedding/host_spill.HostSpillEmbeddingEngine) — the third
storage tier after replicated-HBM and sharded-HBM tables.

This is the TPU-native integration of the reference's PS-resident
embedding path (ps/embedding_table.py:23-136 + worker.py:380-409
pull_embedding_vectors / :505-617 report_gradient_to_ps): where the
reference worker RPC'd rows out of PS pod memory before the forward and
RPC'd row gradients back after the backward, here the host side of the
*same process* pulls rows out of the C++ host store before the compiled
step and applies row gradients after it:

    features = manager.prepare(features)   # pull + dedup, host-side
    state, loss, host_grads = compiled_train_step(...)
    manager.apply(host_grads)              # native row optimizer update

Inside the jit step the pulled rows are an ordinary *differentiable
input* (`<table>.rows` [cap, dim]): the backward of `rows[idx]` is the
scatter-add XLA inserts, so the per-unique-row gradient needs no custom
machinery at all — `jax.grad` w.r.t. the rows input IS the deduped row
gradient the reference assembled by hand (tensor_utils
deduplicate_indexed_slices).

Static shapes: the pulled-row count varies per batch, so rows are padded
to a fixed cap (the id tensor's size rounded up), keeping one compiled
step. Scope: per-process tables (the reference's PS pods were also
per-pod stores); the SPMD multi-host path shards HBM tables instead
(parallel/sharding.py).
"""

import numpy as np
from flax import linen as nn
import jax.numpy as jnp

from elasticdl_tpu.embedding.layer import PADDING_ID, combine_gathered

# Feature-key suffixes the manager adds and HostEmbedding consumes.
ROWS_SUFFIX = ".rows"
IDX_SUFFIX = ".idx"

# Checkpoint key prefix for engine state (merged into the sharded
# checkpoint's flat {keystr: ndarray} map, checkpoint/saver.py).
CKPT_PREFIX = ".host_embeddings"


class HostEmbedding(nn.Module):
    """Model-side lookup over pre-pulled host rows.

    A drop-in for embedding.Embedding when the table is registered with a
    HostEmbeddingManager under `table`: reads `<table>.rows` (the pulled
    unique rows) and `<table>.idx` (each id slot's row index) from the
    features dict the manager prepared. With a combiner, `ids_feature`
    names the raw padded-ragged id tensor used for the PADDING_ID mask
    (reference Embedding._sparse_input_call semantics).
    """

    table: str
    ids_feature: str = None
    combiner: str = None

    @nn.compact
    def __call__(self, features, weights=None):
        rows = jnp.asarray(features[self.table + ROWS_SUFFIX])
        idx = jnp.asarray(features[self.table + IDX_SUFFIX])
        gathered = jnp.take(rows, idx, axis=0)
        if self.combiner is None:
            return gathered
        if self.ids_feature is None:
            raise ValueError(
                "HostEmbedding(table=%r): combiner=%r needs ids_feature "
                "for the padding mask" % (self.table, self.combiner)
            )
        ids = jnp.asarray(features[self.ids_feature])
        return combine_gathered(
            gathered, ids, combiner=self.combiner, weights=weights
        )


class _HostTable(object):
    def __init__(self, name, ids_feature, engine):
        self.name = name
        self.ids_feature = ids_feature
        self.engine = engine
        self.last_unique = None


def _round_up(n, k):
    return ((n + k - 1) // k) * k


class HostEmbeddingManager(object):
    """Owns the host engines and the pull/apply halves of the step."""

    def __init__(self, pad_multiple=8):
        self._tables = {}
        self.pad_multiple = int(pad_multiple)

    def register(self, name, ids_feature, engine):
        if name in self._tables:
            raise ValueError("host table %r already registered" % name)
        self._tables[name] = _HostTable(name, ids_feature, engine)
        return self

    def __bool__(self):
        return bool(self._tables)

    def tables(self):
        return dict(self._tables)

    def fresh_clone(self):
        """A NEW manager with the same registrations but fresh, empty
        engines — for restoring checkpoint state without touching the
        live stores (engines mutate in place)."""
        clone = HostEmbeddingManager(pad_multiple=self.pad_multiple)
        for name, t in self._tables.items():
            clone.register(name, t.ids_feature, t.engine.fresh_clone())
        return clone

    def rows_keys(self):
        """Feature keys holding differentiable pulled rows, sorted for a
        stable compiled-signature order."""
        return tuple(sorted(n + ROWS_SUFFIX for n in self._tables))

    # -------------------------------------------------------------- pull

    def prepare(self, features):
        """Pull + dedup each registered table's rows for this batch.

        Returns a new features dict with `<table>.rows` [cap, dim] f32 and
        `<table>.idx` (id-tensor shape, int32) added. PADDING_ID ids map
        to row 0 — their gradient contribution is zeroed by the combiner
        mask / the model's own mask, exactly like the reference's padded
        lookups (embedding_delegate.py safe lookup).
        """
        features = dict(features)
        for name, t in self._tables.items():
            ids = np.asarray(features[t.ids_feature])
            clean = np.where(ids == PADDING_ID, 0, ids).astype(np.int64)
            unique_ids, rows, inverse = t.engine.pull(clean)
            cap = _round_up(max(int(ids.size), 1), self.pad_multiple)
            padded = np.zeros((cap, t.engine.dim), np.float32)
            padded[: unique_ids.size] = rows
            features[name + ROWS_SUFFIX] = padded
            features[name + IDX_SUFFIX] = inverse.astype(np.int32)
            t.last_unique = unique_ids
        return features

    # ------------------------------------------------------------- apply

    def apply(self, host_grads, lr_scale=1.0):
        """Apply the step's row gradients ({rows_key: [cap, dim]}, the
        grads of the compiled step w.r.t. the pulled rows) through each
        engine's native optimizer. Must follow the prepare() that fed the
        same step. `lr_scale` multiplies each engine's own lr — the LR
        scheduler the Trainer compiled into the dense chain applies to
        host rows through this knob."""
        # Materialize EVERY table's gradients before mutating ANY engine:
        # np.asarray is where async device errors surface, keeping the
        # common failure out of the mutation loop. A failure INSIDE an
        # engine's in-place update (realistically only host OOM) can
        # still leave later tables un-stepped — the Trainer therefore
        # never retries an apply (trainer.train_step logs and moves on),
        # so a partial step degrades to "those rows missed one update"
        # rather than double-applying.
        staged = []
        for name, t in self._tables.items():
            if t.last_unique is None:
                raise RuntimeError(
                    "apply() before prepare() for host table %r" % name
                )
            grads = np.asarray(host_grads[name + ROWS_SUFFIX])
            staged.append((t, grads[: t.last_unique.size]))
        for t, grads in staged:
            t.engine.apply_gradients(
                t.last_unique, grads, lr_scale=lr_scale
            )

    # -------------------------------------------------------- checkpoint

    def flat_state(self):
        """Engine state as checkpoint leaves {keystr: ndarray}, merged
        into the sharded checkpoint next to the TrainState leaves."""
        out = {}
        for name, t in self._tables.items():
            sd = t.engine.state_dict()
            base = "%s['%s']" % (CKPT_PREFIX, name)
            out[base + ".step"] = np.asarray(sd["step"], np.int64)
            for key, value in sd.items():
                if key == "step":
                    continue
                ids, values = value
                out["%s.%s.ids" % (base, key)] = np.asarray(ids)
                out["%s.%s.values" % (base, key)] = np.asarray(values)
        return out

    def load_flat_state(self, flat):
        """Inverse of flat_state(); restore REPLACES engine contents
        (host_spill.load_state_dict semantics)."""
        for name, t in self._tables.items():
            base = "%s['%s']" % (CKPT_PREFIX, name)
            step_key = base + ".step"
            if step_key not in flat:
                raise KeyError(
                    "checkpoint has no host-embedding state for table %r"
                    % name
                )
            state = {"step": int(flat[step_key])}
            for key in ["param"] + list(t.engine.slots):
                state[key] = (
                    flat["%s.%s.ids" % (base, key)],
                    flat["%s.%s.values" % (base, key)],
                )
            t.engine.load_state_dict(state)


def build_manager_from_spec(spec, force_python=False):
    """Construct a HostEmbeddingManager from the zoo convention: a module
    -level `host_embeddings()` returning

        {table_name: dict(ids_feature=..., dim=..., optimizer="adam",
                          <hyperparams>)}

    Returns None when the spec declares no host tables. (The reference's
    analogue is the model handler auto-moving Embedding layers to the PS;
    host placement here is an explicit model declaration, because HBM
    sharding — not host DRAM — is the default home for big tables.)
    """
    from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine

    fn = getattr(spec, "host_embeddings_fn", None)
    if fn is None:
        return None
    config = fn()
    if not config:
        return None
    manager = HostEmbeddingManager()
    for name, cfg in config.items():
        cfg = dict(cfg)
        ids_feature = cfg.pop("ids_feature")
        dim = cfg.pop("dim")
        engine = HostSpillEmbeddingEngine(
            dim, force_python=force_python, **cfg
        )
        manager.register(name, ids_feature, engine)
    return manager


def attach_from_spec(trainer, spec, force_python=False):
    """Build the manager a spec declares (if any) and attach it to the
    trainer. The single wiring point shared by Worker and LocalExecutor.
    Returns the manager or None."""
    manager = build_manager_from_spec(spec, force_python=force_python)
    if manager:
        trainer.attach_host_embeddings(manager)
    return manager


def restore_host_state(manager, checkpoint_dir, version=None):
    """Restore engine state from a checkpoint that was written with the
    manager's flat_state() merged in (see CheckpointSaver extra_state_fn).

    Callers that already restored the TrainState should prefer
    restore_with_host_state (ONE checkpoint read, one version).
    """
    from elasticdl_tpu.checkpoint.saver import load_checkpoint

    flat, version = load_checkpoint(checkpoint_dir, version)
    manager.load_flat_state(flat)
    return version


def restore_with_host_state(state, manager, checkpoint_dir, version=None):
    """Restore the TrainState AND (when `manager` is truthy) the host
    engines from one checkpoint read — the shared resume path for Worker
    and LocalExecutor. A single load also pins both tiers to the same
    version: resolving "latest" twice could straddle a concurrent save
    and mix dense params from version N with host rows from N+k.
    Returns (new_state, version)."""
    from elasticdl_tpu.checkpoint.saver import (
        load_checkpoint,
        restore_state_from_flat,
    )

    flat, version = load_checkpoint(checkpoint_dir, version)
    new_state = restore_state_from_flat(state, flat)
    if manager:
        manager.load_flat_state(flat)
    return new_state, version
