"""Distributed embedding layer: sharded-HBM tables on the `ep` mesh axis.

The TPU-native replacement for the reference's PS-resident sparse embedding
stack (elasticdl/layers/embedding.py:20-163 + embedding_delegate.py:26-310 +
ps/embedding_table.py:23-136):

* the table is ONE dense [vocab, dim] parameter whose rows are sharded over
  the (`ep`, `fsdp`) mesh axes — the analogue of rows living `id % num_ps`
  across PS pods (hash_utils.int_to_id). XLA inserts the all-to-all that the
  reference's pull_embedding_vectors RPC fan-out did by hand;
* lookups are gathers inside the jit-compiled step; gradients come back as
  (dense) scatter-adds that the row-sparse optimizer wrapper
  (embedding/sparse_optim.py) applies with reference OptimizerWrapper
  semantics (untouched rows and their slots stay untouched);
* ragged/sparse inputs are the padded-dense equivalent of tf.SparseTensor:
  an int id matrix [batch, max_ids] where PADDING_ID (-1) marks absent
  entries — static shapes, which is what keeps the step compiled once;
* combiner sum/mean/sqrtn reproduces the reference `Embedding`'s
  `_sparse_input_call` via safe_embedding_lookup (empty rows → zero vectors,
  the safe_embedding_lookup_sparse re-impl of embedding_delegate.py:108-230).

Lazy row init (ps/embedding_table.py `EmbeddingTable.get`) has no TPU
analogue — XLA arrays are materialized whole — so tables are initialized at
state-init time with the same initializer family; the observable semantics
(initializer distribution, trained values) are preserved.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

# Param name the sharding rules and the row-sparse optimizer key on.
EMBEDDING_PARAM_NAME = "embedding_table"

# Id value marking padding slots in ragged inputs (never a valid row).
PADDING_ID = -1


def get_initializer(name_or_fn):
    """Map reference initializer names (keras strings) to jax initializers.
    'uniform' is keras RandomUniform(-0.05, 0.05) — the one the reference's
    Go PS hard-codes too (go/pkg/common/embedding_table.go:50-54)."""
    if callable(name_or_fn):
        return name_or_fn
    name = (name_or_fn or "uniform").lower()
    if name in ("uniform", "random_uniform"):
        def _keras_uniform(key, shape, dtype=jnp.float32):
            return jax.random.uniform(
                key, shape, dtype, minval=-0.05, maxval=0.05
            )

        return _keras_uniform
    if name in ("normal", "random_normal"):
        return nn.initializers.normal(stddev=0.05)
    if name in ("truncated_normal",):
        return nn.initializers.truncated_normal(stddev=0.05)
    if name in ("glorot_uniform", "xavier_uniform"):
        return nn.initializers.glorot_uniform()
    if name in ("zeros", "zero"):
        return nn.initializers.zeros
    if name in ("ones", "one"):
        return nn.initializers.ones
    raise ValueError("Unknown embeddings_initializer %r" % name_or_fn)


def combine_gathered(gathered, ids, combiner="mean", weights=None):
    """Combiner math over already-gathered rows [B, L, D]; see
    safe_embedding_lookup. Split out so the sparse-grad tap can sit
    between the gather and the (linear-in-rows) combiner."""
    dtype = gathered.dtype
    mask = (ids != PADDING_ID).astype(dtype)
    if weights is not None:
        w = jnp.asarray(weights, dtype) * mask
    else:
        w = mask
    summed = jnp.einsum("bl,bld->bd", w, gathered)
    if combiner == "sum":
        return summed
    denom = jnp.sum(w, axis=1, keepdims=True)  # [B, 1]
    if combiner == "mean":
        pass
    elif combiner == "sqrtn":
        denom = jnp.sqrt(denom)
    else:
        raise ValueError("Unknown combiner %r" % combiner)
    return summed / jnp.maximum(denom, 1e-12)


def safe_embedding_lookup(table, ids, combiner="mean", weights=None):
    """Combined lookup over padded ragged ids (PADDING_ID = absent).

    Parity with the reference's safe_embedding_lookup_sparse re-impl
    (embedding_delegate.py:108-230): rows with no ids yield zero vectors;
    `weights`, when given, weight each id's vector before combining (and the
    mean/sqrtn denominators use weight totals, as in TF).

    table: [vocab, dim]; ids: int [batch, max_ids]; weights: float like ids.
    Returns [batch, dim].
    """
    gathered = jnp.take(table, jnp.maximum(ids, 0), axis=0)  # [B, L, D]
    return combine_gathered(gathered, ids, combiner=combiner,
                            weights=weights)


class Embedding(nn.Module):
    """Flax counterpart of `elasticdl.layers.Embedding`.

    input_dim/output_dim/embeddings_initializer/combiner mirror the reference
    layer's constructor (elasticdl/layers/embedding.py:40-66). Input forms:

    * int ids [batch] or [batch, k] with ``combiner=None`` → embeddings with
      a trailing dim axis appended (keras Embedding behavior);
    * padded ragged ids [batch, max_ids] (PADDING_ID marks absent) with a
      combiner → combined [batch, dim] (the SparseTensor path).
    """

    input_dim: int
    output_dim: int
    embeddings_initializer: str = "uniform"
    combiner: str = None
    param_dtype: jnp.dtype = jnp.float32
    # Row-sparse gradient tap (embedding/sparse_update.py). None = auto:
    # tables >= constants.EMBEDDING_PARTITION_THRESHOLD_BYTES (the global
    # 2 MB default — NOT the Trainer's embedding_partition_threshold
    # kwarg, which governs sharding only) stop-gradient the dense table
    # and expose per-row grads through a flax perturbation, so training
    # cost per step is O(touched rows) instead of O(vocab) — the TPU
    # analogue of the reference auto-moving layers > 2 MB to the PS
    # (common/model_handler.py:98-102). Set True/False to override.
    sparse_grads: bool = None

    @nn.compact
    def __call__(self, ids, weights=None):
        table = self.param(
            EMBEDDING_PARAM_NAME,
            get_initializer(self.embeddings_initializer),
            (self.input_dim, self.output_dim),
            self.param_dtype,
        )
        ids = jnp.asarray(ids)
        if self.combiner is not None and ids.ndim != 2:
            raise ValueError(
                "combiner=%r needs [batch, max_ids] padded ids, got shape %s"
                % (self.combiner, ids.shape)
            )
        sparse = self._sparse_enabled() and self._tap_active()
        lookup_table = jax.lax.stop_gradient(table) if sparse else table
        gathered = jnp.take(lookup_table, jnp.maximum(ids, 0), axis=0)
        if sparse:
            gathered = self._tap_rows(gathered, ids)
        if self.combiner is None:
            return gathered
        return combine_gathered(
            gathered, ids, combiner=self.combiner, weights=weights
        )

    # ------------------------------------------------ sparse-grad tap

    def _sparse_enabled(self):
        if self.sparse_grads is not None:
            return self.sparse_grads
        from elasticdl_tpu.common import constants

        itemsize = jnp.dtype(self.param_dtype).itemsize
        return (
            self.input_dim * self.output_dim * itemsize
            >= constants.EMBEDDING_PARTITION_THRESHOLD_BYTES
        )

    def _tap_active(self):
        """Mirror nn.Module.perturb's activation rule: the tap is live
        during init (collection mutable) and whenever the caller passes
        the perturbations collection to apply. Plain inference applies
        (no perturbations) take the ordinary dense path."""
        if self.scope is None:
            return False
        from elasticdl_tpu.embedding.sparse_update import PERTURB_COLLECTION

        if self.is_mutable_collection(PERTURB_COLLECTION):
            return True
        try:
            return PERTURB_COLLECTION in self.scope.root._variables
        except Exception:
            return False

    def _tap_rows(self, gathered, ids):
        from elasticdl_tpu.embedding.sparse_update import (
            PERTURB_COLLECTION,
            PERTURB_NAME,
            SPARSE_IDS_COLLECTION,
        )

        if self.is_mutable_collection(PERTURB_COLLECTION) and (
            self.scope.has_variable(PERTURB_COLLECTION, PERTURB_NAME)
        ):
            # Same restriction the reference hits: an embedding layer
            # called twice per forward breaks the grad bookkeeping
            # (worker.py:689-699 forces eager mode there; here we fail
            # fast). Instantiate one Embedding per call site instead.
            raise ValueError(
                "sparse-grad Embedding %r called more than once per "
                "forward; use one layer instance per call site or set "
                "sparse_grads=False" % self.name
            )
        out = self.perturb(PERTURB_NAME, gathered)
        self.sow(SPARSE_IDS_COLLECTION, "ids", ids)
        return out


def is_embedding_path(path):
    """True if a pytree key path addresses an embedding table param (or a
    leaf of the per-table row-optimizer state, whose dict key is the
    table's serialized path — embedding/sparse_update.py path_str)."""
    for k in path:
        key = getattr(k, "key", None)
        if key is None:
            key = getattr(k, "name", None)
        if key == EMBEDDING_PARAM_NAME:
            return True
        if isinstance(key, str) and key.endswith("/" + EMBEDDING_PARAM_NAME):
            return True
    return False
