from elasticdl_tpu.embedding.layer import (  # noqa: F401
    EMBEDDING_PARAM_NAME,
    Embedding,
    safe_embedding_lookup,
)
from elasticdl_tpu.embedding.sparse_optim import (  # noqa: F401
    make_row_sparse,
)
