"""Trained-model export / import.

The reference exports a TF SavedModel at train end by rebuilding native Keras
embedding layers and loading checkpoint weights (common/model_handler.py
get_model_to_export, model_handler.py:247-289). The TPU-native artifact is a
self-contained directory:

    <dir>/params.msgpack    flax msgpack of {"params": ..., "model_state": ...}
                            fully gathered (unsharded) — loadable anywhere
    <dir>/meta.json         step/version + param count

plus ``make_serving_fn`` to turn (model, restored variables) into a jitted
inference callable — the serving-signature analogue.
"""

import json
import os

import jax
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import default_logger as logger

PARAMS_FILE = "params.msgpack"
META_FILE = "meta.json"


def _gather_full(tree):
    """Device → host, gathering across processes when sharded."""

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            x = multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(x)

    return jax.tree.map(leaf, tree)


def export_model(model, state, export_dir, host_manager=None):
    """Write the export artifact from a live TrainState. Returns the dir.

    With `host_manager` (embedding/host_bridge), the artifact also
    carries every host-resident table's trained rows — the reference's
    export restored PS-resident embedding rows into the exported model
    (model_handler.py get_model_to_export); here the host tier is the
    PS-resident tier, so serving needs those rows too
    (make_serving_fn re-seeds a manager from them)."""
    os.makedirs(export_dir, exist_ok=True)
    payload = {
        "params": _gather_full(state.params),
        "model_state": _gather_full(dict(state.model_state)),
    }
    if host_manager and jax.process_index() == 0:
        # host stores are process-local and only process 0 serializes, so
        # don't materialize full-table copies on the other processes
        host = {}
        for name, table in host_manager.tables().items():
            ids, values = table.engine.param.export_rows()
            host[name] = {
                "ids": np.asarray(ids, np.int64),
                "values": np.asarray(values, np.float32),
            }
        payload["host_embeddings"] = host
    if jax.process_index() == 0:
        with open(os.path.join(export_dir, PARAMS_FILE), "wb") as f:
            f.write(serialization.to_bytes(payload))
        n_params = sum(
            int(np.prod(x.shape))
            for x in jax.tree.leaves(payload["params"])
        )
        with open(os.path.join(export_dir, META_FILE), "w") as f:
            json.dump(
                {
                    "version": int(state.step),
                    "num_params": n_params,
                    "model_class": type(model).__name__,
                },
                f,
            )
    return export_dir


def export_from_checkpoint(model, template_state, checkpoint_dir, export_dir,
                           host_manager=None):
    """Export the LATEST valid checkpoint (the reference export path reads
    the newest checkpoint, not live PS state — model_handler.py:247-273).
    With `host_manager`, host rows are restored from the SAME checkpoint
    version — into a FRESH clone of the manager, never the caller's
    engines: those mutate in place, and rewinding a live training job's
    host tier to the checkpoint while its dense state stays live would
    silently corrupt subsequent updates."""
    from elasticdl_tpu.embedding.host_bridge import restore_with_host_state

    export_manager = host_manager.fresh_clone() if host_manager else None
    state, version = restore_with_host_state(
        template_state, export_manager, checkpoint_dir
    )
    logger.info("Exporting checkpoint version %d", version)
    return export_model(model, state, export_dir,
                        host_manager=export_manager)


def load_exported(export_dir):
    """Read back {"params": ..., "model_state": ...} plus meta dict."""
    with open(os.path.join(export_dir, PARAMS_FILE), "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    meta = {}
    meta_path = os.path.join(export_dir, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return payload, meta


def make_serving_fn(model, payload, host_manager=None):
    """A jitted features → predictions callable over exported weights.

    Exported host tables (payload["host_embeddings"]) need a manager
    whose registrations match the model (embedding/host_bridge
    build_manager_from_spec): its engines are re-seeded from the
    exported rows and `serve` pulls them per batch outside the jit,
    exactly as in training."""
    variables = {"params": payload["params"], **payload.get("model_state", {})}
    host_rows = payload.get("host_embeddings") or {}
    if host_rows and host_manager is None:
        raise ValueError(
            "exported model carries host-resident tables %s; pass the "
            "spec's HostEmbeddingManager (build_manager_from_spec)"
            % sorted(host_rows)
        )
    if host_manager and not host_rows:
        # a host-tier model whose artifact lacks rows (export_model was
        # called without the manager) must fail HERE with a clear
        # message, not later as a KeyError on '<table>.rows' inside jit
        raise ValueError(
            "manager declares host tables %s but the artifact carries "
            "none — re-export with host_manager passed to export_model"
            % sorted(host_manager.tables())
        )
    if host_rows:
        if set(host_manager.tables()) != set(host_rows):
            # strict equality: a manager table ABSENT from the artifact
            # would otherwise serve lazily-initialized random rows
            raise ValueError(
                "host-table mismatch: artifact has %s, manager has %s"
                % (sorted(host_rows), sorted(host_manager.tables()))
            )
        # NEVER mutate the caller's engines (they may be a live training
        # tier whose slots/step must stay aligned with its rows): serve
        # from a fresh clone seeded with the exported rows
        host_manager = host_manager.fresh_clone()
        tables = host_manager.tables()
        for name, rec in host_rows.items():
            engine = tables[name].engine
            engine.param.set_rows(
                np.asarray(rec["ids"], np.int64),
                np.asarray(rec["values"], np.float32),
            )

    @jax.jit
    def apply_fn(features):
        return model.apply(variables, features, training=False)

    if not host_rows:
        return apply_fn

    def serve(features):
        return apply_fn(host_manager.prepare(dict(features)))

    return serve
