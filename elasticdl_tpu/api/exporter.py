"""Trained-model export / import.

The reference exports a TF SavedModel at train end by rebuilding native Keras
embedding layers and loading checkpoint weights (common/model_handler.py
get_model_to_export, model_handler.py:247-289). The TPU-native artifact is a
self-contained directory:

    <dir>/params.msgpack    flax msgpack of {"params": ..., "model_state": ...}
                            fully gathered (unsharded) — loadable anywhere
    <dir>/meta.json         step/version + param count

plus ``make_serving_fn`` to turn (model, restored variables) into a jitted
inference callable — the serving-signature analogue.
"""

import json
import os

import jax
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import default_logger as logger

PARAMS_FILE = "params.msgpack"
META_FILE = "meta.json"


def _gather_full(tree):
    """Device → host, gathering across processes when sharded."""

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            x = multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(x)

    return jax.tree.map(leaf, tree)


def export_model(model, state, export_dir):
    """Write the export artifact from a live TrainState. Returns the dir."""
    os.makedirs(export_dir, exist_ok=True)
    payload = {
        "params": _gather_full(state.params),
        "model_state": _gather_full(dict(state.model_state)),
    }
    if jax.process_index() == 0:
        with open(os.path.join(export_dir, PARAMS_FILE), "wb") as f:
            f.write(serialization.to_bytes(payload))
        n_params = sum(
            int(np.prod(x.shape))
            for x in jax.tree.leaves(payload["params"])
        )
        with open(os.path.join(export_dir, META_FILE), "w") as f:
            json.dump(
                {
                    "version": int(state.step),
                    "num_params": n_params,
                    "model_class": type(model).__name__,
                },
                f,
            )
    return export_dir


def export_from_checkpoint(model, template_state, checkpoint_dir, export_dir):
    """Export the LATEST valid checkpoint (the reference export path reads
    the newest checkpoint, not live PS state — model_handler.py:247-273)."""
    from elasticdl_tpu.checkpoint import restore_state_from_checkpoint

    state, version = restore_state_from_checkpoint(
        template_state, checkpoint_dir
    )
    logger.info("Exporting checkpoint version %d", version)
    return export_model(model, state, export_dir)


def load_exported(export_dir):
    """Read back {"params": ..., "model_state": ...} plus meta dict."""
    with open(os.path.join(export_dir, PARAMS_FILE), "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    meta = {}
    meta_path = os.path.join(export_dir, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return payload, meta


def make_serving_fn(model, payload):
    """A jitted features → predictions callable over exported weights."""
    variables = {"params": payload["params"], **payload.get("model_state", {})}

    @jax.jit
    def serve(features):
        return model.apply(variables, features, training=False)

    return serve
