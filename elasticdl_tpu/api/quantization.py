"""Weight-only int8 quantization for the decode path.

Autoregressive decode is HBM-bandwidth-bound: every generated token
re-reads all dense kernels plus the KV cache. Symmetric per-output-
channel int8 storage halves the weight traffic (vs bf16; 4x vs fp32)
at negligible quality cost for generation — the dequantize
(`q.astype(compute) * scale`) happens INSIDE the jitted decode program,
so XLA fuses it into the consuming matmul's operand read instead of
materializing a float copy in HBM.

Scope: serving/decode only. Training state is untouched — the
quantized pytree is a derived artifact (`quantize_params`), and
`api.generation` dequantizes transparently when it sees quantized
leaves. The reference has no quantization (or generation) story; this
is net-new surface.
"""

import jax.numpy as jnp
import numpy as np

# marker key: a quantized leaf is the dict
#   {_Q8_KEY: int8 [..., out], _SCALE_KEY: f32 [out],
#    _ITEMSIZE_KEY: python int (source dtype itemsize)}
# Dicts are pytree-internal nodes, so jax.tree utilities, device_put
# and jit tracing all traverse the structure naturally (every leaf is
# an array; the itemsize int is a scalar leaf dequantize ignores).
# Dequantization returns the scale's dtype (float32); the model's
# compute-dtype cast happens inside apply as usual.
_Q8_KEY = "__w8__"
_SCALE_KEY = "__w8_scale__"
_ITEMSIZE_KEY = "__w8_src_itemsize__"


def _quantize_leaf(w):
    """Symmetric per-output-channel (last axis) int8: scale chosen so
    the channel's max-|w| maps to 127. Zero channels get scale 1 (all
    zeros stay zero)."""
    src_itemsize = int(np.asarray(w).dtype.itemsize)
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=tuple(range(w32.ndim - 1)))
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w32 / scale), -127, 127).astype(np.int8)
    # device-side leaves: the upload happens ONCE here, not on every
    # jitted decode call (jit re-transfers numpy arguments per call,
    # which would turn the bandwidth win into a per-call H2D copy)
    return {_Q8_KEY: jnp.asarray(q), _SCALE_KEY: jnp.asarray(scale),
            _ITEMSIZE_KEY: src_itemsize}


def quantize_params(params, min_size=4096):
    """Return a copy of the params pytree with every float kernel of
    ndim >= 2 and size >= min_size replaced by its int8 form. Biases,
    LayerNorm scales, and small tensors stay as-is (their traffic is
    negligible and their dynamic range matters more).

    Quantized leaves land on the default device, replicated — fine for
    the single-chip serving this targets; on a sharded mesh, re-shard
    the returned tree (jax.device_put with your shardings) before use."""
    def visit(node):
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        arr = np.asarray(node)
        # jnp.issubdtype, not np.issubdtype: the extension float dtypes
        # (bfloat16 — the usual TPU param dtype) are not numpy floating
        # subtypes, and the bandwidth win vs bf16 is the headline case
        if (arr.ndim >= 2 and arr.size >= min_size
                and jnp.issubdtype(arr.dtype, jnp.floating)):
            return _quantize_leaf(arr)
        return node

    return visit(params)


def is_quantized(params):
    """True if the pytree contains any int8-quantized leaf."""
    found = []

    def visit(node):
        if isinstance(node, dict):
            if _Q8_KEY in node:
                found.append(True)
                return
            for v in node.values():
                visit(v)

    visit(params)
    return bool(found)


def dequantize_params(params):
    """Inverse of quantize_params; traceable (jnp ops on leaves over a
    static python structure), so calling it at the top of a jitted
    decode program lets XLA fuse the dequantize into each consuming
    matmul instead of writing float weights back to HBM."""
    def visit(node):
        if isinstance(node, dict):
            if _Q8_KEY in node:
                scale = jnp.asarray(node[_SCALE_KEY])
                return node[_Q8_KEY].astype(scale.dtype) * scale
            return {k: visit(v) for k, v in node.items()}
        return node

    return visit(params)


def quantized_bytes(params):
    """(quantized_bytes, original_bytes) for the weight payload — the
    bandwidth-ratio the int8 form buys. Original bytes use the source
    dtype recorded at quantize time (trees quantized before the
    itemsize key existed fall back to float32)."""
    q_total = [0]
    o_total = [0]

    def visit(node):
        if isinstance(node, dict):
            if _Q8_KEY in node:
                q = node[_Q8_KEY]
                src_itemsize = int(node.get(_ITEMSIZE_KEY, 4))
                q_total[0] += q.size + node[_SCALE_KEY].size * 4
                o_total[0] += q.size * src_itemsize
                return
            for v in node.values():
                visit(v)
        else:
            arr = np.asarray(node)
            q_total[0] += arr.nbytes
            o_total[0] += arr.nbytes

    visit(params)
    return q_total[0], o_total[0]
