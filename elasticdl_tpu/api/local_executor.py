"""LocalExecutor: single-process train/eval/predict over a model-zoo spec.

Parity with the reference's elasticdl/python/elasticdl/local_executor.py
(debug path without master/PS pods) — but TPU-native: it drives the same
in-process TaskDispatcher the master uses (tasks stay the unit of work, so
local and distributed runs share semantics) and the same jit-compiled Trainer
(so "local" already means "all local TPU chips via the mesh").
"""

import numpy as np

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.dataset import Dataset, pad_batch
from elasticdl_tpu.common.model_utils import resolve_dataset_fn
from elasticdl_tpu.data.reader.data_reader_factory import create_data_reader
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher, TaskType
from elasticdl_tpu.training.metrics import MetricsAggregator
from elasticdl_tpu.training.trainer import Trainer


class LocalExecutor(object):
    def __init__(
        self,
        model_spec,
        training_data=None,
        validation_data=None,
        prediction_data=None,
        minibatch_size=32,
        num_epochs=1,
        records_per_task=256,
        evaluation_steps=0,
        mesh=None,
        model_params="",
        data_reader_params=None,
        seed=0,
        max_steps=None,
        checkpoint_dir=None,
        checkpoint_steps=0,
        keep_checkpoint_max=0,
        checkpoint_dir_for_init=None,
        grad_accum_steps=1,
        trainable_pattern=None,
        job_state_dir=None,
        fault_injector=None,
    ):
        from elasticdl_tpu.common.platform_utils import (
            honor_jax_platforms_env,
        )

        # before the first backend use (Trainer builds the mesh below):
        # JAX_PLATFORMS=cpu must win over an ambient plugin's override
        honor_jax_platforms_env()
        self.spec = model_spec
        self.minibatch_size = minibatch_size
        self.num_epochs = num_epochs
        self.records_per_task = records_per_task
        self.evaluation_steps = evaluation_steps
        self.max_steps = max_steps
        self._reader_params = data_reader_params or {}
        self.training_data = training_data
        self.validation_data = validation_data
        self.prediction_data = prediction_data
        self.trainer = Trainer(
            model_spec, mesh=mesh, model_params=model_params, seed=seed,
            grad_accum_steps=grad_accum_steps,
            trainable_pattern=trainable_pattern,
        )
        from elasticdl_tpu.embedding.host_bridge import attach_from_spec

        self._host_manager = attach_from_spec(self.trainer, model_spec)
        self.state = None
        self.losses = []
        # same crash-recovery machinery as the distributed master: with
        # a job_state_dir the in-process dispatcher journals task
        # lifecycle, so a killed local run resumes from where it died
        # instead of re-training completed ranges
        self._job_state_dir = job_state_dir
        # fault hooks (common/fault_injection.py): local_get_task /
        # local_report rules let drill tests delay, drop, or SIGKILL the
        # local run at the dispatch boundary
        from elasticdl_tpu.common.fault_injection import FaultInjector

        self._fault_injector = (
            fault_injector or FaultInjector.from_env()
        )
        self._checkpoint_dir_for_init = checkpoint_dir_for_init
        self._checkpoint_saver = None
        if checkpoint_dir and checkpoint_steps:
            from elasticdl_tpu.checkpoint import CheckpointSaver

            self._checkpoint_saver = CheckpointSaver(
                checkpoint_dir,
                checkpoint_steps=checkpoint_steps,
                keep_max_version=keep_checkpoint_max,
                extra_state_fn=(
                    self._host_manager.flat_state
                    if self._host_manager
                    else None
                ),
            )

    def _reader(self, data_origin):
        return create_data_reader(
            data_origin, self.records_per_task, **dict(self._reader_params)
        )

    def _make_dispatcher(self):
        def shards_of(data):
            return self._reader(data).create_shards() if data else {}

        state_store = None
        if self._job_state_dir:
            from elasticdl_tpu.master.state_store import JobStateStore

            state_store = JobStateStore(self._job_state_dir)

        return TaskDispatcher(
            shards_of(self.training_data),
            shards_of(self.validation_data),
            shards_of(self.prediction_data),
            self.records_per_task,
            self.num_epochs,
            state_store=state_store,
        )

    def _task_dataset(self, reader, task, mode):
        ds = Dataset.from_generator(lambda: reader.read_records(task))
        ds = resolve_dataset_fn(self.spec, reader)(
            ds, mode, reader.metadata
        )
        # background-thread prefetch overlaps host parsing with the
        # device step (the worker does the same — worker.py)
        return ds.batch(self.minibatch_size).prefetch(1)

    def _ensure_state(self, batch):
        if self.state is None:
            padded, _ = pad_batch(batch, self.minibatch_size)
            self.state = self.trainer.init_state(padded)
            if self._checkpoint_dir_for_init:
                from elasticdl_tpu.embedding.host_bridge import (
                    restore_with_host_state,
                )

                self.state, version = restore_with_host_state(
                    self.state,
                    self._host_manager,
                    self._checkpoint_dir_for_init,
                )
                logger.info(
                    "Restored model version %d from %s",
                    version, self._checkpoint_dir_for_init,
                )

    def run(self):
        if self.training_data:
            return self.train()
        if self.validation_data:
            return self.evaluate()
        if self.prediction_data:
            return self.predict()
        raise ValueError("No data configured")

    def train(self):
        dispatcher = self._make_dispatcher()
        reader = self._reader(self.training_data)
        eval_reader = (
            self._reader(self.validation_data)
            if self.validation_data
            else None
        )
        stop = False
        while not stop:
            if self._fault_injector is not None:
                self._fault_injector.intercept("local_get_task")
            task_id, task = dispatcher.get("local")
            if task is None:
                break
            for batch in self._task_dataset(reader, task, Mode.TRAINING):
                padded, n = pad_batch(batch, self.minibatch_size)
                self._ensure_state(padded)
                self.state, loss = self.trainer.train_step(
                    self.state, padded, n
                )
                self.losses.append(float(loss))
                if self._checkpoint_saver is not None:
                    self._checkpoint_saver.maybe_save(self.state)
                step = int(self.state.step)
                if (
                    self.evaluation_steps
                    and eval_reader
                    and step % self.evaluation_steps == 0
                ):
                    metrics = self._evaluate_with_reader(eval_reader)
                    logger.info("Eval at step %d: %s", step, metrics)
                if self.max_steps and step >= self.max_steps:
                    dispatcher.stop_training = True
                    stop = True
                    break
            if self._fault_injector is not None:
                self._fault_injector.intercept("local_report")
            dispatcher.report(task_id, True)
        final_metrics = (
            self._evaluate_with_reader(eval_reader) if eval_reader else {}
        )
        if final_metrics:
            logger.info("Final eval: %s", final_metrics)
        return self.state, final_metrics

    def _evaluate_with_reader(self, reader):
        agg = MetricsAggregator(self.spec.eval_metrics_fn())
        for shard_name, (start, n) in reader.create_shards().items():
            from elasticdl_tpu.master.task_dispatcher import Task

            task = Task(shard_name, start, start + n, TaskType.EVALUATION)
            for batch in self._task_dataset(reader, task, Mode.EVALUATION):
                padded, n_true = pad_batch(batch, self.minibatch_size)
                self._ensure_state(padded)
                outputs, labels = self.trainer.evaluate_batch(
                    self.state, padded, n_true
                )
                agg.update(labels, outputs)
        return agg.result()

    def evaluate(self):
        reader = self._reader(self.validation_data)
        return self._evaluate_with_reader(reader)

    def predict(self):
        reader = self._reader(self.prediction_data)
        outputs = []
        for shard_name, (start, n) in reader.create_shards().items():
            from elasticdl_tpu.master.task_dispatcher import Task

            task = Task(shard_name, start, start + n, TaskType.PREDICTION)
            for batch in self._task_dataset(reader, task, Mode.PREDICTION):
                padded, n_true = pad_batch(batch, self.minibatch_size)
                self._ensure_state(padded)
                preds, _ = self.trainer.evaluate_batch(
                    self.state, padded, n_true
                )
                outputs.append(preds)
        result = np.concatenate(outputs, axis=0) if outputs else np.array([])
        if self.spec.prediction_outputs_processor is not None:
            from elasticdl_tpu.worker.prediction_outputs_processor import (
                invoke_processor,
            )

            invoke_processor(self.spec.prediction_outputs_processor, result)
        return result
