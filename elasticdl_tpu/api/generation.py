"""Autoregressive decoding for the sequence model families.

The reference's inference story is batch prediction (PREDICTION tasks →
`Worker._predict_only`); for the net-new LM families this adds the
sequence counterpart: a jit-compiled greedy/temperature decode loop.
One `lax.fori_loop` runs on device — the full forward is recomputed per
step (O(n) forwards of the compiled model; correct and simple — a KV
cache is a layout optimization this API can adopt without changing its
contract), and the causal mask guarantees positions >= i never
influence the token sampled at i.

Works with any zoo model following the sequence convention
(features {"tokens": int32 [b, L]} -> logits [b, L, vocab]).
"""

import jax
import jax.numpy as jnp


def autoregressive_generate(trainer, state, prompt, max_new_tokens,
                            temperature=0.0, seed=0):
    """Generate continuations of `prompt` with the trained model.

    trainer: Trainer whose model maps {"tokens": [b, L]} -> [b, L, V]
             logits (L = the model's static sequence length).
    state:   TrainState from the trainer.
    prompt:  int32 [b, p] with 1 <= p, p + max_new_tokens <= L.
    temperature: 0.0 = greedy argmax; > 0 = categorical sampling.
    Returns int32 [b, p + max_new_tokens].
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    model = trainer.model
    seq_len = getattr(model, "seq_len", None)
    if seq_len is None:
        raise ValueError(
            "model %r has no seq_len attribute; autoregressive_generate "
            "needs the sequence-family convention" % type(model).__name__
        )
    if not getattr(model, "causal", True):
        # e.g. the BERT encoder: bidirectional attention would let every
        # decode step see the zero-padded future positions
        raise ValueError(
            "model %r is not causal; autoregressive decoding needs a "
            "causal (left-to-right) model" % type(model).__name__
        )
    total = p + int(max_new_tokens)
    if max_new_tokens < 1 or p < 1 or total > seq_len:
        raise ValueError(
            "need prompt length >= 1 and max_new_tokens >= 1 with "
            "prompt %d + new %d <= the model's seq_len %d"
            % (p, max_new_tokens, seq_len)
        )

    # One compiled decode per (batch, sampling-mode) — the loop bounds
    # ride as traced scalars (lax.fori_loop accepts them under jit), so
    # every prompt/continuation length reuses the same executable.
    # Variables ride as arguments so params aren't baked in as constants.
    cache = trainer.__dict__.setdefault("_generate_cache", {})
    key = (b, temperature > 0.0, float(temperature))
    decode_fn = cache.get(key)
    if decode_fn is None:
        def decode(variables, tokens, rng, start, stop):
            def body(i, carry):
                tokens, rng = carry
                logits = model.apply(
                    variables, {"tokens": tokens}, training=False
                )
                # logits at position i-1 predict token i
                step_logits = jax.lax.dynamic_slice_in_dim(
                    logits, i - 1, 1, axis=1
                )[:, 0]  # [b, V]
                if temperature > 0.0:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(
                        sub, step_logits / temperature, axis=-1
                    )
                else:
                    nxt = jnp.argmax(step_logits, axis=-1)
                tokens = jax.lax.dynamic_update_slice(
                    tokens, nxt.astype(jnp.int32)[:, None], (0, i)
                )
                return tokens, rng

            tokens, _ = jax.lax.fori_loop(
                start, stop, body, (tokens, rng)
            )
            return tokens

        decode_fn = jax.jit(decode)
        cache[key] = decode_fn

    variables = {"params": state.params, **state.model_state}
    buf = jnp.zeros((b, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
    with trainer.mesh:
        out = decode_fn(
            variables, buf, jax.random.PRNGKey(seed),
            jnp.asarray(p, jnp.int32), jnp.asarray(total, jnp.int32),
        )
    return out[:, :total]
