"""Autoregressive decoding for the sequence model families.

The reference's inference story is batch prediction (PREDICTION tasks →
`Worker._predict_only`); for the net-new LM families this adds the
sequence counterpart: jit-compiled decoding with greedy argmax,
temperature sampling (top-k / nucleus filtered), and beam search
(`beam_search_generate`). Two execution strategies behind
`autoregressive_generate`: the default recomputes the full forward per
step inside a `lax.fori_loop` (simple, zero model requirements beyond
the convention), and `use_cache=True` streams single-token steps
through the model's per-layer KV caches (O(L) attention per token).
The causal mask guarantees positions >= i never influence the token
sampled at i in either strategy.

Works with any zoo model following the sequence convention
(features {"tokens": int32 [b, L]} -> logits [b, L, vocab]).
"""

import jax
import jax.numpy as jnp


class _LRUCache(dict):
    """Insertion-ordered bounded cache for compiled decode fns. Every
    distinct (batch, sampling-knob, length) combination compiles its own
    executable; a sweep over sampling configs or prompt lengths would
    otherwise accumulate compiled programs on the Trainer without bound.
    get() refreshes recency; inserting beyond max_entries evicts the
    least-recently-used entry (its executable is re-compiled on next
    use — correctness is unaffected)."""

    max_entries = 16

    def get(self, key, default=None):
        if key in self:
            val = super().pop(key)
            super().__setitem__(key, val)
            return val
        return default

    def __setitem__(self, key, value):
        if key in self:
            super().pop(key)
        elif len(self) >= self.max_entries:
            super().pop(next(iter(self)))
        super().__setitem__(key, value)


#: recompile-sentry hook (observability/runtime_health.py): the
#: serving engine attaches its sentry here so the offline decode
#: paths' jit caches count their compilations into the same
#: edl_serving_recompiles_total{fn=} family. None = counting off —
#: the executables are plain jax.jit either way.
_SENTRY = None


def set_decode_sentry(sentry):
    """Adopt `sentry` (RecompileSentry or None) for every decode-path
    jit site in this module. Process-global like the compile caches
    themselves: one serving process has one sentry."""
    global _SENTRY
    _SENTRY = sentry


def _tjit(name, fn, **jit_kwargs):
    from elasticdl_tpu.observability.runtime_health import tracked_jit

    return tracked_jit(fn, name, lambda: _SENTRY, **jit_kwargs)


def _decode_cache(trainer):
    return trainer.__dict__.setdefault("_generate_cache", _LRUCache())


def _maybe_dequantize(variables, qz):
    """Weight-only int8 support (api.quantization): dequantize INSIDE
    the jitted decode program — XLA fuses `int8 -> compute * scale`
    into each consuming matmul's operand read, so the weights travel
    HBM->VMEM as int8. `qz` is trace-static (baked into the compiled
    fn; the compile-cache keys carry it)."""
    if not qz:
        return variables
    from elasticdl_tpu.api.quantization import dequantize_params

    return dict(
        variables, params=dequantize_params(variables["params"])
    )


def _filter_logits(logits, top_k, top_p):
    """Standard sampling filters, static-shape: top-k keeps the k
    highest logits per row; nucleus (top-p) keeps the smallest set of
    tokens whose cumulative probability reaches p (always at least the
    argmax). Filtered entries drop to -inf before the categorical.

    Tie semantics (the usual static-shape formulation): every logit
    EQUAL to the k-th value survives top-k (>= k tokens on ties), and
    ties at the nucleus threshold likewise all survive — with float
    logits exact ties are measure-zero, so in practice exactly k."""
    neg = jnp.asarray(-jnp.inf, logits.dtype)
    if top_k and top_k > 0:
        k = min(int(top_k), logits.shape[-1])  # clamp to the vocab
        kth = jnp.sort(logits, axis=-1)[..., -k, None]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        # keep while the mass BEFORE the token is < p (first always kept)
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        thr = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thr, neg, logits)
    return logits


def _next_token(step_logits, rng, position, temperature, top_k=0,
                top_p=1.0):
    """Sample/argmax the token for `position`. The RNG key is derived by
    fold_in(rng, position), NOT by sequentially splitting a stream, so
    the full-forward and KV-cached paths produce identical samples for
    the same (seed, temperature) regardless of how many model steps each
    runs."""
    if temperature > 0.0:
        # temperature first, filters on the ACTUAL sampling
        # distribution (the conventional top-p semantics)
        scaled = step_logits / temperature
        scaled = _filter_logits(scaled, top_k, top_p)
        sub = jax.random.fold_in(rng, position)
        nxt = jax.random.categorical(sub, scaled, axis=-1)
    else:
        nxt = jnp.argmax(step_logits, axis=-1)
    return nxt.astype(jnp.int32)


def serving_next_token(step_logits, seed, position, temperature,
                       top_k=0, top_p=1.0):
    """`_next_token` for the online serving scheduler: `temperature` and
    `seed` ride as TRACED per-slot values (one compiled decode step
    serves every sampling config in the batch), with `top_k`/`top_p`
    static server-level knobs. Token-parity contract with the offline
    sampler, which the serving tests lock: for any fixed temperature,
    the selected token equals `_next_token(step_logits,
    PRNGKey(seed), position, temperature, top_k, top_p)` — greedy is
    the same argmax, and sampling applies the same scale -> filter ->
    fold_in(rng, position) -> categorical pipeline. A request's tokens
    therefore never depend on what else shares the serving batch.

    step_logits: [V] (one slot's logits). Returns a scalar int32."""
    greedy = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
    # the guard keeps the division finite when temperature == 0 (the
    # sampled branch is discarded by the select in that case)
    safe_t = jnp.maximum(temperature, 1e-6)
    scaled = _filter_logits(step_logits / safe_t, top_k, top_p)
    sub = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    sampled = jax.random.categorical(sub, scaled, axis=-1).astype(
        jnp.int32
    )
    return jnp.where(temperature > 0.0, sampled, greedy)


def autoregressive_generate(trainer, state, prompt, max_new_tokens,
                            temperature=0.0, seed=0, use_cache=False,
                            top_k=0, top_p=1.0):
    """Generate continuations of `prompt` with the trained model.

    trainer: Trainer whose model maps {"tokens": [b, L]} -> [b, L, V]
             logits (L = the model's static sequence length).
    state:   TrainState from the trainer.
    prompt:  int32 [b, p] with 1 <= p, p + max_new_tokens <= L.
    temperature: 0.0 = greedy argmax; > 0 = categorical sampling,
             optionally filtered by top_k (keep k highest logits) and/or
             top_p (nucleus: smallest set reaching cumulative prob p).
    use_cache: decode through the model's KV cache (decode=True path,
             one single-token step per position: O(L) attention per
             token instead of a full-sequence forward). Requires the
             model to support decode mode (the transformer_lm family).
             Greedy decoding matches the full-forward path exactly;
             temperature sampling uses the same position-derived RNG
             keys but can diverge where the two paths' logits differ in
             kernel numerics.
    Returns int32 [b, p + max_new_tokens].
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    model = trainer.model
    seq_len = getattr(model, "seq_len", None)
    if seq_len is None:
        raise ValueError(
            "model %r has no seq_len attribute; autoregressive_generate "
            "needs the sequence-family convention" % type(model).__name__
        )
    if not getattr(model, "causal", True):
        # e.g. the BERT encoder: bidirectional attention would let every
        # decode step see the zero-padded future positions
        raise ValueError(
            "model %r is not causal; autoregressive decoding needs a "
            "causal (left-to-right) model" % type(model).__name__
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(
            "top_p must be in (0, 1], got %r (top_p -> 0 keeps nothing; "
            "use temperature=0 for greedy)" % (top_p,)
        )
    if top_k < 0:
        raise ValueError("top_k must be >= 0, got %r" % (top_k,))
    if temperature <= 0.0:
        # greedy ignores the filters; normalize them out of the compile
        # cache keys so greedy configs share one executable
        top_k, top_p = 0, 1.0
    total = p + int(max_new_tokens)
    if max_new_tokens < 1 or p < 1 or total > seq_len:
        raise ValueError(
            "need prompt length >= 1 and max_new_tokens >= 1 with "
            "prompt %d + new %d <= the model's seq_len %d"
            % (p, max_new_tokens, seq_len)
        )

    if use_cache:
        _require_kv_convention(model)
        return _kv_generate(
            trainer, state, prompt, p, total, temperature, seed,
            top_k, top_p,
        )

    # One compiled decode per (batch, sampling-mode) — the loop bounds
    # ride as traced scalars (lax.fori_loop accepts them under jit), so
    # every prompt/continuation length reuses the same executable.
    # Variables ride as arguments so params aren't baked in as constants.
    from elasticdl_tpu.api.quantization import is_quantized

    qz = is_quantized(state.params)
    cache = _decode_cache(trainer)
    key = (b, float(temperature), int(top_k), float(top_p), qz)
    decode_fn = cache.get(key)
    if decode_fn is None:
        def decode(variables, tokens, rng, start, stop):
            variables = _maybe_dequantize(variables, qz)

            def body(i, tokens):
                logits = model.apply(
                    variables, {"tokens": tokens}, training=False
                )
                # logits at position i-1 predict token i
                step_logits = jax.lax.dynamic_slice_in_dim(
                    logits, i - 1, 1, axis=1
                )[:, 0]  # [b, V]
                nxt = _next_token(step_logits, rng, i, temperature,
                                  top_k, top_p)
                return jax.lax.dynamic_update_slice(
                    tokens, nxt[:, None], (0, i)
                )

            return jax.lax.fori_loop(start, stop, body, tokens)

        decode_fn = _tjit("offline_decode_nocache", decode)
        cache[key] = decode_fn

    variables = {"params": state.params, **state.model_state}
    buf = jnp.zeros((b, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
    with trainer.mesh:
        out = decode_fn(
            variables, buf, jax.random.PRNGKey(seed),
            jnp.asarray(p, jnp.int32), jnp.asarray(total, jnp.int32),
        )
    return out[:, :total]


def _prefill_bucket(p, seq_len):
    """Static prefill slab: smallest 64-multiple covering the prompt
    (clamped to the cache capacity). Positions in [p, p_pad) hold pad
    junk in the cache; decode overwrites each before attending to it."""
    return min(seq_len, -(-p // 64) * 64)


def _kv_shapes_for(cache, model, b):
    """Cache-buffer structure from an eval_shape'd decode init (no real
    params are materialized); depends only on the batch size, so it is
    cached separately from the compiled decodes."""
    kv_shapes = cache.get(("kv_shapes", b))
    if kv_shapes is None:
        def init_shapes():
            return model.init(
                jax.random.PRNGKey(0),
                {"tokens": jnp.zeros((b, 1), jnp.int32)},
                training=False, decode=True,
            )

        kv_shapes = jax.eval_shape(init_shapes)["cache"]
        cache[("kv_shapes", b)] = kv_shapes
    return kv_shapes


def kv_row_leaf(leaf, cache_len):
    """THE batch-1 decode-cache leaf convention, in one place: True for
    per-layer KV ROW buffers — `[1, kv_heads, cache_len, ...]` arrays
    (k/v rows, and the int8 format's per-row scales) in a tree from
    `_kv_shapes_for(cache, model, 1)`. These are the leaves the serving
    paged pool (serving/kv_pool.py) re-shapes into block arenas; the
    scalar position counter (and any other non-row state) is NOT a row
    leaf and stays per-sequence."""
    shape = getattr(leaf, "shape", None)
    return (shape is not None and len(shape) == 4 and shape[0] == 1
            and shape[2] == cache_len)


def _run_prefill(model, variables, kv_shapes, tokens2d, p_len, p_pad):
    """Shared batched-prefill contract for the greedy-KV and beam-KV
    paths: zero caches, ONE prefill=True forward over the static
    [:, :p_pad] slab, return (filled cache tree, logits at p_len-1).
    tokens2d: [b, L] int32."""
    b = tokens2d.shape[0]
    kv = jax.tree.map(
        lambda sh: jnp.zeros(sh.shape, sh.dtype), kv_shapes
    )
    logits, upd = model.apply(
        dict(variables, cache=kv),
        {"tokens": tokens2d[:, :p_pad]},
        training=False, prefill=True, prompt_len=p_len,
        mutable=["cache"],
    )
    last = jax.lax.dynamic_slice(
        logits, (0, p_len - 1, 0), (b, 1, logits.shape[-1])
    )[:, 0]  # [b, V]
    return upd["cache"], last


def _require_kv_convention(model):
    """use_cache=True needs BOTH decode mode and the batched-prefill
    mode; a clear error beats a TypeError from inside tracing."""
    import inspect

    params = inspect.signature(type(model).__call__).parameters
    missing = [k for k in ("decode", "prefill") if k not in params]
    if missing:
        raise ValueError(
            "model %r lacks %s mode(s); use_cache=True needs the "
            "KV-cache convention (decode + prefill kwargs — the "
            "transformer_lm family)"
            % (type(model).__name__, "/".join(missing))
        )


def _kv_generate(trainer, state, prompt, p, total, temperature, seed,
                 top_k=0, top_p=1.0):
    """KV-cached decode: batched prefill, then one single-token model
    step per generated position.

    The prompt is prefilled in ONE causal forward (the model's
    prefill=True mode writes every layer's k/v for positions [0, p) in
    a single MXU-friendly pass — the flash kernel runs over the whole
    prompt instead of p-1 tiny single-token steps), then a fori_loop
    with dynamic start runs the per-token decode. The prefill length is
    padded to a 64 bucket so one executable serves nearby prompt
    lengths; compiled once per (batch, total, bucket, sampling mode).
    """
    model = trainer.model
    b = prompt.shape[0]
    seq_len = model.seq_len
    p_pad = _prefill_bucket(p, seq_len)

    from elasticdl_tpu.api.quantization import is_quantized

    qz = is_quantized(state.params)
    cache = _decode_cache(trainer)
    key = ("kv", b, total, p_pad, float(temperature), int(top_k),
           float(top_p), qz)
    fn = cache.get(key)
    if fn is None:
        kv_shapes = _kv_shapes_for(cache, model, b)

        def run(variables, tokens, rng, p_len):
            variables = _maybe_dequantize(variables, qz)
            # ---- batched prefill: fill caches for [0, p), take the
            # logits at p-1, write the first generated token at p
            kv, last = _run_prefill(
                model, variables, kv_shapes, tokens, p_len, p_pad
            )
            nxt = _next_token(last, rng, p_len, temperature,
                              top_k, top_p)
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt.astype(jnp.int32)[:, None], (0, p_len)
            )

            # ---- per-token decode, dynamic start at p (the prefill
            # already produced the token at p): iteration i consumes
            # the token at position i and writes position i+1
            def body(i, carry):
                tokens, kv = carry
                tok = jax.lax.dynamic_slice(tokens, (0, i), (b, 1))
                logits, upd = model.apply(
                    dict(variables, cache=kv),
                    {"tokens": tok},
                    training=False, decode=True, mutable=["cache"],
                )
                nxt = _next_token(logits[:, 0], rng, i + 1, temperature,
                                  top_k, top_p)
                tokens = jax.lax.dynamic_update_slice(
                    tokens, nxt.astype(jnp.int32)[:, None], (0, i + 1)
                )
                return (tokens, upd["cache"])

            tokens, _ = jax.lax.fori_loop(
                p_len, total - 1, body, (tokens, kv)
            )
            return tokens

        fn = _tjit("offline_decode_kv", run)
        cache[key] = fn

    variables = {"params": state.params, **state.model_state}
    buf = jnp.zeros((b, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
    with trainer.mesh:
        out = fn(
            variables, buf, jax.random.PRNGKey(seed),
            jnp.asarray(p, jnp.int32),
        )
    return out[:, :total]


def beam_search_generate(trainer, state, prompt, max_new_tokens,
                         num_beams=4, use_cache=False):
    """Beam-search decoding: keeps the `num_beams` highest-log-
    probability continuations per batch row and returns the best one.
    Deterministic; beams ride as extra batch rows so the compiled model
    is the same one the greedy path uses.

    Initial beam scores are [0, -inf, ...], which both deduplicates the
    first expansion (all beams start as copies of the prompt) and keeps
    every tensor static-shape. Returns int32 [b, p + max_new_tokens].

    use_cache=True: KV-cached strategy — one batched prompt prefill
    (beams share it: the caches are prefilled for b rows and tiled to
    b*num_beams), then single-token decode steps; beam reordering
    gathers the per-layer cache rows along the batch axis each step.
    O(L) attention per token instead of a full forward per step."""
    if use_cache:
        return _beam_kv_generate(trainer, state, prompt, max_new_tokens,
                                 num_beams)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    model = trainer.model
    seq_len = getattr(model, "seq_len", None)
    if seq_len is None or not getattr(model, "causal", True):
        raise ValueError(
            "beam search needs a causal sequence-family model"
        )
    total = p + int(max_new_tokens)
    if max_new_tokens < 1 or p < 1 or total > seq_len:
        raise ValueError(
            "need prompt length >= 1 and max_new_tokens >= 1 with "
            "prompt %d + new %d <= the model's seq_len %d"
            % (p, max_new_tokens, seq_len)
        )
    k = int(num_beams)
    vocab = getattr(model, "vocab_size", None)
    if k < 1 or (vocab is not None and k > vocab):
        raise ValueError(
            "num_beams must be in [1, vocab_size], got %d" % k
        )

    from elasticdl_tpu.api.quantization import is_quantized

    qz = is_quantized(state.params)
    cache = _decode_cache(trainer)
    key = ("beam", b, k, qz)
    fn = cache.get(key)
    if fn is None:
        def run(variables, tokens, start, stop):
            # tokens [b, k, L]; scores [b, k]
            variables = _maybe_dequantize(variables, qz)
            neg = jnp.asarray(-jnp.inf, jnp.float32)
            scores = jnp.where(
                jnp.arange(k)[None, :] == 0, 0.0, neg
            ) * jnp.ones((b, 1), jnp.float32)

            def body(i, carry):
                tokens, scores = carry
                logits = model.apply(
                    variables,
                    {"tokens": tokens.reshape(b * k, -1)},
                    training=False,
                )
                step = jax.nn.log_softmax(
                    jax.lax.dynamic_slice_in_dim(
                        logits, i - 1, 1, axis=1
                    )[:, 0].reshape(b, k, -1).astype(jnp.float32),
                    axis=-1,
                )  # [b, k, V]
                cand = scores[:, :, None] + step
                v = cand.shape[-1]
                vals, idx = jax.lax.top_k(cand.reshape(b, k * v), k)
                beam_src = idx // v  # [b, k]
                tok = (idx % v).astype(jnp.int32)
                tokens = jnp.take_along_axis(
                    tokens, beam_src[:, :, None], axis=1
                )
                tokens = jax.lax.dynamic_update_slice(
                    tokens, tok[..., None], (0, 0, i)
                )
                return tokens, vals

            tokens, scores = jax.lax.fori_loop(
                start, stop, body, (tokens, scores)
            )
            best = jnp.argmax(scores, axis=-1)  # [b]
            return jnp.take_along_axis(
                tokens, best[:, None, None], axis=1
            )[:, 0], scores

        fn = _tjit("offline_beam_nocache", run)
        cache[key] = fn

    variables = {"params": state.params, **state.model_state}
    buf = jnp.zeros((b, k, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(
        buf, jnp.broadcast_to(prompt[:, None, :], (b, k, p)), (0, 0, 0)
    )
    with trainer.mesh:
        out, _ = fn(
            variables, buf,
            jnp.asarray(p, jnp.int32), jnp.asarray(total, jnp.int32),
        )
    return out[:, :total]


def _beam_kv_generate(trainer, state, prompt, max_new_tokens, num_beams):
    """KV-cached beam search (beam_search_generate use_cache=True).

    Same selection math as the full-forward strategy — the [0, -inf]
    initial scores and top-k over (beam, vocab) — so the two strategies
    return identical tokens; only the attention cost differs. The
    prompt is prefilled ONCE for the b true rows (model prefill mode,
    see _kv_generate), the caches are row-tiled to b*num_beams, and
    each step gathers the cache rows of the surviving beams.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    model = trainer.model
    seq_len = getattr(model, "seq_len", None)
    if seq_len is None or not getattr(model, "causal", True):
        raise ValueError(
            "beam search needs a causal sequence-family model"
        )
    _require_kv_convention(model)
    total = p + int(max_new_tokens)
    if max_new_tokens < 1 or p < 1 or total > seq_len:
        raise ValueError(
            "need prompt length >= 1 and max_new_tokens >= 1 with "
            "prompt %d + new %d <= the model's seq_len %d"
            % (p, max_new_tokens, seq_len)
        )
    k = int(num_beams)
    vocab = getattr(model, "vocab_size", None)
    if k < 1 or (vocab is not None and k > vocab):
        raise ValueError(
            "num_beams must be in [1, vocab_size], got %d" % k
        )
    bk = b * k
    p_pad = _prefill_bucket(p, seq_len)

    from elasticdl_tpu.api.quantization import is_quantized

    qz = is_quantized(state.params)
    cache = _decode_cache(trainer)
    key = ("beam_kv", b, k, total, p_pad, qz)
    fn = cache.get(key)
    if fn is None:
        kv_shapes = _kv_shapes_for(cache, model, b)

        def run(variables, tokens, p_len):
            # tokens [b, k, L]; shared prefill on the b true rows
            variables = _maybe_dequantize(variables, qz)
            kv, last = _run_prefill(
                model, variables, kv_shapes, tokens[:, 0], p_len, p_pad
            )
            # beams share the prompt: tile each cache row k times
            kv = jax.tree.map(
                lambda a: (
                    jnp.repeat(a, k, axis=0)
                    if a.ndim and a.shape[0] == b else a
                ),
                kv,
            )
            neg = jnp.asarray(-jnp.inf, jnp.float32)
            scores = jnp.where(
                jnp.arange(k)[None, :] == 0, 0.0, neg
            ) * jnp.ones((b, 1), jnp.float32)

            def expand(i, tokens, scores, kv, step_logits):
                """One beam expansion writing position i: the shared
                top-k over (beam, vocab) + beam gathers."""
                step = jax.nn.log_softmax(
                    step_logits.reshape(b, k, -1).astype(jnp.float32),
                    axis=-1,
                )  # [b, k, V]
                cand = scores[:, :, None] + step
                v = cand.shape[-1]
                vals, idx = jax.lax.top_k(cand.reshape(b, k * v), k)
                beam_src = idx // v  # [b, k]
                tok = (idx % v).astype(jnp.int32)
                tokens = jnp.take_along_axis(
                    tokens, beam_src[:, :, None], axis=1
                )
                tokens = jax.lax.dynamic_update_slice(
                    tokens, tok[..., None], (0, 0, i)
                )
                flat_src = (
                    jnp.arange(b)[:, None] * k + beam_src
                ).reshape(bk)
                kv = jax.tree.map(
                    lambda a: (
                        jnp.take(a, flat_src, axis=0)
                        if a.ndim and a.shape[0] == bk else a
                    ),
                    kv,
                )
                return tokens, vals, kv

            # first expansion (position p) from the prefill logits —
            # the [0, -inf] scores make the beam gather a no-op on the
            # identical tiled caches
            first = jnp.broadcast_to(
                last[:, None, :], (b, k, last.shape[-1])
            ).reshape(bk, -1)
            tokens, scores, kv = expand(p_len, tokens, scores, kv,
                                        first)

            def body(i, carry):
                tokens, scores, kv = carry
                tok = jax.lax.dynamic_slice(
                    tokens.reshape(bk, -1), (0, i - 1), (bk, 1)
                )
                logits, upd = model.apply(
                    dict(variables, cache=kv),
                    {"tokens": tok},
                    training=False, decode=True, mutable=["cache"],
                )
                tokens, scores, kv = expand(
                    i, tokens, scores, upd["cache"], logits[:, 0]
                )
                return tokens, scores, kv

            tokens, scores, _ = jax.lax.fori_loop(
                p_len + 1, total, body, (tokens, scores, kv)
            )
            best = jnp.argmax(scores, axis=-1)  # [b]
            return jnp.take_along_axis(
                tokens, best[:, None, None], axis=1
            )[:, 0]

        fn = _tjit("offline_beam_kv", run)
        cache[key] = fn

    variables = {"params": state.params, **state.model_state}
    buf = jnp.zeros((b, k, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(
        buf, jnp.broadcast_to(prompt[:, None, :], (b, k, p)), (0, 0, 0)
    )
    with trainer.mesh:
        out = fn(variables, buf, jnp.asarray(p, jnp.int32))
    return out[:, :total]


def speculative_generate(trainer, state, draft_trainer, draft_state,
                         prompt, max_new_tokens, gamma=4,
                         return_stats=False):
    """Speculative greedy decoding: a small DRAFT model proposes gamma
    tokens per iteration (cheap single-token KV steps) and the TARGET
    model verifies them in ONE chunked decode step (the model's t>1
    decode mode: one batched cache read for gamma queries). Accepted
    prefix + the target's correction token advance the stream 1..gamma
    positions per target invocation.

    EXACTNESS: output tokens equal plain greedy decoding of the target
    model (same argmax at every position — the draft only affects how
    many target steps are needed, never what they produce; kernel
    reduction-order ULPs aside). Greedy only — temperature sampling
    would need the rejection-sampling correction.

    Cache rollback is counter-only: entries past the rolled-back
    counter are junk that the chunk mask hides and later writes
    overwrite — the same safety argument as the prefill slab.

    Both models follow the KV convention (decode + prefill modes) and
    share the vocabulary; the draft's seq_len must also cover the
    stream. Returns int32 [b, p + max_new_tokens]; with
    return_stats=True, (tokens, stats) where stats reports
    verify_calls (target invocations after prefill), committed_tokens,
    and acceptance_rate (mean accepted proposals / (gamma-1)) — the
    observability that tells a ceiling draft from a floor one.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    model, draft = trainer.model, draft_trainer.model
    for m in (model, draft):
        _require_kv_convention(m)
        if not getattr(m, "causal", True):
            raise ValueError("speculative decode needs causal models")
    if getattr(model, "vocab_size", None) != getattr(
            draft, "vocab_size", None):
        raise ValueError(
            "target and draft must share a vocabulary, got %r vs %r"
            % (getattr(model, "vocab_size", None),
               getattr(draft, "vocab_size", None))
        )
    gamma = int(gamma)
    if gamma < 1:
        raise ValueError("gamma must be >= 1, got %d" % gamma)
    total = p + int(max_new_tokens)
    seq_len = min(model.seq_len, draft.seq_len)
    # the last verify chunk can reach position (total-2) + gamma
    if max_new_tokens < 1 or p < 1 or total + gamma - 1 > seq_len:
        raise ValueError(
            "need prompt %d + new %d + gamma %d - 1 <= min seq_len %d "
            "(the verify chunk must fit the cache)"
            % (p, max_new_tokens, gamma, seq_len)
        )
    p_pad = _prefill_bucket(p, seq_len)

    cache = _decode_cache(trainer)
    from elasticdl_tpu.api.quantization import is_quantized

    qz = is_quantized(state.params)
    d_qz = is_quantized(draft_state.params)
    # the compiled fn closes over the DRAFT module too — same target
    # with a different draft must not reuse it. The cache entry holds a
    # STRONG reference to the draft trainer so its id cannot be
    # recycled onto a new object while the entry lives (the LRU bounds
    # the lifetime).
    # return_stats is NOT part of the key: the compiled program always
    # returns (tokens, n, acc); the flag only gates Python-side
    # post-processing, so both call forms share one executable
    key = ("spec", b, total, gamma, p_pad, qz, d_qz,
           id(draft_trainer))
    fn = None
    entry = cache.get(key)
    if entry is not None:
        fn, _draft_ref = entry
    if fn is None:
        kv_shapes = _kv_shapes_for(cache, model, b)
        # draft cache shapes live under the draft trainer's own cache
        d_cache = _decode_cache(draft_trainer)
        d_kv_shapes = _kv_shapes_for(d_cache, draft, b)

        def run(variables, d_variables, tokens, p_len):
            variables = _maybe_dequantize(variables, qz)
            d_variables = _maybe_dequantize(d_variables, d_qz)
            # ---- prefill BOTH models; target's logits pick token at p
            tkv, t_last = _run_prefill(
                model, variables, kv_shapes, tokens, p_len, p_pad
            )
            dkv, _ = _run_prefill(
                draft, d_variables, d_kv_shapes, tokens, p_len, p_pad
            )
            first = jnp.argmax(t_last, axis=-1).astype(jnp.int32)
            tokens = jax.lax.dynamic_update_slice(
                tokens, first[:, None], (0, p_len)
            )

            def cond(carry):
                tokens, pos, tkv, dkv, n, acc = carry
                return pos < total

            def body(carry):
                tokens, pos, tkv, dkv, n, acc = carry
                # ---- draft: gamma single-token proposals from pos-1
                def d_step(c, _):
                    dkv, tok = c
                    lg, upd = draft.apply(
                        dict(d_variables, cache=dkv),
                        {"tokens": tok},
                        training=False, decode=True, mutable=["cache"],
                    )
                    nxt = jnp.argmax(
                        lg[:, 0], axis=-1
                    ).astype(jnp.int32)[:, None]
                    return (upd["cache"], nxt), nxt

                tok0 = jax.lax.dynamic_slice(
                    tokens, (0, pos - 1), (b, 1)
                )
                # gamma-1 proposals: the verify chunk only ever reads
                # d[0..gamma-2] (row j feeds position pos-1+j), and the
                # gamma-th proposal could not change the commit count
                # either — it would be pure dead work
                (dkv, _), d_toks = jax.lax.scan(
                    d_step, (dkv, tok0), None, length=gamma - 1
                )
                d_toks = jnp.moveaxis(
                    d_toks[..., 0], 0, 1
                )  # [b, gamma-1]
                # stage proposals in the buffer so the verify chunk can
                # read them contiguously: positions pos .. pos+gamma-2
                tokens_staged = jax.lax.dynamic_update_slice(
                    tokens, d_toks, (0, pos)
                )
                # ---- target: ONE gamma-wide chunk from position pos-1
                chunk = jax.lax.dynamic_slice(
                    tokens_staged, (0, pos - 1), (b, gamma)
                )
                t_logits, t_upd = model.apply(
                    dict(variables, cache=tkv),
                    {"tokens": chunk},
                    training=False, decode=True, mutable=["cache"],
                )
                tkv = t_upd["cache"]
                g_toks = jnp.argmax(
                    t_logits, axis=-1
                ).astype(jnp.int32)  # [b, gamma] targets for pos..pos+gamma-1
                # ---- acceptance: longest common prefix over the
                # gamma-1 proposals, batch-min so every row stays in
                # lockstep (a row's extra accepted tokens are simply
                # re-derived next iteration). Committing a+1 tokens is
                # always valid: position pos+a takes the target's own
                # g[a] (correction when d[a] mismatched, bonus when
                # every proposal matched).
                match = jnp.cumprod(
                    (d_toks == g_toks[:, :gamma - 1]).astype(jnp.int32),
                    axis=1,
                )
                a = jnp.min(match.sum(axis=1))  # scalar in [0, gamma-1]
                c = a + 1                       # tokens to commit
                # commit g[0..c-1] at positions pos..pos+c-1 (g == d on
                # the accepted prefix; position pos+a takes the
                # target's correction when a < gamma)
                keep = jnp.arange(gamma)[None, :] < c
                window = jax.lax.dynamic_slice(
                    tokens, (0, pos), (b, gamma)
                )
                merged = jnp.where(keep, g_toks, window)
                tokens = jax.lax.dynamic_update_slice(
                    tokens, merged, (0, pos)
                )
                pos = pos + c
                # ---- rollback: counters to consumed = pos - 1; cache
                # rows past the counter are masked junk
                tkv = dict(tkv, pos=jnp.asarray(pos - 1, jnp.int32))
                dkv = dict(dkv, pos=jnp.asarray(pos - 1, jnp.int32))
                return (tokens, pos, tkv, dkv, n + 1, acc + a)

            zero = jnp.asarray(0, jnp.int32)
            tokens, _, _, _, n, acc = jax.lax.while_loop(
                cond, body, (tokens, p_len + 1, tkv, dkv, zero, zero)
            )
            return tokens, n, acc

        fn = _tjit("offline_speculative", run)
        cache[key] = (fn, draft_trainer)

    variables = {"params": state.params, **state.model_state}
    d_variables = {
        "params": draft_state.params, **draft_state.model_state
    }
    buf = jnp.zeros((b, seq_len), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
    with trainer.mesh:
        out, n, acc = fn(variables, d_variables, buf,
                         jnp.asarray(p, jnp.int32))
    out = out[:, :total]
    if not return_stats:
        return out
    verify_calls = int(n)
    stats = {
        "verify_calls": verify_calls,
        "committed_tokens": int(max_new_tokens) - 1,  # first from prefill
        # accepted proposals per verify, as a fraction of the gamma-1
        # proposed — counted in-loop (batch-min per iteration, like the
        # commit), so stream-end truncation of the last chunk doesn't
        # read as rejection
        "acceptance_rate": (
            float(acc) / max(1, (gamma - 1) * verify_calls)
            if gamma > 1 else 0.0
        ),
    }
    return out, stats
