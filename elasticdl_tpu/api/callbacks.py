"""Training callbacks.

Parity with the reference's elasticdl/callbacks.py:25-154 (SavedModelExporter,
MaxStepsStopping, LearningRateScheduler) without the Keras dependency:

* ``SavedModelExporter`` runs at train end via the TRAIN_END_CALLBACK task the
  dispatcher emits after the last training task (reference
  task_dispatcher.py:219-254 → callbacks.py:39-67);
* ``MaxStepsStopping`` counts completed training-task steps master-side and
  flips the dispatcher's ``stop_training`` (reference callbacks.py:69-117,
  on_task_end);
* ``LearningRateScheduler`` modulates the learning rate as a function of the
  model version (reference callbacks.py:119-154 sets
  ``optimizer.learning_rate`` before every batch). TPU-native difference: the
  schedule is compiled INTO the train step as an
  ``optax.scale_by_schedule`` over ``state.step`` (== model version), so the
  callback's fn maps version → **multiplier on the optimizer's base LR**
  rather than overwriting an absolute LR; there is no per-batch host hook in
  a jit loop.
"""

from elasticdl_tpu.common.log_utils import default_logger as logger


class Callback(object):
    """Minimal callback interface. Hooks are discovered by name:
    on_task_end(task), on_train_end(worker)."""


class CallbackList(object):
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)


class SavedModelExporter(Callback):
    """Exports the trained model at train end (reference callbacks.py:39-67,
    driven by the TRAIN_END_CALLBACK task)."""

    def __init__(self, export_dir):
        self.export_dir = export_dir

    def on_train_end(self, worker):
        from elasticdl_tpu.api.exporter import export_model

        if worker.state is None:
            logger.warning("No trained state to export")
            return
        path = export_model(
            worker.trainer.model,
            worker.state,
            self.export_dir,
            host_manager=worker.trainer.host_manager,
        )
        logger.info("Exported trained model to %s", path)


class MaxStepsStopping(Callback):
    """Stops the job once `max_steps` optimizer updates have been dispatched
    (reference callbacks.py:69-117: counts steps from completed task record
    ranges — the master never sees individual batches)."""

    def __init__(self, max_steps, minibatch_size=32):
        self.max_steps = int(max_steps)
        self.minibatch_size = int(minibatch_size)
        self._completed_steps = 0
        self._dispatcher = None

    def set_task_dispatcher(self, dispatcher):
        self._dispatcher = dispatcher

    def set_completed_steps(self, steps):
        """Seed the counter on resume — the reference master sets this to
        the checkpoint's model version so max_steps counts TOTAL job
        steps, not steps-since-restart (master.py:176-192)."""
        self._completed_steps = int(steps)

    def on_task_end(self, task):
        from elasticdl_tpu.master.task_dispatcher import TaskType

        if task.type != TaskType.TRAINING:
            return
        records = task.end - task.start
        self._completed_steps += (
            records + self.minibatch_size - 1
        ) // self.minibatch_size
        if (
            self._completed_steps >= self.max_steps
            and self._dispatcher is not None
            and not self._dispatcher.stop_training
        ):
            logger.info(
                "MaxStepsStopping: %d steps completed (max %d); stopping",
                self._completed_steps, self.max_steps,
            )
            self._dispatcher.stop_training = True


class LearningRateScheduler(Callback):
    """LR modulation by model version, compiled into the train step.

    ``multiplier_fn(version) -> float`` scales the optimizer's base LR (the
    reference's fn returned an absolute LR and overwrote
    ``optimizer.learning_rate`` per batch — callbacks.py:119-154; under jit
    the schedule must be a traced function of the step counter instead).
    Consumed by Trainer at optimizer construction.
    """

    def __init__(self, multiplier_fn):
        self.multiplier_fn = multiplier_fn
