"""Draft-model distillation for speculative decoding.

Speculative decode's speedup is acceptance-bound: a draft that mimics
the target's argmax at most positions advances the stream ~gamma
tokens per target invocation; a random draft degenerates to slower-
than-plain decode. The reference framework has no decoding stack at
all (SURVEY.md §5) — this is net-new surface completing the
speculative path (api/generation.py speculative_generate) with the
piece that makes it actually fast: a cheaply TRAINED draft.

Two steps, composable:

  * warm_start_draft — copy every identically-shaped top-level param
    subtree from the target into the draft (embeddings, final norm,
    head, and the first N transformer blocks, since both come from the
    same zoo family the names line up). A 2-layer draft of an L-layer
    target starts as "the target minus its upper blocks" — already far
    better than random.
  * distill_draft — soft-label distillation: minimize
    KL(target || draft) over the target's next-token distributions on
    provided token batches. No labels needed; any token stream works
    (including model-generated or random tokens — the draft learns the
    TARGET's behavior, not the data's).

Both are serving-side utilities: they never touch the target state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import default_logger as logger


def _shapes_match(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return (
        jax.tree_util.tree_structure(a) == jax.tree_util.tree_structure(b)
        and len(la) == len(lb)
        and all(
            getattr(x, "shape", None) == getattr(y, "shape", None)
            for x, y in zip(la, lb)
        )
    )


def _dense_params(params):
    """Int8-quantized targets (api/quantization.py) carry marker-dict
    leaves the draft cannot copy or apply; dequantize to the float
    view first (no-op for float trees)."""
    from elasticdl_tpu.api.quantization import (
        dequantize_params,
        is_quantized,
    )

    return dequantize_params(params) if is_quantized(params) else params


def warm_start_draft(target_state, draft_state):
    """Return draft_state with every top-level param subtree whose name
    AND shape-structure match the target's copied over (wte/wpe, ln_f,
    head, block_0..block_{N-1} for an N-block draft). Mismatched
    subtrees (none, for same-family models with fewer layers) keep the
    draft's fresh init. Quantized targets are dequantized for the copy
    (the draft warm-starts from the float view)."""
    t_params = _dense_params(target_state.params)
    new_params = {}
    copied = []
    for key, sub in draft_state.params.items():
        src = t_params.get(key) if hasattr(t_params, "get") else None
        if src is not None and _shapes_match(src, sub):
            # land on the draft's shardings, not the target's
            shardings = jax.tree.map(lambda x: x.sharding, sub)
            new_params[key] = jax.device_put(
                jax.tree.map(np.asarray, jax.device_get(src)), shardings
            )
            copied.append(key)
        else:
            new_params[key] = sub
    logger.info("warm_start_draft copied subtrees: %s", copied)
    return draft_state.replace(params=new_params)


def distill_draft(trainer, state, draft_trainer, draft_state, batches,
                  lr=1e-3, temperature=1.0):
    """Soft-label distillation of the draft against the frozen target.

    batches: iterable of int32 token arrays [b, l] (l <= both models'
    seq_len). Minimizes mean KL(softmax(t/T) || softmax(d/T)) over all
    positions with Adam. Returns (new_draft_state, losses). One jitted
    step, re-used across batches; the target's logits are computed
    inside the same program so nothing round-trips through HBM twice.
    """
    model, draft = trainer.model, draft_trainer.model
    t_vars = {"params": _dense_params(state.params),
              **state.model_state}
    d_mstate = draft_state.model_state
    tx = optax.adam(lr)
    opt_state = tx.init(draft_state.params)
    inv_t = 1.0 / float(temperature)

    @jax.jit
    def step(d_params, opt_state, tokens):
        t_logits = model.apply(t_vars, {"tokens": tokens},
                               training=False)
        t_lp = jax.nn.log_softmax(
            t_logits.astype(jnp.float32) * inv_t
        )

        def loss_fn(p):
            d_logits = draft.apply(
                {"params": p, **d_mstate}, {"tokens": tokens},
                training=False,
            )
            d_lp = jax.nn.log_softmax(
                d_logits.astype(jnp.float32) * inv_t
            )
            return jnp.mean(
                jnp.sum(jnp.exp(t_lp) * (t_lp - d_lp), axis=-1)
            )

        loss, grads = jax.value_and_grad(loss_fn)(d_params)
        updates, opt_state = tx.update(grads, opt_state, d_params)
        return optax.apply_updates(d_params, updates), opt_state, loss

    params = draft_state.params
    losses = []
    for tokens in batches:
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(tokens, jnp.int32)
        )
        losses.append(float(loss))
    if losses:
        logger.info(
            "distill_draft: %d steps, KL %.4f -> %.4f",
            len(losses), losses[0], losses[-1],
        )
    return draft_state.replace(params=params), losses
