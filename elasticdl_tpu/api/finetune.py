"""Fine-tuning utilities: LoRA adapter merging.

The training-side pieces live elsewhere (Trainer ``trainable_pattern``
for optimizer-level freezing; ``lora_rank`` on the transformer_lm
family for the adapter branches; checkpoint ``strict=False`` for
dense-checkpoint warm starts). This module closes the loop for
serving: fold trained adapters back into the base kernels so the
deployed model is a PLAIN dense model again — no extra matmuls per
step, loadable by a ``lora_rank=0`` model, quantizable, exportable.
"""

from collections.abc import Mapping

import jax
import jax.numpy as jnp


def merge_lora(params, model=None, lora_alpha=None):
    """Fold ``*_lora_a`` / ``*_lora_b`` adapter pairs into their base
    kernels: ``W += (A @ B) * alpha/rank``, then drop the adapter
    params. The result matches a ``lora_rank=0`` model's param
    structure, and its outputs equal the adapter model's to float
    tolerance (``(x@A)@B*scale`` vs ``x@(A@B*scale)`` reassociation).

    Pass ``model`` (the flax module, e.g. ``trainer.model``) so alpha
    is read from its ``lora_alpha`` — a silently mismatched alpha
    would halve/double every delta; ``lora_alpha`` overrides
    explicitly. One of the two must be given.

    Math runs in jnp, so sharded ``jax.Array`` leaves stay jax arrays
    with their committed placement (under multi-host SPMD, call on
    every host like any other computation). Returns a new pytree; the
    input is not mutated. Raises if an adapter pair has no base kernel
    sibling (``<name>/kernel``) to merge into.
    """
    if lora_alpha is None:
        lora_alpha = getattr(model, "lora_alpha", None)
        if lora_alpha is None:
            raise ValueError(
                "pass model= (to read its lora_alpha) or an explicit "
                "lora_alpha — a mismatched alpha merges silently wrong"
            )
    if model is not None and lora_alpha != getattr(
            model, "lora_alpha", lora_alpha):
        raise ValueError(
            "explicit lora_alpha %r contradicts model.lora_alpha %r"
            % (lora_alpha, model.lora_alpha)
        )

    def visit(node):
        # Mapping covers flax FrozenDict too — a silent no-op on a
        # frozen tree would ship unmerged weights; plain dicts out
        if not isinstance(node, Mapping):
            return node
        out = {}
        adapters = {}
        for key, val in node.items():
            if key.endswith("_lora_a") or key.endswith("_lora_b"):
                base = key[: -len("_lora_a")]
                adapters.setdefault(base, {})[key[-1]] = val
            else:
                out[key] = visit(val)
        for base, ab in adapters.items():
            if sorted(ab) != ["a", "b"]:
                raise ValueError(
                    "incomplete LoRA pair for %r: found only %s"
                    % (base, sorted(ab))
                )
            target = out.get(base)
            if not isinstance(target, dict) or "kernel" not in target:
                raise ValueError(
                    "no base kernel %s/kernel to merge adapters into"
                    % base
                )
            a = jnp.asarray(ab["a"], jnp.float32)
            b = jnp.asarray(ab["b"], jnp.float32)
            rank = a.shape[-1]
            kernel = target["kernel"]
            delta = (a @ b) * (float(lora_alpha) / rank)
            merged = (
                jnp.asarray(kernel, jnp.float32) + delta
            ).astype(kernel.dtype)
            if isinstance(kernel, jax.Array):
                merged = jax.device_put(merged, kernel.sharding)
            out[base] = dict(target, kernel=merged)
        return out

    return visit(params)
