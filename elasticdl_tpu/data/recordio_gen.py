"""Synthetic dataset fixture generators (TRec files).

Mirrors the reference's data/recordio_gen/ converters and the test fixtures in
elasticdl/python/tests/test_utils.py:101-225 (mnist-style images, frappe,
census schemas) — used by tests, tutorials, and bench.py.
"""

import os

import numpy as np

from elasticdl_tpu.data.example_codec import encode_example
from elasticdl_tpu.data.record_format import RecordWriter


def _generate(data_dir, prefix, make_example, num_files, records_per_file,
              seed):
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "%s-%04d.trec" % (prefix, i))
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(encode_example(make_example(rng)))
        paths.append(path)
    return paths


def gen_mnist_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """28x28 float images in [0,1) + int32 labels in [0,10)."""
    def example(rng):
        return {
            "image": rng.rand(28, 28).astype(np.float32),
            "label": np.array([rng.randint(10)], dtype=np.int32),
        }

    return _generate(data_dir, "mnist", example, num_files,
                     records_per_file, seed)


def gen_cifar10_like(data_dir, num_files=2, records_per_file=128, seed=0):
    def example(rng):
        return {
            "image": rng.rand(32, 32, 3).astype(np.float32),
            "label": np.array([rng.randint(10)], dtype=np.int32),
        }

    return _generate(data_dir, "cifar10", example, num_files,
                     records_per_file, seed)


def gen_frappe_like(data_dir, num_files=2, records_per_file=128,
                    feature_dim=10, input_dim=5383, seed=0):
    """Sparse-id recommendation records (frappe schema: fixed-length id list +
    binary label), used by the DeepFM configs."""
    def example(rng):
        return {
            "feature": rng.randint(input_dim, size=feature_dim).astype(
                np.int64
            ),
            "label": np.array([rng.randint(2)], dtype=np.int32),
        }

    return _generate(data_dir, "frappe", example, num_files,
                     records_per_file, seed)


def gen_census_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """Tabular wide&deep records: a few dense floats + categorical ids."""
    def example(rng):
        return {
            "dense": rng.rand(5).astype(np.float32),
            "category": rng.randint(1000, size=8).astype(np.int64),
            "label": np.array([rng.randint(2)], dtype=np.int32),
        }

    return _generate(data_dir, "census", example, num_files,
                     records_per_file, seed)
