"""Synthetic dataset fixture generators (TRec files).

Mirrors the reference's data/recordio_gen/ converters and the test fixtures in
elasticdl/python/tests/test_utils.py:101-225 (mnist-style images, frappe,
census schemas) — used by tests, tutorials, and bench.py.
"""

import os

import numpy as np

from elasticdl_tpu.data.example_codec import encode_example
from elasticdl_tpu.data.record_format import RecordWriter


def _generate(data_dir, prefix, make_example, num_files, records_per_file,
              seed):
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "%s-%04d.trec" % (prefix, i))
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(encode_example(make_example(rng)))
        paths.append(path)
    return paths


def gen_mnist_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """28x28 float images in [0,1) + int32 labels in [0,10)."""
    def example(rng):
        return {
            "image": rng.rand(28, 28).astype(np.float32),
            "label": np.array([rng.randint(10)], dtype=np.int32),
        }

    return _generate(data_dir, "mnist", example, num_files,
                     records_per_file, seed)


def gen_cifar10_like(data_dir, num_files=2, records_per_file=128, seed=0):
    def example(rng):
        return {
            "image": rng.rand(32, 32, 3).astype(np.float32),
            "label": np.array([rng.randint(10)], dtype=np.int32),
        }

    return _generate(data_dir, "cifar10", example, num_files,
                     records_per_file, seed)


def gen_frappe_like(data_dir, num_files=2, records_per_file=128,
                    feature_dim=10, input_dim=5383, seed=0):
    """Sparse-id recommendation records (frappe schema: fixed-length id list +
    binary label), used by the DeepFM configs."""
    def example(rng):
        return {
            "feature": rng.randint(input_dim, size=feature_dim).astype(
                np.int64
            ),
            "label": np.array([rng.randint(2)], dtype=np.int32),
        }

    return _generate(data_dir, "frappe", example, num_files,
                     records_per_file, seed)


def gen_census_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """Tabular wide&deep records: a few dense floats + categorical ids."""
    def example(rng):
        return {
            "dense": rng.rand(5).astype(np.float32),
            "category": rng.randint(1000, size=8).astype(np.int64),
            "label": np.array([rng.randint(2)], dtype=np.int32),
        }

    return _generate(data_dir, "census", example, num_files,
                     records_per_file, seed)


CENSUS_CATEGORICAL_VOCAB = {
    "workclass": [b"Private", b"Self-emp-not-inc", b"Self-emp-inc",
                  b"Federal-gov", b"Local-gov", b"State-gov", b"Without-pay",
                  b"Never-worked"],
    "education": [b"Bachelors", b"HS-grad", b"11th", b"Masters", b"9th",
                  b"Some-college", b"Assoc-acdm", b"Assoc-voc", b"Doctorate"],
    "marital-status": [b"Married-civ-spouse", b"Divorced", b"Never-married",
                       b"Separated", b"Widowed", b"Married-spouse-absent",
                       b"Married-AF-spouse"],
    "occupation": [b"Tech-support", b"Craft-repair", b"Other-service",
                   b"Sales", b"Exec-managerial", b"Prof-specialty"],
    "relationship": [b"Wife", b"Own-child", b"Husband", b"Not-in-family",
                     b"Other-relative", b"Unmarried"],
    "race": [b"White", b"Asian-Pac-Islander", b"Amer-Indian-Eskimo",
             b"Other", b"Black"],
    "sex": [b"Female", b"Male"],
    "native-country": [b"United-States", b"Cambodia", b"England",
                       b"Puerto-Rico", b"Canada", b"Germany", b"India"],
}


def gen_census_raw(data_dir, num_files=2, records_per_file=128, seed=0):
    """Raw census-income schema (reference data/recordio_gen/census schema +
    tests/test_utils.py census fixtures): 8 string categoricals, 4 numerics,
    binary label."""
    def example(rng):
        ex = {}
        for name, vocab in CENSUS_CATEGORICAL_VOCAB.items():
            ex[name] = np.array(vocab[rng.randint(len(vocab))], dtype="S32")
        ex["age"] = np.array(rng.randint(17, 90), dtype=np.float32)
        ex["capital-gain"] = np.array(rng.randint(0, 9000),
                                      dtype=np.float32)
        ex["capital-loss"] = np.array(rng.randint(0, 4500),
                                      dtype=np.float32)
        ex["hours-per-week"] = np.array(rng.randint(1, 80),
                                        dtype=np.float32)
        ex["label"] = np.array(rng.randint(2), dtype=np.int64)
        return ex

    return _generate(data_dir, "census-raw", example, num_files,
                     records_per_file, seed)


def gen_heart_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """Heart-disease schema (reference model_zoo/heart_functional_api
    dataset_fn feature_description)."""
    def example(rng):
        return {
            "age": np.array(rng.randint(18, 90), dtype=np.int64),
            "trestbps": np.array(rng.randint(90, 200), dtype=np.int64),
            "chol": np.array(rng.randint(120, 560), dtype=np.int64),
            "thalach": np.array(rng.randint(70, 210), dtype=np.int64),
            "oldpeak": np.array(rng.rand() * 6.0, dtype=np.float32),
            "slope": np.array(rng.randint(0, 3), dtype=np.int64),
            "ca": np.array(rng.randint(0, 4), dtype=np.int64),
            "thal": np.array(
                [b"fixed", b"normal", b"reversible"][rng.randint(3)],
                dtype="S16",
            ),
            "target": np.array(rng.randint(2), dtype=np.int64),
        }

    return _generate(data_dir, "heart", example, num_files,
                     records_per_file, seed)


def gen_imagenet_like(data_dir, num_files=1, records_per_file=16,
                      image_size=224, num_classes=1000, seed=0):
    """ImageNet-shaped records (reference tests/test_utils.py imagenet
    fixtures): HxWx3 uint8-valued floats + int label."""
    def example(rng):
        return {
            "image": (rng.rand(image_size, image_size, 3) * 255).astype(
                np.float32
            ),
            "label": np.array([rng.randint(num_classes)], dtype=np.int32),
        }

    return _generate(data_dir, "imagenet", example, num_files,
                     records_per_file, seed)


def gen_criteo_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """Criteo/DAC CTR schema (reference model_zoo/dac_ctr/feature_config:
    numeric I1..I13, categorical C1..C26 as strings, binary label)."""
    def example(rng):
        ex = {}
        for i in range(1, 14):
            ex["I%d" % i] = np.array(rng.rand() * 100, dtype=np.float32)
        for i in range(1, 27):
            ex["C%d" % i] = np.array(
                ("cat%d" % rng.randint(1000)).encode(), dtype="S16"
            )
        ex["label"] = np.array(rng.randint(2), dtype=np.int64)
        return ex

    return _generate(data_dir, "criteo", example, num_files,
                     records_per_file, seed)


def gen_iris_csv(data_dir, num_files=2, rows_per_file=64, seed=0):
    """Iris-style CSV files (reference odps_iris_dnn_model consumes
    MaxCompute rows of 4 floats + class label; debug path uses CSV)."""
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "iris-%04d.csv" % i)
        with open(path, "w") as f:
            f.write("sepal_l,sepal_w,petal_l,petal_w,label\n")
            for _ in range(rows_per_file):
                vals = rng.rand(4) * 7.0
                f.write("%.3f,%.3f,%.3f,%.3f,%d\n"
                        % (*vals, rng.randint(3)))
        paths.append(path)
    return paths


def gen_tokens_like(data_dir, num_files=2, records_per_file=128, seed=0,
                    seq_len=33, vocab_size=64):
    """Token-sequence records for the sequence families (transformer_lm /
    transformer_pp consume seq_len+1 tokens per record; bert masks
    seq_len tokens). Each record is self-describing (carries
    vocab_size), so dataset_fns can mask without out-of-band config."""
    def example(rng):
        return {
            "tokens": rng.randint(
                0, vocab_size, size=(seq_len,)
            ).astype(np.int64),
            "vocab_size": np.array(vocab_size, np.int64),
        }

    return _generate(data_dir, "tokens", example, num_files,
                     records_per_file, seed)


# -------------------------------------------------- real-dataset converters
#
# Counterparts of the reference's data/recordio_gen/ converters that worked
# on REAL inputs rather than synthetic fixtures: image_label.py (image
# arrays / directories -> sharded records) and heart_recordio_gen.py
# (CSV -> records via pandas). Same sharding semantics: records_per_shard
# records per file, files named <prefix>-NNNNN.


class _ShardedWriter(object):
    """Shard-rollover writer shared by the converters: every
    records_per_shard writes closes the current file and opens
    <prefix>-NNNNN.trec. O(1) memory regardless of dataset size."""

    def __init__(self, data_dir, prefix, records_per_shard):
        os.makedirs(data_dir, exist_ok=True)
        self._data_dir = data_dir
        self._prefix = prefix
        self._per_shard = int(records_per_shard)
        self._writer = None
        self._written = 0
        self.paths = []

    def write(self, example):
        if self._written % self._per_shard == 0:
            self._roll()
        self._writer.write(encode_example(example))
        self._written += 1

    def _roll(self):
        if self._writer is not None:
            self._writer.close()
        path = os.path.join(
            self._data_dir, "%s-%05d.trec" % (self._prefix, len(self.paths))
        )
        self.paths.append(path)
        self._writer = RecordWriter(path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._writer is not None:
            self._writer.close()
        return False


def convert_arrays(data_dir, x, y, records_per_shard=1024, fraction=1.0,
                   prefix="data"):
    """Image/label numpy arrays -> sharded TRec files (reference
    image_label.py convert(): shard rollover every records_per_shard,
    optional leading `fraction` of the data)."""
    x = np.asarray(x)
    y = np.asarray(y)
    if len(x) != len(y):
        raise ValueError("x and y lengths differ: %d vs %d"
                         % (len(x), len(y)))
    n = int(len(x) * fraction)
    with _ShardedWriter(data_dir, prefix, records_per_shard) as w:
        for row in range(n):
            w.write({
                "image": np.asarray(x[row], np.float32),
                "label": np.asarray(y[row], np.int64).reshape(()),
            })
        return w.paths


def convert_image_dir(image_dir, data_dir, records_per_shard=1024,
                      image_size=None, image_mode=None):
    """Directory of <class-name>/<image files> -> sharded TRec files with
    integer labels by sorted class-dir order (the image-directory path of
    reference image_label.py, PIL-gated like the reference's TF datasets
    dependency). Images are written INCREMENTALLY (O(1) memory).

    Real directories mix modes and sizes: pass `image_mode` (e.g. "RGB",
    "L") to normalize channels and `image_size` (w, h) to normalize
    dimensions; without them, a shape mismatch raises naming the file.
    Returns (paths, class_names)."""
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "convert_image_dir needs pillow (PIL) to decode images"
        ) from e
    classes = sorted(
        d for d in os.listdir(image_dir)
        if os.path.isdir(os.path.join(image_dir, d))
    )
    if not classes:
        raise ValueError("no class subdirectories under %r" % image_dir)
    expect_shape = None
    with _ShardedWriter(data_dir, "images", records_per_shard) as w:
        for label, cls in enumerate(classes):
            cls_dir = os.path.join(image_dir, cls)
            for name in sorted(os.listdir(cls_dir)):
                path = os.path.join(cls_dir, name)
                if not os.path.isfile(path):
                    continue  # nested dirs etc.
                try:
                    img = Image.open(path)
                except Exception:
                    # real directories carry .DS_Store/README strays —
                    # skip loudly rather than abort the conversion
                    from elasticdl_tpu.common.log_utils import (
                        default_logger,
                    )

                    default_logger.warning(
                        "skipping non-image file %s", path
                    )
                    continue
                if image_mode is not None:
                    img = img.convert(image_mode)
                if image_size is not None:
                    img = img.resize(image_size)
                arr = np.asarray(img, np.float32)
                if expect_shape is None:
                    expect_shape = arr.shape
                elif arr.shape != expect_shape:
                    raise ValueError(
                        "image %s/%s has shape %s, expected %s; pass "
                        "image_size and/or image_mode to normalize"
                        % (cls, name, arr.shape, expect_shape)
                    )
                w.write({
                    "image": arr,
                    "label": np.array(label, np.int64),
                })
        return w.paths, classes


def convert_csv(csv_path, data_dir, records_per_shard=1024, label_column=None,
                prefix=None):
    """CSV file -> sharded TRec files, one feature per column with dtype
    sniffing int64 / float32 / bytes (reference heart_recordio_gen.py
    convert_series_to_tf_feature semantics, without the pandas
    dependency). Returns the written paths."""
    import csv as _csv

    prefix = prefix or os.path.splitext(os.path.basename(csv_path))[0]

    def sniff_column(values):
        """int64 if every value parses as int, else float32 if every value
        parses as float, else bytes — whole-column promotion (a first-row
        "233" must not pin a column that later holds "250.5" to int)."""
        dtype = np.int64
        for v in values:
            if dtype is np.int64:
                try:
                    int(v)
                    continue
                except ValueError:
                    dtype = np.float32
            try:
                float(v)
            except ValueError:
                return None  # string/bytes
        return dtype

    with open(csv_path, newline="") as f:
        reader = _csv.reader(f)
        header = next(reader)
        rows = []
        for lineno, row in enumerate(reader, start=2):
            if not row:
                continue
            if len(row) != len(header):
                raise ValueError(
                    "%s line %d has %d fields, header has %d"
                    % (csv_path, lineno, len(row), len(header))
                )
            rows.append(row)
    if not rows:
        return []
    if label_column is not None and label_column not in header:
        raise ValueError(
            "label column %r not in CSV header %s" % (label_column, header)
        )
    dtypes = [
        sniff_column([row[i] for row in rows]) for i in range(len(header))
    ]
    with _ShardedWriter(data_dir, prefix, records_per_shard) as w:
        for row in rows:
            ex = {}
            for name, value, dtype in zip(header, row, dtypes):
                if name == label_column:
                    ex[name] = np.array(int(float(value)), np.int64)
                elif dtype is None:
                    # exact-length bytes dtype: no silent truncation
                    ex[name] = np.array(value.encode("utf-8"))
                else:
                    ex[name] = np.array(
                        dtype(float(value))
                        if dtype is np.float32 else int(value),
                        dtype,
                    )
            w.write(ex)
        return w.paths


def gen_docs_like(data_dir, num_files=2, records_per_file=128, seed=0,
                  vocab_size=64, min_len=4, max_len=48, cyclic=False):
    """VARIABLE-length documents for the packed-LM family
    (model_zoo/transformer_lm_packed): each record is one document of
    min_len..max_len tokens. cyclic=True writes next=(tok+1)%vocab
    cycles so tiny models can demonstrably learn from packed batches."""
    def example(rng):
        n = rng.randint(min_len, max_len + 1)
        if cyclic:
            tokens = (rng.randint(0, vocab_size)
                      + np.arange(n)) % vocab_size
        else:
            tokens = rng.randint(0, vocab_size, size=(n,))
        return {
            "tokens": tokens.astype(np.int64),
            "vocab_size": np.array(vocab_size, np.int64),
        }

    return _generate(data_dir, "docs", example, num_files,
                     records_per_file, seed)
