"""Synthetic dataset fixture generators (TRec files).

Mirrors the reference's data/recordio_gen/ converters and the test fixtures in
elasticdl/python/tests/test_utils.py:101-225 (mnist-style images, frappe,
census schemas) — used by tests, tutorials, and bench.py.
"""

import os

import numpy as np

from elasticdl_tpu.data.example_codec import encode_example
from elasticdl_tpu.data.record_format import RecordWriter


def gen_mnist_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """28x28 float images in [0,1) + int32 labels in [0,10)."""
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "mnist-%04d.trec" % i)
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(
                    encode_example(
                        {
                            "image": rng.rand(28, 28).astype(np.float32),
                            "label": np.array(
                                [rng.randint(10)], dtype=np.int32
                            ),
                        }
                    )
                )
        paths.append(path)
    return paths


def gen_cifar10_like(data_dir, num_files=2, records_per_file=128, seed=0):
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "cifar10-%04d.trec" % i)
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(
                    encode_example(
                        {
                            "image": rng.rand(32, 32, 3).astype(np.float32),
                            "label": np.array(
                                [rng.randint(10)], dtype=np.int32
                            ),
                        }
                    )
                )
        paths.append(path)
    return paths


def gen_frappe_like(
    data_dir, num_files=2, records_per_file=128, feature_dim=10,
    input_dim=5383, seed=0
):
    """Sparse-id recommendation records (frappe schema: fixed-length id list +
    binary label), used by the DeepFM configs."""
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "frappe-%04d.trec" % i)
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(
                    encode_example(
                        {
                            "feature": rng.randint(
                                input_dim, size=feature_dim
                            ).astype(np.int64),
                            "label": np.array(
                                [rng.randint(2)], dtype=np.int32
                            ),
                        }
                    )
                )
        paths.append(path)
    return paths


def gen_census_like(data_dir, num_files=2, records_per_file=128, seed=0):
    """Tabular wide&deep records: a few dense floats + categorical ids."""
    rng = np.random.RandomState(seed)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_files):
        path = os.path.join(data_dir, "census-%04d.trec" % i)
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                w.write(
                    encode_example(
                        {
                            "dense": rng.rand(5).astype(np.float32),
                            "category": rng.randint(
                                1000, size=8
                            ).astype(np.int64),
                            "label": np.array(
                                [rng.randint(2)], dtype=np.int32
                            ),
                        }
                    )
                )
        paths.append(path)
    return paths
