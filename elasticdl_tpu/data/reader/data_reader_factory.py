"""Reader factory (reference data/reader/data_reader_factory.py:23-73).

Resolution order: explicit `reader_type` param > custom reader from the model
zoo > extension sniffing (.csv -> CSV, else TRec/RecordIO).
"""

import os

from elasticdl_tpu.common.constants import ReaderType
from elasticdl_tpu.data.reader.csv_reader import CSVDataReader
from elasticdl_tpu.data.reader.recordio_reader import RecordIODataReader


def create_data_reader(data_origin, records_per_task=None, **kwargs):
    reader_type = kwargs.pop("reader_type", None)
    kwargs.setdefault("data_dir", data_origin)
    if records_per_task is not None:
        kwargs.setdefault("records_per_task", records_per_task)

    if reader_type is None:
        if data_origin and os.path.isdir(data_origin):
            names = os.listdir(data_origin)
            if names and all(n.endswith(".csv") for n in names):
                return CSVDataReader(**kwargs)
        return RecordIODataReader(**kwargs)
    if reader_type == ReaderType.CSV:
        return CSVDataReader(**kwargs)
    if reader_type == ReaderType.RECORDIO:
        return RecordIODataReader(**kwargs)
    raise ValueError("Unknown reader_type %s" % reader_type)
