"""Reader factory (reference data/reader/data_reader_factory.py:23-73).

Resolution order: explicit `reader_type` param > ODPS env sniffing
(MAXCOMPUTE_* credentials present and the origin is a table name, as in
the reference's env-based choice) > extension sniffing (.csv -> CSV,
else TRec/RecordIO).
"""

import os

from elasticdl_tpu.common.constants import ReaderType
from elasticdl_tpu.data.reader.csv_reader import CSVDataReader
from elasticdl_tpu.data.reader.recordio_reader import RecordIODataReader


def _odps_env():
    """MaxCompute credentials from the env (reference
    data_reader_factory.py env sniffing + odps_io MaxComputeConfig)."""
    ak = os.environ.get("MAXCOMPUTE_AK") or os.environ.get("ODPS_ACCESS_ID")
    sk = os.environ.get("MAXCOMPUTE_SK") or os.environ.get(
        "ODPS_ACCESS_KEY"
    )
    project = os.environ.get("MAXCOMPUTE_PROJECT") or os.environ.get(
        "ODPS_PROJECT_NAME"
    )
    endpoint = os.environ.get("MAXCOMPUTE_ENDPOINT") or os.environ.get(
        "ODPS_ENDPOINT"
    )
    if ak and sk and project:
        return {
            "access_id": ak,
            "access_key": sk,
            "project": project,
            "endpoint": endpoint,
        }
    return None


def _make_odps_reader(data_origin, kwargs):
    from elasticdl_tpu.data.reader.odps_reader import ODPSDataReader

    kwargs.pop("data_dir", None)
    env = _odps_env() or {}
    for k, v in env.items():
        kwargs.setdefault(k, v)
    kwargs.setdefault("table", data_origin)
    return ODPSDataReader(**kwargs)


def create_data_reader(data_origin, records_per_task=None, **kwargs):
    reader_type = kwargs.pop("reader_type", None)
    kwargs.setdefault("data_dir", data_origin)
    if records_per_task is not None:
        kwargs.setdefault("records_per_task", records_per_task)

    if reader_type is None:
        if (
            _odps_env() is not None
            and data_origin
            and os.sep not in data_origin
            and not os.path.exists(data_origin)
        ):
            return _make_odps_reader(data_origin, kwargs)
        if data_origin and os.path.isdir(data_origin):
            names = os.listdir(data_origin)
            if names and all(n.endswith(".csv") for n in names):
                return CSVDataReader(**kwargs)
        return RecordIODataReader(**kwargs)
    if reader_type == ReaderType.CSV:
        return CSVDataReader(**kwargs)
    if reader_type == ReaderType.RECORDIO:
        return RecordIODataReader(**kwargs)
    if reader_type == ReaderType.ODPS:
        return _make_odps_reader(data_origin, kwargs)
    raise ValueError("Unknown reader_type %s" % reader_type)


def build_data_reader(data_origin, records_per_task=None,
                      data_reader_params=None, custom_data_reader=None):
    """The ONE reader-construction contract shared by the worker's
    TaskDataService and the master's submission-time validation
    (master/main.py _validate_dataset_fn): a spec-declared
    custom_data_reader wins, else the factory; params may be the
    'k=v; k=v' wire string or an already-parsed dict. Keeping both
    callers on this helper means the master validates against exactly
    the reader the workers will build."""
    if isinstance(data_reader_params, str):
        from elasticdl_tpu.common.model_utils import (
            get_dict_from_params_str,
        )

        data_reader_params = get_dict_from_params_str(data_reader_params)
    create_fn = custom_data_reader or create_data_reader
    return create_fn(
        data_origin, records_per_task, **(data_reader_params or {})
    )
