"""CSV reader (debug-oriented, like reference data/reader/csv_reader.py).

Unlike the reference's (which cannot shard by index), this one counts rows at
shard creation so CSV sources get real record-range tasks too.
"""

import csv
import os

from elasticdl_tpu.data.reader.data_reader import (
    AbstractDataReader,
    Metadata,
    check_required_kwargs,
)


class CSVDataReader(AbstractDataReader):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        check_required_kwargs(["data_dir"], kwargs)
        self._kwargs = kwargs
        self._sep = kwargs.get("sep", ",")
        self._columns = kwargs.get("columns", None)

    def _paths(self):
        data_dir = self._kwargs["data_dir"]
        return [
            os.path.join(data_dir, f)
            for f in sorted(os.listdir(data_dir))
            if f.endswith(".csv")
        ]

    def read_records(self, task):
        with open(task.shard_name, newline="") as f:
            reader = csv.reader(f, delimiter=self._sep)
            header = next(reader, None)
            for i, row in enumerate(reader):
                if i < task.start:
                    continue
                if i >= task.end:
                    break
                yield row

    def create_shards(self):
        shards = {}
        for path in self._paths():
            with open(path, newline="") as f:
                n = sum(1 for _ in f) - 1  # minus header
            shards[path] = (0, max(0, n))
        return shards

    @property
    def metadata(self):
        paths = self._paths()
        if not paths:
            return Metadata(column_names=self._columns)
        with open(paths[0], newline="") as f:
            header = next(csv.reader(f, delimiter=self._sep), None)
        return Metadata(column_names=header)
