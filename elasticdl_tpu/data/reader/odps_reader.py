"""MaxCompute/ODPS table reader (reference data/reader/odps_reader.py
251 LoC + data/odps_io.py 515 LoC).

Behavior parity:
* shards are row ranges over the table: {"<table>:<start>": (start, n)}
  (reference ODPSDataReader.create_shards via table size);
* `read_records(task)` streams rows for [task.start, task.end), fetched
  in parallel windows ahead of consumption (reference
  ODPSReader._worker_loop prefetch machinery) with per-window retry;
* a `parse_fn` turns raw column tuples into records
  (ParallelODPSDataReader);
* `metadata` carries column names/dtypes so a default dataset_fn can be
  derived from the table schema.

The `odps` package import is gated exactly like kubernetes: pass a
`table` object implementing `open_reader`/`schema` (what the tests fake)
or install pyodps and pass access keys."""

import queue
import threading

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.reader.data_reader import (
    AbstractDataReader,
    Metadata,
)

_DEFAULT_WINDOW = 1000
_MAX_RETRIES = 3


def _open_odps_table(project, access_id, access_key, endpoint, table):
    try:
        from odps import ODPS
    except ImportError as e:
        raise RuntimeError(
            "The odps package is not installed; pass a `table` object or "
            "install pyodps"
        ) from e
    odps = ODPS(access_id, access_key, project, endpoint)
    return odps.get_table(table)


class ODPSReader(object):
    """Windowed parallel prefetcher over one table (reference
    data/odps_io.py ODPSReader: N window-fetch threads stay ahead of the
    consumer; failed windows retry)."""

    def __init__(self, table, num_prefetch=2, window_size=_DEFAULT_WINDOW):
        self._table = table
        self._num_prefetch = max(1, num_prefetch)
        self._window_size = window_size

    @staticmethod
    def _close_session(session):
        cm = session.pop("cm", None)
        session["reader"] = None
        if cm is not None:
            try:
                cm.__exit__(None, None, None)
            except Exception:
                pass

    def _read_window(self, session, start, count):
        """Read one window from the open `session` holder, reopening the
        reader session only after a failure (one session per range, not
        per window — session creation is a service round trip)."""
        last_error = None
        for _ in range(_MAX_RETRIES):
            try:
                if session.get("reader") is None:
                    cm = self._table.open_reader()
                    session["cm"] = cm
                    session["reader"] = cm.__enter__()
                return list(session["reader"].read(start, count))
            except Exception as e:  # retry transient fetch failures
                last_error = e
                self._close_session(session)
                logger.warning(
                    "ODPS window read (%d, %d) failed: %s; retrying",
                    start, count, e,
                )
        raise last_error

    def to_iterator(self, num_workers, worker_index, batch_size,
                    epochs=1, shuffle=False, limit=-1, table_size=None):
        """Yield record batches for ONE worker of a fleet — the
        reference's standalone consumption surface
        (odps_io.py:222-324 `to_iterator`): the table's row space is cut
        into large windows, windows are split round-robin over
        `num_workers`, optionally shuffled, repeated for `epochs`, and
        this worker's windows stream through the prefetching reader and
        re-chunk into `batch_size` slices."""
        if not 0 <= worker_index < num_workers:
            raise ValueError(
                "index of worker should be in [0, number of workers)"
            )
        if batch_size <= 0:
            raise ValueError("batch_size should be positive")
        if table_size is None:
            with self._table.open_reader() as reader:
                table_size = reader.count
        if 0 < limit < table_size:
            table_size = limit
        window = max(self._window_size, batch_size)
        starts = list(range(0, table_size, window))
        if len(starts) < num_workers:
            # fall back to one window per worker (reference behavior for
            # tiny tables)
            window = max(1, table_size // num_workers)
            starts = list(range(0, table_size, window))
        my_starts = [
            s for i, s in enumerate(starts) if i % num_workers ==
            worker_index
        ]
        if shuffle:
            import random

            random.shuffle(my_starts)
        my_starts = my_starts * max(1, int(epochs))
        for s in my_starts:
            rows = list(self.read_range(s, min(s + window, table_size)))
            for i in range(0, len(rows), batch_size):
                yield rows[i:i + batch_size]

    def read_range(self, start, end):
        """Yield rows of [start, end) with windows fetched ahead on a
        thread pool."""
        windows = [
            (s, min(self._window_size, end - s))
            for s in range(start, end, self._window_size)
        ]
        results = queue.Queue(maxsize=self._num_prefetch)

        def producer():
            session = {}
            try:
                for w_start, w_count in windows:
                    try:
                        results.put(
                            ("ok",
                             self._read_window(session, w_start, w_count))
                        )
                    except Exception as e:
                        results.put(("error", e))
                        return
                results.put(("done", None))
            finally:
                self._close_session(session)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        while True:
            kind, payload = results.get()
            if kind == "done":
                return
            if kind == "error":
                raise payload
            for row in payload:
                yield row


class ODPSDataReader(AbstractDataReader):
    """The AbstractDataReader over an ODPS table (reference
    ODPSDataReader + ParallelODPSDataReader)."""

    def __init__(
        self,
        table=None,
        records_per_task=256,
        parse_fn=None,
        columns=None,
        project=None,
        access_id=None,
        access_key=None,
        endpoint=None,
        num_prefetch=2,
        window_size=_DEFAULT_WINDOW,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if table is None or isinstance(table, str):
            table = _open_odps_table(
                project, access_id, access_key, endpoint, table
            )
        self._table = table
        self._records_per_task = records_per_task
        self._parse_fn = parse_fn
        self._columns = columns
        self._kwargs = kwargs
        self._reader = ODPSReader(
            table, num_prefetch=num_prefetch, window_size=window_size
        )

    def _table_size(self):
        with self._table.open_reader() as reader:
            return reader.count

    def _table_name(self):
        return getattr(self._table, "name", "odps_table")

    def create_shards(self):
        size = self._table_size()
        shards = {}
        start = 0
        while start < size:
            count = min(self._records_per_task, size - start)
            shards["%s:%d" % (self._table_name(), start)] = (start, count)
            start += count
        return shards

    def read_records(self, task):
        for row in self._reader.read_range(task.start, task.end):
            if self._parse_fn is not None:
                yield self._parse_fn(row)
            else:
                yield row

    @property
    def metadata(self):
        schema = getattr(self._table, "schema", None)
        if schema is None:
            return Metadata(self._columns or [])
        names = [c.name for c in schema.columns]
        dtypes = {
            c.name: str(getattr(c, "type", "")) for c in schema.columns
        }
        return Metadata(names, dtypes)

    def default_dataset_fn(self):
        """Schema-driven dataset_fn for specs that declare none
        (reference odps_reader.py:140-192 `default_dataset_fn`): every
        column parses to float32, the `label_col` named in the reader
        params becomes the label, and the remaining columns concatenate
        into the feature vector. Prediction mode drops the label (or
        passes all columns through when the table has none); training
        shuffles with the reference's buffer of 200."""
        from elasticdl_tpu.common.constants import Mode
        from elasticdl_tpu.data.reader.data_reader import (
            check_required_kwargs,
        )

        check_required_kwargs(["label_col"], self._kwargs)
        label_col = self._kwargs["label_col"]

        def dataset_fn(dataset, mode, metadata):
            names = list(metadata.column_names or [])
            label_idx = names.index(label_col) if label_col in names \
                else None
            if mode != Mode.PREDICTION and label_idx is None:
                raise ValueError(
                    "Missing the label column '%s' in the retrieved "
                    "ODPS table during %s mode." % (label_col, mode)
                )

            def parse(record):
                row = np.asarray(
                    [float(v) for v in record], np.float32
                )
                if mode == Mode.PREDICTION:
                    if label_idx is None:
                        return {"feature": row}
                    feats = np.delete(row, label_idx)
                    return {"feature": feats}
                feats = np.delete(row, label_idx)
                return (
                    {"feature": feats},
                    np.float32(row[label_idx]),
                )

            dataset = dataset.map(parse)
            if mode == Mode.TRAINING:
                dataset = dataset.shuffle(buffer_size=200)
            return dataset

        return dataset_fn
