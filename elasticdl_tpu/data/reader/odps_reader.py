"""MaxCompute/ODPS table reader (reference data/reader/odps_reader.py
251 LoC + data/odps_io.py 515 LoC).

Behavior parity:
* shards are row ranges over the table: {"<table>:<start>": (start, n)}
  (reference ODPSDataReader.create_shards via table size);
* `read_records(task)` streams rows for [task.start, task.end), fetched
  in parallel windows ahead of consumption (reference
  ODPSReader._worker_loop prefetch machinery) with per-window retry;
* a `parse_fn` turns raw column tuples into records
  (ParallelODPSDataReader);
* `metadata` carries column names/dtypes so a default dataset_fn can be
  derived from the table schema.

The `odps` package import is gated exactly like kubernetes: pass a
`table` object implementing `open_reader`/`schema` (what the tests fake)
or install pyodps and pass access keys."""

import queue
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.reader.data_reader import (
    AbstractDataReader,
    Metadata,
)

_DEFAULT_WINDOW = 1000
_MAX_RETRIES = 3


def _open_odps_table(project, access_id, access_key, endpoint, table):
    try:
        from odps import ODPS
    except ImportError as e:
        raise RuntimeError(
            "The odps package is not installed; pass a `table` object or "
            "install pyodps"
        ) from e
    odps = ODPS(access_id, access_key, project, endpoint)
    return odps.get_table(table)


class ODPSReader(object):
    """Windowed parallel prefetcher over one table (reference
    data/odps_io.py ODPSReader: N window-fetch threads stay ahead of the
    consumer; failed windows retry)."""

    def __init__(self, table, num_prefetch=2, window_size=_DEFAULT_WINDOW):
        self._table = table
        self._num_prefetch = max(1, num_prefetch)
        self._window_size = window_size

    @staticmethod
    def _close_session(session):
        cm = session.pop("cm", None)
        session["reader"] = None
        if cm is not None:
            try:
                cm.__exit__(None, None, None)
            except Exception:
                pass

    def _read_window(self, session, start, count):
        """Read one window from the open `session` holder, reopening the
        reader session only after a failure (one session per range, not
        per window — session creation is a service round trip)."""
        last_error = None
        for _ in range(_MAX_RETRIES):
            try:
                if session.get("reader") is None:
                    cm = self._table.open_reader()
                    session["cm"] = cm
                    session["reader"] = cm.__enter__()
                return list(session["reader"].read(start, count))
            except Exception as e:  # retry transient fetch failures
                last_error = e
                self._close_session(session)
                logger.warning(
                    "ODPS window read (%d, %d) failed: %s; retrying",
                    start, count, e,
                )
        raise last_error

    def read_range(self, start, end):
        """Yield rows of [start, end) with windows fetched ahead on a
        thread pool."""
        windows = [
            (s, min(self._window_size, end - s))
            for s in range(start, end, self._window_size)
        ]
        results = queue.Queue(maxsize=self._num_prefetch)

        def producer():
            session = {}
            try:
                for w_start, w_count in windows:
                    try:
                        results.put(
                            ("ok",
                             self._read_window(session, w_start, w_count))
                        )
                    except Exception as e:
                        results.put(("error", e))
                        return
                results.put(("done", None))
            finally:
                self._close_session(session)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        while True:
            kind, payload = results.get()
            if kind == "done":
                return
            if kind == "error":
                raise payload
            for row in payload:
                yield row


class ODPSDataReader(AbstractDataReader):
    """The AbstractDataReader over an ODPS table (reference
    ODPSDataReader + ParallelODPSDataReader)."""

    def __init__(
        self,
        table=None,
        records_per_task=256,
        parse_fn=None,
        columns=None,
        project=None,
        access_id=None,
        access_key=None,
        endpoint=None,
        num_prefetch=2,
        window_size=_DEFAULT_WINDOW,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if table is None or isinstance(table, str):
            table = _open_odps_table(
                project, access_id, access_key, endpoint, table
            )
        self._table = table
        self._records_per_task = records_per_task
        self._parse_fn = parse_fn
        self._columns = columns
        self._reader = ODPSReader(
            table, num_prefetch=num_prefetch, window_size=window_size
        )

    def _table_size(self):
        with self._table.open_reader() as reader:
            return reader.count

    def _table_name(self):
        return getattr(self._table, "name", "odps_table")

    def create_shards(self):
        size = self._table_size()
        shards = {}
        start = 0
        while start < size:
            count = min(self._records_per_task, size - start)
            shards["%s:%d" % (self._table_name(), start)] = (start, count)
            start += count
        return shards

    def read_records(self, task):
        for row in self._reader.read_range(task.start, task.end):
            if self._parse_fn is not None:
                yield self._parse_fn(row)
            else:
                yield row

    @property
    def metadata(self):
        schema = getattr(self._table, "schema", None)
        if schema is None:
            return Metadata(self._columns or [])
        names = [c.name for c in schema.columns]
        dtypes = {
            c.name: str(getattr(c, "type", "")) for c in schema.columns
        }
        return Metadata(names, dtypes)
