"""AbstractDataReader: the pluggable data-source interface.

Parity with the reference's elasticdl/python/data/reader/data_reader.py:65-106:
a reader exposes ``read_records(task)`` (a generator over the task's record
range) and ``create_shards()`` (the {shard_name: (start, num_records)} map the
master shards into tasks). Tasks — not ranks — are the unit of work, which is
what makes the worker count elastic.
"""

from abc import ABC, abstractmethod


class Metadata(object):
    """Dataset metadata: column names/dtypes for table-like sources
    (reference data_reader.py `Metadata`)."""

    def __init__(self, column_names, column_dtypes=None):
        self.column_names = column_names
        self.column_dtypes = column_dtypes


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        pass

    @abstractmethod
    def read_records(self, task):
        """Yield raw records (bytes or parsed rows) for `task`'s
        [start, end) range of its shard."""

    @abstractmethod
    def create_shards(self):
        """Return {shard_name: (start_index, num_records)}."""

    @property
    def records_output_types(self):
        """Kept for API parity; TPU pipeline is dtype-agnostic until
        dataset_fn parses records."""
        return None

    @property
    def metadata(self):
        return Metadata(column_names=None)


def check_required_kwargs(required_args, kwargs):
    missing = [k for k in required_args if k not in kwargs]
    if missing:
        raise ValueError(
            "The following required arguments are missing: %s"
            % ", ".join(missing)
        )
