"""TRec (RecordIO-equivalent) reader.

Parity with reference data/reader/recordio_reader.py:27-62: shards are one
file each, named by path, with (0, record_count); read_records scans
[task.start, task.end) of task.shard_name. Uses the native C++ scanner when
built, else the pure-Python codec.
"""

import os

from elasticdl_tpu.data.reader.data_reader import (
    AbstractDataReader,
    check_required_kwargs,
)


def _scan(path, start, count):
    try:
        from elasticdl_tpu.native import recordio_native

        if recordio_native.available():
            return recordio_native.scan(path, start, count)
    except Exception:
        pass
    from elasticdl_tpu.data import record_format

    return iter(record_format.Scanner(path, start, count))


class RecordIODataReader(AbstractDataReader):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        check_required_kwargs(["data_dir"], kwargs)
        self._kwargs = kwargs

    def read_records(self, task):
        for record in _scan(
            task.shard_name, task.start, task.end - task.start
        ):
            yield record

    def create_shards(self):
        from elasticdl_tpu.data.record_format import get_record_count

        data_dir = self._kwargs["data_dir"]
        if not data_dir:
            return {}
        shards = {}
        for fname in sorted(os.listdir(data_dir)):
            path = os.path.join(data_dir, fname)
            if not os.path.isfile(path):
                continue
            shards[path] = (0, get_record_count(path))
        return shards
