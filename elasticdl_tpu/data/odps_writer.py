"""MaxCompute/ODPS table writer (reference data/odps_io.py:444-515
`ODPSWriter`), completing the read path in data/reader/odps_reader.py.

Behavior parity:
* lazy table initialization: an existing table is used as-is; a missing
  one is created from (columns, column_types) with a `worker` string
  partition column — reference `_initialize_table` (odps_io.py:490-506);
* `from_iterator(records_iter, worker_index)` writes each batch into the
  `worker=<index>` partition with create_partition=True (odps_io.py:508-515);
* `write_records` adds what the reference reader had but its writer
  lacked and VERDICT round-1 asked to mirror: WINDOWED PARALLEL writes
  with per-window retry (the write-side twin of ODPSReader's prefetch
  windows + record_generator_with_retry);
* `project.table` names split into (project, table) — odps_io.py:474-475.

Like the reader, the `odps` package is import-gated: tests (and any
caller that already holds a table handle) pass a `table` object
implementing `open_writer(partition=..., create_partition=True)`;
otherwise pyodps credentials are required.
"""

import threading

from elasticdl_tpu.common.log_utils import default_logger as logger

_DEFAULT_WINDOW = 1000
_MAX_RETRIES = 3


class ODPSWriter(object):
    def __init__(
        self,
        table=None,
        columns=None,
        column_types=None,
        project=None,
        access_id=None,
        access_key=None,
        endpoint=None,
        table_name=None,
        window_size=_DEFAULT_WINDOW,
        num_parallel=2,
        max_retries=_MAX_RETRIES,
    ):
        if table_name and table_name.find(".") > 0:
            project, table_name = table_name.split(".", 1)
        self._table = table
        self._columns = columns
        self._column_types = column_types
        self._project = project
        self._access_id = access_id
        self._access_key = access_key
        self._endpoint = endpoint
        self._table_name = table_name
        self._window_size = int(window_size)
        self._num_parallel = max(1, int(num_parallel))
        self._max_retries = max(1, int(max_retries))

    # ----------------------------------------------------- table creation

    def _ensure_table(self):
        if self._table is not None:
            return self._table
        try:
            from odps import ODPS
            from odps.models import Schema
        except ImportError as e:
            raise RuntimeError(
                "The odps package is not installed; pass a `table` object "
                "or install pyodps"
            ) from e
        client = ODPS(
            self._access_id, self._access_key, self._project, self._endpoint
        )
        if client.exist_table(self._table_name, self._project):
            self._table = client.get_table(self._table_name, self._project)
        else:
            if self._columns is None or self._column_types is None:
                raise ValueError(
                    "columns and column_types need to be specified for a "
                    "non-existing table."
                )
            schema = Schema.from_lists(
                self._columns, self._column_types, ["worker"], ["string"]
            )
            self._table = client.create_table(self._table_name, schema)
        return self._table

    # ------------------------------------------------------------ writing

    def from_iterator(self, records_iter, worker_index=0):
        """Stream pre-batched records into this worker's partition
        (reference from_iterator, odps_io.py:508-515: one writer session,
        sequential batch writes)."""
        table = self._ensure_table()
        with table.open_writer(
            partition="worker=%s" % worker_index, create_partition=True
        ) as writer:
            for records in records_iter:
                writer.write(records)

    def write_records(self, records, worker_index=0):
        """Write a record list as parallel windows with per-window retry.

        Windows are dealt round-robin to `num_parallel` writer threads,
        each with its own writer session; a window that raises is retried
        up to max_retries times (the write-side mirror of the reader's
        windowed prefetch + retry)."""
        records = list(records)
        if not records:
            return 0
        table = self._ensure_table()
        windows = [
            records[i:i + self._window_size]
            for i in range(0, len(records), self._window_size)
        ]
        errors = []
        lock = threading.Lock()

        def write_windows(thread_id):
            try:
                with table.open_writer(
                    partition="worker=%s" % worker_index,
                    create_partition=True,
                ) as writer:
                    for w in range(thread_id, len(windows),
                                   self._num_parallel):
                        self._write_window_with_retry(writer, windows[w], w)
            except Exception as e:  # noqa: BLE001 - collected and re-raised
                with lock:
                    errors.append(e)

        n_threads = min(self._num_parallel, len(windows))
        if n_threads == 1:
            write_windows(0)
        else:
            threads = [
                threading.Thread(
                    target=write_windows, args=(t,), daemon=True
                )
                for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        return len(records)

    def _write_window_with_retry(self, writer, window, window_idx):
        for attempt in range(self._max_retries):
            try:
                writer.write(window)
                return
            except Exception:  # noqa: BLE001 - retried, then re-raised
                if attempt == self._max_retries - 1:
                    raise
                logger.warning(
                    "ODPS write window %d failed (attempt %d/%d); retrying",
                    window_idx, attempt + 1, self._max_retries,
                )
